"""Runtime auto-upgrade controller: per-node cordon→drain→swap→validate→uncordon.

Reference analogue: controllers/upgrade_controller.go (:80-227) driving the
external k8s-operator-libs/pkg/upgrade state machine — reimplemented in-tree
(SURVEY §7 step 7).  Per-node state rides the
``tpu.google.com/tpu-runtime-upgrade-state`` label:

  upgrade-required → cordon-required → drain-required →
  pod-restart-required → validation-required → uncordon-required →
  upgrade-done | upgrade-failed

Bounded by ``libtpu.upgradePolicy.maxParallelUpgrades`` and ``maxUnavailable``
(:156-164), gated on validation before uncordon (:145 WithValidationEnabled),
metrics-fed (:177-184), labels cleaned when auto-upgrade is disabled
(:199-227), requeued every 2 minutes (:58,196).

"Needs upgrade" = the node's tpu.runtime.version feature label differs from
the policy's pinned libtpu version.  The swap itself is delegated to the
node: the controller stamps the upgrade-requested annotation and deletes the
OnDelete runtime DS pod; the replacement pod's runtime-manager init drains
locally and the installer writes the new version, which feature discovery
reflects back into the label the controller validates against.
"""

from __future__ import annotations

import logging
from typing import Optional

from tpu_operator import consts
from tpu_operator.api.types import CLUSTER_POLICY_KIND, GROUP, TPUClusterPolicy  # noqa: F401 (GROUP/KIND used in setup watches)
from tpu_operator.controllers import clusterinfo, migration as mig, nodestate
from tpu_operator.controllers.labels import node_advertises_tpu
from tpu_operator.controllers.runtime import Controller, Manager
from tpu_operator.k8s import nodeinfo
from tpu_operator.k8s.client import ApiClient, ApiError
from tpu_operator.metrics import OperatorMetrics
from tpu_operator.obs import events as obs_events
from tpu_operator.obs.events import EventRecorder
from tpu_operator.obs.trace import Tracer
from tpu_operator.utils import deep_get

log = logging.getLogger("tpu_operator.upgrade")

# state-label values (k8s-operator-libs upgrade states)
REQUIRED = "upgrade-required"
CORDON = "cordon-required"
DRAIN = "drain-required"
POD_RESTART = "pod-restart-required"
VALIDATION = "validation-required"
UNCORDON = "uncordon-required"
DONE = "upgrade-done"
FAILED = "upgrade-failed"

IN_PROGRESS_STATES = (CORDON, DRAIN, POD_RESTART, VALIDATION, UNCORDON)
# every state in which the machine still owns the node's cordon/pods
# (remediation defers to these; DONE/FAILED/absent are terminal)
NON_TERMINAL_STATES = (REQUIRED,) + IN_PROGRESS_STATES

RECONCILE_KEY = "upgrade"

VALIDATOR_POD_SELECTOR = "app=tpu-operator-validator"


# promoted to controllers/nodestate.py (shared with remediation + health);
# the alias keeps the historical private import path working
_parse_ts = nodestate.parse_ts


def parse_max_unavailable(value: Optional[str], total: int) -> int:
    """'25%' or '2' → absolute bound ≥1 (upgrade_controller.go:156-164)."""
    if not value:
        return max(1, total)
    value = str(value).strip()
    try:
        if value.endswith("%"):
            return max(1, int(total * int(value[:-1]) / 100))
        return max(1, int(value))
    except ValueError:
        return 1


class UpgradeReconciler:
    def __init__(
        self,
        client: ApiClient,
        namespace: str,
        metrics: Optional[OperatorMetrics] = None,
        tracer: Optional[Tracer] = None,
        recorder: Optional[EventRecorder] = None,
    ):
        self.client = client
        self.namespace = namespace
        self.metrics = metrics or OperatorMetrics()
        self.tracer = tracer or Tracer(self.metrics)
        self.recorder = recorder or EventRecorder(client, namespace)
        # the checkpoint→reschedule→restore drain phase shared with the
        # remediation and health machines (controllers/migration.py)
        self.migration = mig.MigrationCoordinator(
            client, namespace, metrics=self.metrics, recorder=self.recorder
        )

    # ------------------------------------------------------------------
    async def reconcile(self, key: str) -> Optional[float]:
        with self.tracer.reconcile("upgrade", key=key):
            return await self._reconcile(key)

    async def _reconcile(self, key: str) -> Optional[float]:
        policy = await self._cluster_policy()
        if policy is None:
            return None
        up = policy.spec.libtpu.upgrade_policy
        nodes = [
            n for n in await self.client.list_items("", "Node") if clusterinfo.is_tpu_node(n)
        ]
        self.metrics.auto_upgrade_enabled.set(1 if up.auto_upgrade else 0)
        if not up.auto_upgrade:
            await self._clear_labels(nodes)
            return consts.UPGRADE_REQUEUE_SECONDS

        desired = policy.spec.libtpu.libtpu_version
        states = {n["metadata"]["name"]: self._state_of(n) for n in nodes}

        # Mark out-of-date nodes (BuildState analogue).  DONE nodes become
        # eligible again when a NEW version is pinned (v2 done, v3 pinned →
        # re-required); FAILED stays sticky until operator intervention,
        # matching the reference machine's failed-state semantics.  Each
        # node's patch is isolated: one mid-loop ApiError must not abort the
        # whole pass for every node behind it.
        for node in nodes:
            name = node["metadata"]["name"]
            if states[name] and states[name] != DONE:
                continue
            current = nodeinfo.attributes(node).runtime_version
            if desired and current and current != desired:
                try:
                    await self._set_state(name, REQUIRED)
                except ApiError as e:
                    log.error("upgrade mark-required on %s failed: %s", name, e)
                    continue
                states[name] = REQUIRED

        in_progress = sum(1 for s in states.values() if s in IN_PROGRESS_STATES)
        unavailable = sum(
            1 for n in nodes
            if deep_get(n, "spec", "unschedulable") or not node_advertises_tpu(n)
        )
        # maxParallelUpgrades: 0 = unbounded (the reference
        # DriverUpgradePolicySpec semantics the schema's minimum:0 always
        # promised); maxUnavailable remains the availability backstop
        max_parallel = up.max_parallel_upgrades if up.max_parallel_upgrades > 0 else len(nodes)
        max_unavailable = parse_max_unavailable(up.max_unavailable, len(nodes))

        # Admit required nodes into the pipeline within bounds (ApplyState);
        # per-node failures skip the node, they do not starve the rest.
        for node in nodes:
            name = node["metadata"]["name"]
            if states[name] != REQUIRED:
                continue
            if in_progress >= max_parallel or unavailable >= max_unavailable:
                break
            try:
                await self._set_state(name, CORDON)
            except ApiError as e:
                log.error("upgrade admission on %s failed: %s", name, e)
                continue
            states[name] = CORDON
            in_progress += 1
            unavailable += 1

        # Advance each in-flight node one step.
        for node in nodes:
            name = node["metadata"]["name"]
            state = states[name]
            try:
                if state == CORDON:
                    await self._cordon(name, True)
                    await self._set_state(name, DRAIN)
                elif state == DRAIN:
                    drained = await self._drain_step(
                        node, up, policy.spec.migration, nodes
                    )
                    if drained:
                        await self._request_runtime_swap(node)
                        await self._set_state(name, POD_RESTART)
                    elif self._state_age(node) > float(up.drain.timeout_seconds):
                        if up.drain.force:
                            log.warning(
                                "drain timeout on %s; forcing swap per drain.force", name
                            )
                            await self._request_runtime_swap(node)
                            await self._set_state(name, POD_RESTART)
                        else:
                            log.error("drain timed out on %s; marking %s", name, FAILED)
                            await self._set_state(name, FAILED)
                elif state == POD_RESTART:
                    if await self._runtime_pod_running(name):
                        # the NEW runtime is live — only NOW delete the
                        # validator pod, so its replacement provably re-runs
                        # the init chain against the new libtpu (deleting it
                        # at swap time would let the DS recreate it while the
                        # OLD .so was still installed, producing stale
                        # Running evidence)
                        await self._delete_validator_pods(name)
                        await self._set_state(name, VALIDATION)
                elif state == VALIDATION:
                    live = await self.client.get("", "Node", name)
                    vpod = await self._validator_pod(name)
                    if self._validated(live, desired, policy, vpod):
                        await self._set_state(name, UNCORDON)
                    elif self._validation_failed(live, vpod, up):
                        log.error(
                            "post-swap validation failed on %s; marking %s", name, FAILED
                        )
                        await self._set_state(name, FAILED)
                elif state == UNCORDON:
                    await self._cordon(name, False)
                    await self._set_state(name, DONE)
            except ApiError as e:
                log.error("upgrade step %s on %s failed: %s", state, name, e)
                await self._set_state(name, FAILED)

        fresh = [
            n for n in await self.client.list_items("", "Node") if clusterinfo.is_tpu_node(n)
        ]
        await self._report(fresh)
        return consts.UPGRADE_REQUEUE_SECONDS

    # ------------------------------------------------------------------
    def _state_of(self, node: dict) -> str:
        return nodeinfo.attributes(node).upgrade_state

    async def _set_state(self, node_name: str, state: Optional[str]) -> None:
        await nodestate.patch_state(
            self.client, node_name,
            consts.UPGRADE_STATE_LABEL, state, consts.UPGRADE_STATE_TS_ANNOTATION,
        )
        # milestone Events on the Node — every path into CORDON/DONE/FAILED
        # funnels through here, so this is the single emission point
        ref = obs_events.node_ref(node_name)
        if state == CORDON:
            await self.recorder.normal(
                ref, obs_events.REASON_UPGRADE_STARTED,
                f"runtime upgrade started on {node_name} (cordon -> drain -> swap -> validate)",
            )
        elif state == DONE:
            await self.recorder.normal(
                ref, obs_events.REASON_UPGRADE_DONE,
                f"runtime upgrade completed and validated on {node_name}",
            )
        elif state == FAILED:
            await self.recorder.warning(
                ref, obs_events.REASON_UPGRADE_FAILED,
                f"runtime upgrade failed on {node_name}; node left cordoned for intervention",
            )

    async def _cordon(self, node_name: str, value: bool) -> None:
        await self.client.patch("", "Node", node_name, {"spec": {"unschedulable": value or None}})

    def _state_age(self, node: dict) -> float:
        """Seconds since the node entered its current upgrade state."""
        return nodestate.state_age(node, consts.UPGRADE_STATE_TS_ANNOTATION)

    async def _drain_step(
        self, node: dict, up, migration_spec=None,
        nodes: Optional[list[dict]] = None,
    ) -> bool:
        """One non-blocking drain pass: settle every TPU workload pod on
        the node, report whether it is drained.  Pods carrying the
        checkpoint migration handler ride the migrate-instead-of-evict
        phase (controllers/migration.py): annotate → await the checkpoint →
        reschedule onto a healthy slice — the drain waits on them exactly
        like the historical delete waited on termination.  Everything else
        keeps the historical evict, now counted per pod in
        ``drain_evictions_total{controller=upgrade}``.  The node stays in
        DRAIN across requeues until empty — drain.timeoutSeconds is
        enforced against the state-entry timestamp, never by sleeping
        inside the reconcile worker (a stuck finalizer must not stall every
        other node's upgrade)."""
        if not up.drain.enable:
            return True
        from tpu_operator.api.types import MigrationSpec

        if migration_spec is None:
            migration_spec = MigrationSpec()
        name = node["metadata"]["name"]
        pods = await self.client.list_items(
            "", "Pod", field_selector=f"spec.nodeName={name}"
        )
        remaining = False
        # shared eligibility filter (TPU request, skip-drain opt-out,
        # DaemonSet exclusion): one implementation with the remediation
        # and health drains so the three paths can never select different
        # pod sets (controllers/migration.py workload_pods)
        for pod in mig.workload_pods(pods, name):
            meta = pod["metadata"]
            refs = meta.get("ownerReferences") or []
            if migration_spec.enabled and mig.is_migratable(pod):
                await self.migration.drain_pod(
                    pod, migration_spec, "upgrade", nodes=nodes,
                    force=up.drain.force,
                    grace_period_seconds=up.drain.grace_period_seconds,
                )
                # ANY outcome this pass still counts the node as draining:
                # even a completed/evicted pod runs out its termination
                # grace holding the chips — only a later pass that no
                # longer lists the pod concludes drained (the historical
                # delete path's semantics, kept for migrations)
                remaining = True
                continue
            if not refs and not up.drain.force:
                # bare pod: blocks the drain until timeout unless force
                remaining = True
                continue
            remaining = True
            if not meta.get("deletionTimestamp"):
                # the workload gets the spec'd termination grace (None
                # preserves the pod's own terminationGracePeriodSeconds);
                # the coordinator's evict path keeps those semantics and
                # adds the per-pod eviction accounting
                await self.migration.evict(
                    pod, "upgrade",
                    mig.FORCED if up.drain.force else mig.NO_HANDLER,
                    up.drain.grace_period_seconds,
                )
        return not remaining

    def _node_pods(self, node_name: str, label_selector: str):
        """Namespace pods on one node, filtered server-side."""
        return self.client.list_items(
            "", "Pod", self.namespace,
            label_selector=label_selector,
            field_selector=f"spec.nodeName={node_name}",
        )

    async def _request_runtime_swap(self, node: dict) -> None:
        """Annotate + delete the OnDelete runtime DS pod on this node.  The
        validator pod is NOT touched here — it is deleted later, once the new
        runtime pod is Running (see the POD_RESTART step), so that its
        replacement's init chain re-proves pjrt→plugin→jax against the new
        libtpu (cmd/gpu-operator/main.go:145 WithValidationEnabled analogue;
        stale pre-swap validations must never pass a node)."""
        name = node["metadata"]["name"]
        await self.client.patch(
            "", "Node", name,
            {"metadata": {"annotations": {consts.UPGRADE_REQUESTED_ANNOTATION: "true"}}},
        )
        for pod in await self._node_pods(name, "app=tpu-runtime"):
            await self.client.delete("", "Pod", pod["metadata"]["name"], self.namespace)
            log.info("deleted %s for swap on %s", pod["metadata"]["name"], name)

    async def _delete_validator_pods(self, node_name: str) -> None:
        """Clear every validator pod on the node (including lingering Failed
        ones) so the DS-recreated pod is the only source of evidence."""
        for pod in await self._node_pods(node_name, VALIDATOR_POD_SELECTOR):
            await self.client.delete("", "Pod", pod["metadata"]["name"], self.namespace)
            log.info("deleted %s for re-validation on %s", pod["metadata"]["name"], node_name)

    async def _validator_pod(self, node_name: str) -> Optional[dict]:
        """The validator pod whose state should gate this node: a Running
        non-terminating pod wins over a lingering Failed sibling (an evicted
        pod object persists until GC even after the DS recreated a healthy
        replacement — it must not fail the upgrade)."""
        best: Optional[dict] = None
        for pod in await self._node_pods(node_name, VALIDATOR_POD_SELECTOR):
            if deep_get(pod, "metadata", "deletionTimestamp"):
                continue
            if deep_get(pod, "status", "phase") == "Running":
                return pod
            best = best or pod
        return best

    async def _runtime_pod_running(self, node_name: str) -> bool:
        for pod in await self._node_pods(node_name, "app=tpu-runtime"):
            # the old pod lingers Running with a deletionTimestamp during
            # graceful termination — only a non-terminating pod counts
            if deep_get(pod, "metadata", "deletionTimestamp"):
                continue
            return deep_get(pod, "status", "phase") == "Running"
        return False

    def _validated(
        self,
        node: dict,
        desired: Optional[str],
        policy: TPUClusterPolicy,
        vpod: Optional[dict],
    ) -> bool:
        """Post-swap gate before uncordon (validator-app gate analogue,
        upgrade_controller.go:145): capacity advertised, version caught up,
        and — when the validator operand is enabled — a FRESH validator pod
        Running on the node.  The swap deleted the old validator pod, so any
        Running one proves the full init chain re-ran against the new
        runtime (phase only reaches Running after initContainers pass)."""
        if not node_advertises_tpu(node):
            return False
        if desired and nodeinfo.attributes(node).runtime_version != desired:
            return False
        if policy.spec.validator.is_enabled():
            return vpod is not None and deep_get(vpod, "status", "phase") == "Running"
        return True

    def _validation_failed(self, node: dict, vpod: Optional[dict], up) -> bool:
        """FAILED when the validator pod crashed outright, or the node sat in
        validation-required past upgradePolicy.validationTimeoutSeconds
        (0 = wait forever).  A failed node stays cordoned for operator
        intervention instead of silently uncordoning unproven."""
        if vpod is not None and deep_get(vpod, "status", "phase") == "Failed":
            return True
        timeout = float(getattr(up, "validation_timeout_seconds", 0) or 0)
        return bool(timeout) and self._state_age(node) > timeout

    async def _clear_labels(self, nodes: list[dict]) -> None:
        """Auto-upgrade disabled → remove state labels (:199-227)."""
        for node in nodes:
            if self._state_of(node):
                await self._set_state(node["metadata"]["name"], None)

    async def _report(self, nodes: list[dict]) -> None:
        states = [self._state_of(n) for n in nodes]
        self.metrics.upgrades_in_progress.set(sum(1 for s in states if s in IN_PROGRESS_STATES))
        self.metrics.upgrades_done.set(sum(1 for s in states if s == DONE))
        self.metrics.upgrades_failed.set(sum(1 for s in states if s == FAILED))
        self.metrics.upgrades_pending.set(sum(1 for s in states if s == REQUIRED))
        self.metrics.upgrades_available.set(sum(1 for s in states if not s))

    async def _cluster_policy(self) -> Optional[TPUClusterPolicy]:
        obj = await clusterinfo.active_cluster_policy(self.client)
        return TPUClusterPolicy(obj) if obj else None

    # ------------------------------------------------------------------
    def setup(self, mgr: Manager) -> Controller:
        controller = mgr.add_controller(Controller("upgrade", self.reconcile))
        policies = mgr.informer(GROUP, CLUSTER_POLICY_KIND)
        nodes = mgr.informer("", "Node")

        async def kick(event_type: str, obj: dict) -> None:
            controller.enqueue(RECONCILE_KEY)

        policies.add_handler(kick)
        nodes.add_handler(kick)
        return controller
