"""TPU kubelet device plugin (google.com/tpu)."""

from tpu_operator.deviceplugin.plugin import PluginConfig, TPUDevicePlugin  # noqa: F401
