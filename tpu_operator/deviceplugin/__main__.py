"""python -m tpu_operator.deviceplugin [--mode accel|vfio]"""

from __future__ import annotations

import argparse
import asyncio
import logging

from tpu_operator import consts
from tpu_operator.deviceplugin.plugin import PluginConfig, TPUDevicePlugin


def main() -> None:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(levelname)s %(message)s")
    p = argparse.ArgumentParser("tpu-device-plugin")
    p.add_argument("--mode", choices=["accel", "vfio"], default="accel")
    p.add_argument("--resource-name", default=consts.TPU_RESOURCE)
    p.add_argument("--socket-name", default=None)
    args = p.parse_args()
    config = PluginConfig(
        resource_name=args.resource_name,
        mode=args.mode,
        socket_name=args.socket_name or ("tpu-vfio.sock" if args.mode == "vfio" else "tpu.sock"),
    )
    plugin = TPUDevicePlugin(config)

    async def run() -> None:
        try:
            await plugin.run_forever()
        finally:
            await plugin.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
