"""python -m tpu_operator.deviceplugin [--mode accel|vfio]

SLICE_STRATEGY env (none|single|mixed, DS-injected from
sliceManager.strategy) selects the plugin set: mixed serves one
google.com/tpu-<shape> resource per applied partition shape.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os

from tpu_operator import consts
from tpu_operator.deviceplugin import sliceconfig
from tpu_operator.deviceplugin.plugin import PluginConfig


def main() -> None:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(levelname)s %(message)s")
    p = argparse.ArgumentParser("tpu-device-plugin")
    p.add_argument("--mode", choices=["accel", "vfio"], default="accel")
    p.add_argument("--resource-name", default=consts.TPU_RESOURCE)
    p.add_argument("--socket-name", default=None)
    p.add_argument(
        "--slice-strategy",
        choices=["none", "single", "mixed"],
        default=os.environ.get("SLICE_STRATEGY", "none") or "none",
    )
    args = p.parse_args()
    base = PluginConfig(
        resource_name=args.resource_name,
        mode=args.mode,
        socket_name=args.socket_name or ("tpu-vfio.sock" if args.mode == "vfio" else "tpu.sock"),
    )
    # vfio partitions too: under `mixed`, VM-passthrough nodes advertise
    # the same per-shape google.com/tpu-<shape> resources as container
    # nodes, each unit backed by the partition's vfio groups — the
    # vgpu-device-manager (mdev-type partitioning) analogue.  Workloads
    # request identical resource names either way; the workload-config
    # node routing decides which plugin serves them.
    asyncio.run(sliceconfig.run_plugins(args.slice_strategy, base))


if __name__ == "__main__":
    main()
