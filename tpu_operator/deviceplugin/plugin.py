"""TPU device plugin: advertises google.com/tpu to the kubelet.

Reference analogue: the k8s-device-plugin image the operator deploys
(assets/state-device-plugin/0500_daemonset.yaml) — the plugin itself lives
out-of-tree for the reference; here it is part of the framework.

Protocol (kubelet device-plugin v1beta1):
1. serve DevicePlugin on /var/lib/kubelet/device-plugins/tpu.sock
2. Register with the kubelet's Registration service on kubelet.sock
3. stream device health via ListAndWatch; answer Allocate with /dev/accel*
   DeviceSpecs + TPU runtime env; GetPreferredAllocation returns
   ICI-contiguous chip sets

TPU specifics vs the NVIDIA plugin:
- chips are topology-constrained: preferred allocations are contiguous chip
  index ranges (neighbours on the ICI ring), and sub-host requests that
  cannot form a contiguous block are still honoured but deprioritised
- allocation env: TPU_CHIPS_PER_HOST_BOUNDS / TPU_VISIBLE_CHIPS /
  TPU_WORKER_ID + the libtpu install dir mount, which is how jax/PJRT in the
  workload container finds its runtime
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import math
import os
from dataclasses import dataclass, field
from typing import Optional

import grpc.aio

from tpu_operator import consts, hw
from tpu_operator.deviceplugin import api_pb2, rpc

log = logging.getLogger("tpu_operator.deviceplugin")

KUBELET_DIR = "/var/lib/kubelet/device-plugins"
KUBELET_SOCKET = "kubelet.sock"
HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"


@dataclass
class PluginConfig:
    resource_name: str = consts.TPU_RESOURCE
    socket_name: str = "tpu.sock"
    kubelet_dir: str = field(default_factory=lambda: os.environ.get("KUBELET_PLUGIN_DIR", KUBELET_DIR))
    mode: str = "accel"  # accel | vfio (sandbox-device-plugin)
    health_interval: float = field(
        default_factory=lambda: float(os.environ.get("HEALTH_INTERVAL_SECONDS", "5"))
    )
    libtpu_dir: str = "/home/kubernetes/tpu"
    # CDI (container-device-interface) support, mirroring the reference's
    # cdi sub-spec (clusterpolicy_types.go CDIConfig): ``cdi_enabled``
    # maintains a CDI spec file under ``cdi_dir`` describing every chip;
    # ``cdi_default`` switches Allocate to answer with CDI device names
    # (the runtime injects nodes/mounts from the spec) instead of raw
    # DeviceSpecs.  Annotation-based requests always work once the spec
    # file exists.
    cdi_enabled: bool = field(
        default_factory=lambda: os.environ.get("CDI_ENABLED", "").lower() in ("1", "true")
    )
    cdi_default: bool = field(
        default_factory=lambda: os.environ.get("CDI_DEFAULT", "").lower() in ("1", "true")
    )
    cdi_dir: str = field(
        default_factory=lambda: os.environ.get("CDI_DIR", "/var/run/cdi")
    )
    # Static device sets (mixed slice strategy): device id → list of host
    # chip paths forming one partition unit, plus the unit's ICI shape.
    # None ⇒ dynamic per-chip discovery (one device per /dev/accel*).
    device_sets: Optional[dict[str, list[str]]] = None
    device_shape: str = ""  # partition shape these sets share, e.g. "2x2"

    @property
    def socket_path(self) -> str:
        return os.path.join(self.kubelet_dir, self.socket_name)

    @property
    def kubelet_socket_path(self) -> str:
        return os.path.join(self.kubelet_dir, KUBELET_SOCKET)


def discover_devices(mode: str = "accel") -> list[str]:
    """Host chip device paths for this mode."""
    if mode == "vfio":
        return hw.vfio_device_paths()
    paths = hw.accel_device_paths()
    if not paths:
        # env-declared count without device nodes (tests, some VM images)
        return [f"/dev/accel{i}" for i in range(hw.chip_count())]
    return paths


def device_id(path: str) -> str:
    return "tpu-" + os.path.basename(path)


def read_worker_id() -> Optional[int]:
    """This host's worker index within its multi-host slice: the
    TPU_WORKER_ID env (DS-injected) wins, else the ``worker_id`` file
    tpu-feature-discovery drops beside the validations dir.  None on
    single-host nodes with neither source — the env is then omitted and
    jax.distributed derives the id from its coordinator instead."""
    env = os.environ.get("TPU_WORKER_ID")
    if env is not None and env != "":
        try:
            return int(env)
        except ValueError:
            pass
    from tpu_operator.validator import status as vstatus

    try:
        with open(vstatus.worker_id_path()) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def shape_bounds(shape: str) -> str:
    """ICI shape string → x,y,z bounds env value ("2x2" → "2,2,1")."""
    dims = [d for d in shape.lower().split("x") if d]
    dims += ["1"] * (3 - len(dims))
    return ",".join(dims[:3])


def host_grid_coords(total: int) -> dict[int, tuple[int, int]]:
    """chip index → (x, y) position on the host's canonical chip grid
    (hw.chip_bounds row-major: a 4-chip v5e host is a 2x2 mesh with chip 1
    beside chip 0 and chip 2 above it).  The geometry the kubelet's flat
    device ids erase — and the reason index-span picks are wrong: on 2x2,
    indices {0,3} span 3 but are DIAGONAL (two hops), {0,2} span 2 and
    share a link."""
    x, y, _ = (int(v) for v in hw.chip_bounds(total).split(","))
    return {i: (i % x, (i // x) % max(1, y)) for i in range(total)}


# combinations cap for the exhaustive adjacency search: C(16,8)=12870 sets
# on the largest (16-chip) host, each scoring up to C(16,2) pairwise
# distances in pure Python — ~100 ms worst case, which is why the gRPC
# handler runs the pick in an executor instead of on the event loop that
# also serves ListAndWatch
_MAX_ADJACENCY_SEARCH = 20_000


def cdi_device_name(did: str) -> str:
    """Device id → CDI device name ('tpu-accel3' → 'accel3'); the CDI name
    is qualified by the spec's kind, so the 'tpu-' disambiguator the plugin
    uses for kubelet ids would be redundant."""
    return did[4:] if did.startswith("tpu-") else did


def chip_index(name: str) -> int:
    """Trailing chip number of a device id/path basename ('tpu-accel3' → 3)."""
    digits = ""
    for c in reversed(name):
        if c.isdigit():
            digits = c + digits
        elif digits:
            break
    return int(digits) if digits else 0


class TPUDevicePlugin:
    """The DevicePlugin service implementation + kubelet registration."""

    def __init__(self, config: Optional[PluginConfig] = None):
        self.config = config or PluginConfig()
        self.devices: dict[str, list[str]] = {}  # id -> host path(s)
        self.health: dict[str, str] = {}
        # one queue per live ListAndWatch stream (broadcast, not steal)
        self._watchers: set[asyncio.Queue] = set()
        self._server: Optional[grpc.aio.Server] = None
        self._health_task: Optional[asyncio.Task] = None

    # -- discovery / health -------------------------------------------
    def refresh_devices(self) -> bool:
        """Re-discover chips.  A previously-seen chip whose device node
        vanished stays advertised as Unhealthy (the kubelet's signal to fail
        pods bound to it) rather than silently dropping capacity."""
        if self.config.device_sets is not None:
            return self._refresh_static()
        found = {device_id(p): [p] for p in discover_devices(self.config.mode)}
        devices = dict(found)
        health = {did: HEALTHY for did in found}
        for did, paths in self.devices.items():
            if did not in devices:
                devices[did] = paths
                health[did] = UNHEALTHY
        changed = devices != self.devices or health != self.health
        self.devices, self.health = devices, health
        return changed

    def _refresh_static(self) -> bool:
        """Mixed-strategy partition units: membership is fixed by the slice
        layout; only health moves.  A unit is Healthy when every chip node
        exists — or when the host has no device nodes at all (env-declared
        virtual chips, same rule the dynamic path applies)."""
        devices = {did: list(paths) for did, paths in self.config.device_sets.items()}
        virtual = not hw.accel_device_paths()
        health = {
            did: HEALTHY if virtual or all(os.path.exists(p) for p in paths) else UNHEALTHY
            for did, paths in devices.items()
        }
        changed = devices != self.devices or health != self.health
        self.devices, self.health = devices, health
        return changed

    def _cdi_spec_path(self) -> str:
        return os.path.join(
            self.config.cdi_dir, self.config.resource_name.replace("/", "-") + ".json"
        )

    def write_cdi_spec(self) -> Optional[str]:
        """Converge the host CDI spec file describing every advertised
        device (reference cdi sub-spec analogue: the toolkit generates
        nvidia.com/gpu CDI specs; here the plugin owns the device
        inventory, so it owns the spec).  Returns the path, or None when
        CDI is disabled (a leftover spec from a previous enablement is
        removed — an orphaned file would keep resolving annotation-based
        requests against stale state).

        Called every health tick, NOT only on inventory changes: the spec
        captures filesystem truths that move independently of the device
        dict — libtpu lands asynchronously via the state-libtpu DS, and
        env-declared chips can grow device nodes after startup — the same
        truths the raw path re-checks per Allocate.  Unchanged content is
        not rewritten."""
        path = self._cdi_spec_path()
        if not self.config.cdi_enabled:
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        devices = []
        for did in sorted(self.devices):
            nodes = [
                {
                    "path": f"/dev/{os.path.basename(p)}",
                    "hostPath": p,
                    "permissions": "rw",
                }
                for p in self.devices[did]
                if os.path.exists(p)
            ]
            devices.append(
                {"name": cdi_device_name(did), "containerEdits": {"deviceNodes": nodes}}
            )
        spec: dict = {
            "cdiVersion": "0.6.0",
            "kind": self.config.resource_name,
            "devices": devices,
        }
        if os.path.isdir(self.config.libtpu_dir):
            # the libtpu install rides every CDI injection, replacing the
            # per-allocation Mount of the raw path
            spec["containerEdits"] = {
                "mounts": [
                    {
                        "hostPath": self.config.libtpu_dir,
                        "containerPath": self.config.libtpu_dir,
                        "options": ["ro", "rbind"],
                    }
                ]
            }
        import json

        os.makedirs(self.config.cdi_dir, exist_ok=True)
        content = json.dumps(spec, indent=2)
        try:
            with open(path) as f:
                if f.read() == content:
                    return path
        except OSError:
            pass
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(content)
        os.replace(tmp, path)
        return path

    def _snapshot(self) -> api_pb2.ListAndWatchResponse:
        resp = api_pb2.ListAndWatchResponse()
        for did in sorted(self.devices):
            resp.devices.append(api_pb2.Device(ID=did, health=self.health.get(did, UNHEALTHY)))
        return resp

    async def _health_loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.health_interval)
            changed = self.refresh_devices()
            # every tick, not only on inventory changes: the spec also
            # tracks libtpu/device-node filesystem state (see docstring).
            # A transient host-fs error (ro cdi_dir, ENOSPC) must not kill
            # the loop — health refresh is what keeps kubelet truthful.
            try:
                self.write_cdi_spec()
            except OSError as e:
                log.warning("CDI spec write failed (will retry): %s", e)
            if changed:
                for queue in list(self._watchers):
                    queue.put_nowait(None)

    # -- DevicePlugin service (async handlers wired by rpc.py) ---------
    async def GetDevicePluginOptions(self, request, context) -> api_pb2.DevicePluginOptions:
        return api_pb2.DevicePluginOptions(
            pre_start_required=False, get_preferred_allocation_available=True
        )

    async def ListAndWatch(self, request, context):
        queue: asyncio.Queue = asyncio.Queue()
        self._watchers.add(queue)
        try:
            yield self._snapshot()
            while True:
                await queue.get()
                yield self._snapshot()
        finally:
            self._watchers.discard(queue)

    async def GetPreferredAllocation(self, request, context) -> api_pb2.PreferredAllocationResponse:
        resp = api_pb2.PreferredAllocationResponse()
        for creq in request.container_requests:
            # executor: the exhaustive pick is ~100 ms worst case (16-chip
            # host) — the event loop must keep serving ListAndWatch
            picked = await asyncio.get_event_loop().run_in_executor(
                None,
                self.preferred_allocation,
                list(creq.available_deviceIDs),
                list(creq.must_include_deviceIDs),
                creq.allocation_size,
            )
            resp.container_responses.append(
                api_pb2.ContainerPreferredAllocationResponse(deviceIDs=picked)
            )
        return resp

    def preferred_allocation(
        self, available: list[str], must_include: list[str], size: int
    ) -> list[str]:
        """Prefer ICI-adjacent chip sets under the host's 2-D mesh metric
        (the TPU analogue of NUMA-aware GPU picks).

        Chips live on a physical grid (hw.chip_bounds): the pick maximizes
        shared-link pairs, then minimizes total pairwise mesh distance — a
        2-chip request on a 2x2 host gets a linked pair (never the
        diagonal), a 4-chip request on a 2x4 host gets a 2x2 block (4
        links) over an index-contiguous row (3).  Flat index spans — the
        r03 approach — measure neither.  Falls back to index-contiguous
        windows for static partition units (their adjacency is the slice
        layout's business) or an unexpectedly huge search space."""

        idx = chip_index
        chosen = list(must_include)
        pool = sorted((d for d in available if d not in chosen), key=idx)
        need = size - len(chosen)
        if need <= 0:
            return chosen[:size]
        if need >= len(pool):
            return chosen + pool
        if self.config.device_sets is None:
            picked = self._mesh_adjacent_pick(pool, chosen, need)
            if picked is not None:
                return chosen + picked
        # fallback: best contiguous window by index span
        best: Optional[list[str]] = None
        best_span = 1 << 30
        for i in range(0, max(0, len(pool) - need) + 1):
            window = pool[i : i + need]
            if len(window) < need:
                break
            span = idx(window[-1]) - idx(window[0])
            if span < best_span:
                best, best_span = window, span
        return chosen + (best or pool[:need])

    def _mesh_adjacent_pick(
        self, pool: list[str], chosen: list[str], need: int
    ) -> Optional[list[str]]:
        """Exhaustive best-adjacency pick over the host grid; None when the
        geometry doesn't apply (chip ids outside the canonical grid) or the
        search space exceeds the cap."""
        coords = host_grid_coords(len(self.devices))
        ids = [chip_index(d) for d in (*chosen, *pool)]
        if len(set(ids)) != len(ids) or any(i not in coords for i in ids):
            return None
        if math.comb(len(pool), need) > _MAX_ADJACENCY_SEARCH:
            return None
        base = [coords[chip_index(d)] for d in chosen]
        best, best_key = None, None
        for combo in itertools.combinations(pool, need):
            pts = base + [coords[chip_index(d)] for d in combo]
            dists = [
                abs(a[0] - b[0]) + abs(a[1] - b[1])
                for a, b in itertools.combinations(pts, 2)
            ]
            links = sum(1 for d in dists if d == 1)
            # most shared links first; among equals the tightest cluster
            key = (-links, sum(dists))
            if best_key is None or key < best_key:
                best, best_key = list(combo), key
        return best

    async def Allocate(self, request, context) -> api_pb2.AllocateResponse:
        resp = api_pb2.AllocateResponse()
        for creq in request.container_requests:
            if self.config.device_shape and len(creq.devicesIDs) > 1:
                # a partition unit is the isolation boundary (MIG-instance
                # semantics); two units do not merge into a larger ICI box,
                # so the bounds env could not describe the union truthfully
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"{self.config.resource_name}: at most one partition unit "
                    "per container (request a larger slice shape instead)",
                )
            cresp = api_pb2.ContainerAllocateResponse()
            # CDI-default: answer with qualified CDI device names and let
            # the runtime inject nodes/mounts from the plugin-maintained
            # spec file; env vars (below) still carry per-allocation values
            use_cdi = self.config.cdi_enabled and self.config.cdi_default
            chip_indices = []
            for did in creq.devicesIDs:
                paths = self.devices.get(did)
                if paths is None:
                    await context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT, f"unknown device {did}"
                    )
                if use_cdi:
                    cresp.cdi_devices.append(
                        api_pb2.CDIDevice(
                            name=f"{self.config.resource_name}={cdi_device_name(did)}"
                        )
                    )
                for path in paths:
                    # env-declared (virtual) chips have no device node to
                    # map; a nonexistent host_path would fail containerd
                    if os.path.exists(path) and not use_cdi:
                        cresp.devices.append(
                            api_pb2.DeviceSpec(
                                container_path=f"/dev/{os.path.basename(path)}",
                                host_path=path,
                                permissions="rw",
                            )
                        )
                    chip_indices.append(chip_index(os.path.basename(path)))
            chip_indices.sort()
            cresp.envs["TPU_VISIBLE_CHIPS"] = ",".join(str(i) for i in chip_indices)
            # libtpu wants the bounds of the chip grid the container sees as
            # a comma-separated x,y,z string, not a count ("2,2,1" for a
            # 4-chip v5e host) — a bare count breaks PJRT init.  Partition
            # units carry their exact ICI shape; the dynamic path falls back
            # to the canonical grid for the chip count.
            if self.config.device_shape:
                cresp.envs["TPU_CHIPS_PER_HOST_BOUNDS"] = shape_bounds(
                    self.config.device_shape
                )
            else:
                cresp.envs["TPU_CHIPS_PER_HOST_BOUNDS"] = hw.chip_bounds(len(chip_indices))
            cresp.envs["TPU_RUNTIME_METRICS_PORTS"] = ",".join(
                str(8431 + i) for i in chip_indices
            )
            # Worker id only describes multi-host slice membership, which
            # holds only for FULL-HOST allocations of the flat resource:
            # sub-host chips and mixed-strategy partition units are their own
            # (single- or partition-scoped) topology, where a host-level id
            # would misdeclare membership and break PJRT slice init.
            full_host = not self.config.device_shape and chip_indices and len(
                chip_indices
            ) == len(self.devices)
            wid = self.worker_id() if full_host else None
            if wid is not None:
                cresp.envs["TPU_WORKER_ID"] = str(wid)
            if os.path.isdir(self.config.libtpu_dir) and not use_cdi:
                # under CDI-default the spec's containerEdits carry this
                cresp.mounts.append(
                    api_pb2.Mount(
                        container_path=self.config.libtpu_dir,
                        host_path=self.config.libtpu_dir,
                        read_only=True,
                    )
                )
            resp.container_responses.append(cresp)
        return resp

    def worker_id(self) -> Optional[int]:
        return read_worker_id()

    async def PreStartContainer(self, request, context) -> api_pb2.PreStartContainerResponse:
        return api_pb2.PreStartContainerResponse()

    # -- lifecycle -----------------------------------------------------
    async def serve(self) -> None:
        """(Re)start the DevicePlugin server; safe to call after a kubelet
        restart wiped the plugin dir (old unlinked socket is replaced)."""
        if self._server is not None:
            await self.stop()
        self.refresh_devices()
        self.write_cdi_spec()
        os.makedirs(self.config.kubelet_dir, exist_ok=True)
        try:
            os.remove(self.config.socket_path)
        except OSError:
            pass
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((rpc.device_plugin_handler(self),))
        self._server.add_insecure_port(f"unix://{self.config.socket_path}")
        await self._server.start()
        self._health_task = asyncio.create_task(self._health_loop())
        log.info(
            "device plugin serving %d %s devices on %s",
            len(self.devices), self.config.resource_name, self.config.socket_path,
        )

    async def register(self) -> None:
        """Register with the kubelet (retried by the caller on failure)."""
        async with grpc.aio.insecure_channel(
            f"unix://{self.config.kubelet_socket_path}"
        ) as channel:
            stub = rpc.RegistrationStub(channel)
            await stub.Register(
                api_pb2.RegisterRequest(
                    version=rpc.API_VERSION,
                    endpoint=self.config.socket_name,
                    resource_name=self.config.resource_name,
                    options=api_pb2.DevicePluginOptions(
                        get_preferred_allocation_available=True
                    ),
                )
            )
        log.info("registered %s with kubelet", self.config.resource_name)

    async def stop(self) -> None:
        # reference-toolkit parity: specs are removed on shutdown so no
        # orphaned file keeps resolving against a dead inventory (re-serve
        # rewrites it)
        if self.config.cdi_enabled:
            try:
                os.remove(self._cdi_spec_path())
            except OSError:
                pass
        if self._health_task:
            self._health_task.cancel()
            try:
                await self._health_task
            except (asyncio.CancelledError, Exception):
                pass
            self._health_task = None
        if self._server:
            await self._server.stop(grace=1.0)
            self._server = None

    async def run_forever(self) -> None:
        """serve + register, re-serving AND re-registering after a kubelet
        restart: the kubelet wipes its device-plugins dir on startup, so the
        plugin socket must be recreated on disk, not just re-registered
        (restart detected via kubelet.sock inode change / plugin socket
        disappearance)."""
        await self.serve()
        while True:
            if not os.path.exists(self.config.socket_path):
                log.info("plugin socket removed (kubelet restart); re-serving")
                await self.serve()
            try:
                await self.register()
            except Exception as e:  # noqa: BLE001
                log.warning("kubelet registration failed (%s); retrying", e)
                await asyncio.sleep(5)
                continue
            try:
                ino = os.stat(self.config.kubelet_socket_path).st_ino
            except OSError:
                ino = None
            while True:
                await asyncio.sleep(self.config.health_interval)
                if not os.path.exists(self.config.socket_path):
                    break
                try:
                    if os.stat(self.config.kubelet_socket_path).st_ino != ino:
                        log.info("kubelet socket changed; re-registering")
                        break
                except OSError:
                    break
