"""Hand-rolled gRPC service/stub wiring for the device-plugin API.

grpcio is present but grpcio-tools (the _pb2_grpc generator) is not, so the
service handlers and stubs that `protoc-gen-grpc_python` would emit are
written out here — same method paths, same serializers.
"""

from __future__ import annotations

import grpc
import grpc.aio

from tpu_operator.deviceplugin import api_pb2

REGISTRATION_SERVICE = "v1beta1.Registration"
DEVICE_PLUGIN_SERVICE = "v1beta1.DevicePlugin"
API_VERSION = "v1beta1"


# ---------------------------------------------------------------------------
# Server-side: generic handlers.


def registration_handler(servicer) -> grpc.GenericRpcHandler:
    """servicer: async Register(request, context) -> Empty"""
    return grpc.method_handlers_generic_handler(
        REGISTRATION_SERVICE,
        {
            "Register": grpc.unary_unary_rpc_method_handler(
                servicer.Register,
                request_deserializer=api_pb2.RegisterRequest.FromString,
                response_serializer=api_pb2.Empty.SerializeToString,
            )
        },
    )


def device_plugin_handler(servicer) -> grpc.GenericRpcHandler:
    """servicer implements the five DevicePlugin methods (async)."""
    return grpc.method_handlers_generic_handler(
        DEVICE_PLUGIN_SERVICE,
        {
            "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
                servicer.GetDevicePluginOptions,
                request_deserializer=api_pb2.Empty.FromString,
                response_serializer=api_pb2.DevicePluginOptions.SerializeToString,
            ),
            "ListAndWatch": grpc.unary_stream_rpc_method_handler(
                servicer.ListAndWatch,
                request_deserializer=api_pb2.Empty.FromString,
                response_serializer=api_pb2.ListAndWatchResponse.SerializeToString,
            ),
            "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
                servicer.GetPreferredAllocation,
                request_deserializer=api_pb2.PreferredAllocationRequest.FromString,
                response_serializer=api_pb2.PreferredAllocationResponse.SerializeToString,
            ),
            "Allocate": grpc.unary_unary_rpc_method_handler(
                servicer.Allocate,
                request_deserializer=api_pb2.AllocateRequest.FromString,
                response_serializer=api_pb2.AllocateResponse.SerializeToString,
            ),
            "PreStartContainer": grpc.unary_unary_rpc_method_handler(
                servicer.PreStartContainer,
                request_deserializer=api_pb2.PreStartContainerRequest.FromString,
                response_serializer=api_pb2.PreStartContainerResponse.SerializeToString,
            ),
        },
    )


# ---------------------------------------------------------------------------
# Client-side stubs.


class RegistrationStub:
    def __init__(self, channel: grpc.aio.Channel):
        self.Register = channel.unary_unary(
            f"/{REGISTRATION_SERVICE}/Register",
            request_serializer=api_pb2.RegisterRequest.SerializeToString,
            response_deserializer=api_pb2.Empty.FromString,
        )


class DevicePluginStub:
    def __init__(self, channel: grpc.aio.Channel):
        self.GetDevicePluginOptions = channel.unary_unary(
            f"/{DEVICE_PLUGIN_SERVICE}/GetDevicePluginOptions",
            request_serializer=api_pb2.Empty.SerializeToString,
            response_deserializer=api_pb2.DevicePluginOptions.FromString,
        )
        self.ListAndWatch = channel.unary_stream(
            f"/{DEVICE_PLUGIN_SERVICE}/ListAndWatch",
            request_serializer=api_pb2.Empty.SerializeToString,
            response_deserializer=api_pb2.ListAndWatchResponse.FromString,
        )
        self.GetPreferredAllocation = channel.unary_unary(
            f"/{DEVICE_PLUGIN_SERVICE}/GetPreferredAllocation",
            request_serializer=api_pb2.PreferredAllocationRequest.SerializeToString,
            response_deserializer=api_pb2.PreferredAllocationResponse.FromString,
        )
        self.Allocate = channel.unary_unary(
            f"/{DEVICE_PLUGIN_SERVICE}/Allocate",
            request_serializer=api_pb2.AllocateRequest.SerializeToString,
            response_deserializer=api_pb2.AllocateResponse.FromString,
        )
        self.PreStartContainer = channel.unary_unary(
            f"/{DEVICE_PLUGIN_SERVICE}/PreStartContainer",
            request_serializer=api_pb2.PreStartContainerRequest.SerializeToString,
            response_deserializer=api_pb2.PreStartContainerResponse.FromString,
        )
