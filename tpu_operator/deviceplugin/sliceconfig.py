"""Mixed slice strategy: partition layout → per-shape device-plugin set.

Reference analogue: MIG ``mixed`` strategy, where the device plugin stops
advertising bare ``nvidia.com/gpu`` and serves one resource per MIG profile
(``nvidia.com/mig-1g.5gb`` …, controllers/object_controls.go:2230-2241).
TPU version: the slice manager materialises the applied partition layout at
``/run/tpu/slice_config.json`` (agents/slice_manager.py); under
``sliceManager.strategy: mixed`` this module turns that layout into one
plugin instance per partition SHAPE — resource ``google.com/tpu-<shape>``,
each device being one partition unit (this host's chips of one partition),
allocated atomically like a MIG instance.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

from tpu_operator import consts, hw
from tpu_operator.deviceplugin.plugin import PluginConfig, TPUDevicePlugin, read_worker_id
from tpu_operator.validator import status as vstatus

log = logging.getLogger("tpu_operator.deviceplugin")


def read_layout() -> Optional[dict]:
    """The applied slice layout, or None when absent/unreadable."""
    try:
        with open(vstatus.slice_config_path()) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def host_chip_count(mode: str = "accel") -> int:
    """Chips this host owns, by the mode's device source.  /dev/accel* (or
    the TPU_CHIP_COUNT env) is the truth on container nodes; a vfio-bound
    host has NO accel nodes left (the vfio-manager's driver_override rebind
    removed them), so its iommu groups — one per chip — are the count."""
    count = hw.chip_count()
    if count == 0 and mode == "vfio":
        count = len(hw.vfio_device_paths())
    return count


def config_signature(mode: str = "accel") -> str:
    """Change-detection key for the reconfig watch: the applied layout, this
    host's worker id, and its chip count — a late-arriving worker_id file
    (TFD starting after the plugin DS on a fresh multi-host node) changes
    which partition units this host owns, and device nodes appearing after
    the plugin started flips the spans-hosts classification; both must
    rebuild the plugin set."""
    layout = read_layout()
    sig = json.dumps(layout, sort_keys=True) if layout else ""
    return f"{sig}|worker={_worker_id()}|chips={host_chip_count(mode)}"


def host_units(
    layout: Optional[dict], worker_id: int, chips_per_host: int
) -> dict[str, list[list[int]]]:
    """{shape: [local chip indices of each partition unit on this host]}.

    Global chip ids are row-major over the slice mesh; host h owns
    [h*chips_per_host, (h+1)*chips_per_host) (slices.chip_assignments
    convention).  A partition spanning several hosts contributes one unit
    per host — each host advertises its share, and multi-host workloads
    consume one unit per worker pod.
    """
    out: dict[str, list[list[int]]] = {}
    if not layout:
        return out
    lo = worker_id * chips_per_host
    hi = lo + chips_per_host
    for part in layout.get("partitions") or []:
        local = [cid - lo for cid in part.get("chip_ids", []) if lo <= cid < hi]
        if local:
            out.setdefault(part["shape"], []).append(sorted(local))
    return out


def resource_name(shape: str) -> str:
    return f"{consts.TPU_RESOURCE}-{shape.lower()}"


def build_plugin_configs(
    strategy: str,
    base: Optional[PluginConfig] = None,
) -> list[PluginConfig]:
    """The plugin set this node should run right now.

    - strategy none/single, or mixed with an empty/whole-slice layout →
      the single dynamic ``google.com/tpu`` plugin (MIG-single semantics:
      homogeneous sub-slices still count under the flat resource).
    - mixed with partitions → one static plugin per shape.
    """
    base = base or PluginConfig()
    if strategy != "mixed":
        return [base]
    layout = read_layout()
    chips = host_chip_count(base.mode)
    worker = _worker_id()
    if worker is None:
        if _layout_spans_hosts(layout, max(1, chips)):
            # no worker-id source yet (TFD hasn't written the handoff file):
            # assuming worker 0 would advertise another host's partition
            # units backed by the wrong chips — serve the flat plugin until
            # the id arrives (config_signature flips when it does)
            log.warning(
                "mixed strategy on a multi-host layout with no worker id yet; "
                "serving flat plugin until TFD provides one"
            )
            return [base]
        worker = 0
    units = host_units(layout, worker, max(1, chips))
    if not units:
        return [base]
    configs = []
    for shape, unit_list in sorted(units.items()):
        sets = {
            f"tpu-{shape}-{k}": [_chip_path(i, base.mode) for i in unit]
            for k, unit in enumerate(unit_list)
        }
        configs.append(
            PluginConfig(
                resource_name=resource_name(shape),
                socket_name=f"tpu-{shape.lower()}.sock",
                kubelet_dir=base.kubelet_dir,
                mode=base.mode,
                health_interval=base.health_interval,
                libtpu_dir=base.libtpu_dir,
                device_sets=sets,
                device_shape=shape,
            )
        )
    return configs


def _worker_id() -> Optional[int]:
    """This host's slice worker id, or None when no source (env or TFD
    handoff file) has produced one yet."""
    return read_worker_id()


def _layout_spans_hosts(layout: Optional[dict], chips_per_host: int) -> bool:
    """True when the layout describes a multi-host slice, i.e. worker
    identity decides which partition units this host owns.  Derived from the
    layout's slice topology (a 4x4 slice at 4 chips/host is 4 hosts even if
    every partition's chip ids happen to fall inside host 0's range); the
    chip-id span check is the fallback when the topology is absent."""
    from tpu_operator.utils import topology_chips

    topo = (layout or {}).get("topology") or ""
    if topo:
        try:
            return topology_chips(topo) > chips_per_host
        except ValueError:
            pass
    for part in (layout or {}).get("partitions") or []:
        if any(cid >= chips_per_host for cid in part.get("chip_ids", [])):
            return True
    return False


def _chip_path(local_index: int, mode: str = "accel") -> str:
    """Local chip index → host device path (existing node preferred; the
    virtual fallback mirrors discover_devices' env-declared mode).
    Both path lists are numerically ordered, so index N is chip N — the
    same ordering contract the flat plugin's discover_devices relies on
    (for vfio, the vfio-manager binds chips in /dev/accel order, so group
    numbering follows chip numbering)."""
    if mode == "vfio":
        paths = hw.vfio_device_paths()
        fallback = f"/dev/vfio/{local_index}"
    else:
        paths = hw.accel_device_paths()
        fallback = f"/dev/accel{local_index}"
    if local_index < len(paths):
        return paths[local_index]
    return fallback


async def run_plugins(strategy: str, base: PluginConfig, poll_seconds: float = 10.0) -> None:
    """Serve the plugin set, reconciling it whenever the applied slice layout
    changes (the slice manager's post-reconfig 'notification' is the file
    itself).  The reconcile is INCREMENTAL: only plugins whose config
    actually changed are stopped/started — an unchanged shape keeps its
    socket and kubelet registration across a repartition that only touches
    other shapes (the r02 full-restart caused a kubelet-visible blip for
    every resource on every reconfigure)."""
    import asyncio
    import dataclasses

    # resource name → (config identity, plugin, serving task)
    running: dict[str, tuple[str, TPUDevicePlugin, "asyncio.Task"]] = {}

    def _key(cfg: PluginConfig) -> str:
        return json.dumps(dataclasses.asdict(cfg), sort_keys=True, default=str)

    async def _stop(resource: str) -> None:
        _, plugin, task = running.pop(resource)
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass
        await plugin.stop()

    try:
        while True:
            # signature FIRST: a layout write landing between the config
            # build and a later capture would be absorbed unseen (the
            # reconcile below spans real await points)
            signature = config_signature(base.mode) if strategy == "mixed" else ""
            desired = {
                c.resource_name: c for c in build_plugin_configs(strategy, base)
            }
            for resource in list(running):
                if (
                    resource not in desired
                    or _key(desired[resource]) != running[resource][0]
                    or running[resource][2].done()  # crashed task: revive
                ):
                    log.info("plugin %s removed/changed/dead; restarting it", resource)
                    await _stop(resource)
            for resource, cfg in desired.items():
                if resource not in running:
                    plugin = TPUDevicePlugin(cfg)
                    running[resource] = (
                        _key(cfg),
                        plugin,
                        asyncio.create_task(plugin.run_forever()),
                    )
            log.info("serving %d plugin(s): %s", len(running), sorted(running))
            while True:
                await asyncio.sleep(poll_seconds)
                if strategy == "mixed" and config_signature(base.mode) != signature:
                    log.info("slice layout/worker-id changed; reconciling plugin set")
                    break
                dead = {
                    resource: entry[2]
                    for resource, entry in running.items()
                    if entry[2].done()
                }
                if dead:
                    for resource, task in dead.items():
                        exc = None if task.cancelled() else task.exception()
                        log.warning(
                            "plugin %s serving task died; reconciling plugin set",
                            resource, exc_info=exc,
                        )
                    break
    finally:
        for resource in list(running):
            await _stop(resource)
