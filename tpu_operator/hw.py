"""Host hardware probing seam.

The reference shells out to chroot'd nvidia-smi / lspci for host truth
(validator/main.go:606-718, metrics.go:250-300).  TPU hosts have no smi tool;
truth comes from /dev/accel* device nodes, the libtpu shared object, and PJRT
client init.  Everything roots at ``TPU_HW_ROOT`` (default ``/``) so tests
and the fake kubelet can present a synthetic host.
"""

from __future__ import annotations

import glob
import os


def hw_root() -> str:
    return os.environ.get("TPU_HW_ROOT", "/")


def _trailing_number(path: str) -> int:
    digits = ""
    for c in reversed(os.path.basename(path)):
        if c.isdigit():
            digits = c + digits
        else:
            break
    return int(digits) if digits else -1


def accel_device_paths() -> list[str]:
    """TPU chip device nodes: /dev/accel* (COS) or /dev/vfio/* when bound
    for passthrough.  Numeric order — lexicographic sorting would put
    accel10 before accel2, scrambling chip-index↔path alignment on 10+ chip
    hosts."""
    root = hw_root()
    paths = glob.glob(os.path.join(root, "dev", "accel*"))
    return sorted(paths, key=lambda p: (_trailing_number(p), p))


def vfio_device_paths() -> list[str]:
    """IOMMU group device nodes, in NUMERIC group order (same rationale as
    accel_device_paths: lexicographic sorting puts group 10 before group 7,
    scrambling the chip-index↔group alignment the partitioned-passthrough
    path relies on)."""
    root = hw_root()
    paths = [
        p
        for p in glob.glob(os.path.join(root, "dev", "vfio", "*"))
        if os.path.basename(p) != "vfio"  # the container device, not a group
    ]
    return sorted(paths, key=lambda p: (_trailing_number(p), p))


def chip_count() -> int:
    """TPU_CHIP_COUNT env override → /dev/accel* count → 0."""
    env = os.environ.get("TPU_CHIP_COUNT")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    return len(accel_device_paths())


# chip count → x,y,z bounds of the chip grid those chips form on one host.
# libtpu parses TPU_CHIPS_PER_HOST_BOUNDS as a comma-separated 3-D bounds
# string (a v5e 4-chip host is a 2x2x1 grid), NOT a bare count.
_CHIP_GRID_BOUNDS = {
    1: (1, 1, 1),
    2: (1, 2, 1),
    4: (2, 2, 1),
    8: (2, 4, 1),
    16: (4, 4, 1),
}


def chip_bounds(count: int) -> str:
    """x,y,z bounds string for ``count`` chips (e.g. 4 → "2,2,1")."""
    x, y, z = _CHIP_GRID_BOUNDS.get(count, (count, 1, 1))
    return f"{x},{y},{z}"


_LIBTPU_GLOBS = (
    "home/kubernetes/tpu/libtpu.so",
    "usr/lib/libtpu.so",
    "usr/local/lib/libtpu.so",
    "lib/libtpu.so",
)


def libtpu_path() -> str:
    """LIBTPU_PATH env override → well-known install locations under hw root."""
    env = os.environ.get("LIBTPU_PATH")
    if env and os.path.exists(env):
        return env
    root = hw_root()
    for rel in _LIBTPU_GLOBS:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            return path
    # the pip-installed libtpu the jax stack bundles also counts as present
    try:
        import libtpu  # type: ignore[import-not-found]

        return os.path.dirname(libtpu.__file__)
    except ImportError:
        return ""
