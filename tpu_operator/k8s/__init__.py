"""Minimal in-tree Kubernetes client.

The reference leans on sigs.k8s.io/controller-runtime for its client, caches,
watches and leader election; no Python equivalent ships in this image, so this
package provides the slice of that functionality the operator needs:

- ``objects``    unstructured object helpers (GVK ↔ REST path mapping)
- ``selectors``  label-selector parsing/matching (k8s.io/apimachinery labels)
- ``client``     async REST client: CRUD, status subresource, list, watch
- ``informer``   list+watch cache with handlers (controller-runtime cache)
- ``apply``      create-or-update with last-applied-hash skip (stateSkel analogue)
- ``leader``     Lease-based leader election (main.go:105-115 analogue)
"""

from tpu_operator.k8s.client import ApiClient, ApiError, Config
from tpu_operator.k8s.objects import gvk_of, resource_path

__all__ = ["ApiClient", "ApiError", "Config", "gvk_of", "resource_path"]
