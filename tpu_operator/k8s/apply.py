"""Create-or-update with last-applied-hash skip.

Reference analogue: internal/state/state_skel.go:223-285 (createOrUpdateObjs)
and the DaemonSet hash-skip of controllers/object_controls.go:4173-4199.
Rather than strategic-merge or SSA (which the fake apiserver doesn't model),
desired state fully replaces spec; server-owned metadata is preserved by the
server on PUT, and a content hash annotation avoids no-op updates (and thus
pointless DaemonSet restarts).
"""

from __future__ import annotations

import contextlib
import copy
import logging
from typing import Optional

from tpu_operator import consts
from tpu_operator.k8s.client import ApiClient, ApiError
from tpu_operator.k8s import objects as obj_api
from tpu_operator.obs import trace
from tpu_operator.utils import object_hash

log = logging.getLogger("tpu_operator.k8s.apply")


def desired_hash(obj: dict) -> str:
    scrubbed = copy.deepcopy(obj)
    meta = scrubbed.get("metadata", {})
    meta.pop("resourceVersion", None)
    meta.pop("uid", None)
    meta.pop("creationTimestamp", None)
    meta.pop("generation", None)
    (meta.get("annotations") or {}).pop(consts.LAST_APPLIED_HASH_ANNOTATION, None)
    scrubbed.pop("status", None)
    return object_hash(scrubbed)


async def create_or_update(
    client: ApiClient,
    obj: dict,
    owner: Optional[dict] = None,
    state_label: Optional[str] = None,
) -> tuple[dict, bool]:
    """Apply desired state. Returns (live_object, changed).

    - stamps the state label (addStateSpecificLabels analogue, state_skel.go:287)
    - sets the controller ownerReference when an owner is given
    - skips the update entirely when the desired-hash annotation matches
    """
    # in-flight gauge when the client is a CachedReader carrying metrics
    inflight = getattr(client, "inflight_apply", None)
    with inflight() if inflight is not None else contextlib.nullcontext():
        with trace.span(
            f"apply/{obj.get('kind', '')}",
            kind=trace.KIND_APPLY,
            object_kind=obj.get("kind", ""),
            object_name=(obj.get("metadata") or {}).get("name", ""),
        ):
            return await _create_or_update(client, obj, owner, state_label)


def _prepare_update(obj: dict, live: dict, gvk) -> None:
    """Carry server-owned fields from ``live`` into the desired ``obj`` ahead
    of a full-replace PUT: the resourceVersion for optimistic concurrency,
    plus fields we do not manage (state_skel.go:358-380 analogue)."""
    obj["metadata"]["resourceVersion"] = live["metadata"].get("resourceVersion")
    if gvk.kind == "ServiceAccount":
        for f in ("secrets", "imagePullSecrets"):
            if f in live and f not in obj:
                obj[f] = live[f]
    if gvk.kind == "Service":
        # immutable/server-allocated Service fields: a full-replace PUT that
        # omits spec.clusterIP is a 422 on a real apiserver, wedging the
        # owning state in ERROR on any Service drift
        live_spec = live.get("spec") or {}
        spec = obj.setdefault("spec", {})
        for f in ("clusterIP", "clusterIPs", "ipFamilies", "ipFamilyPolicy", "healthCheckNodePort"):
            if f in live_spec and f not in spec:
                spec[f] = live_spec[f]


async def _create_or_update(
    client: ApiClient,
    obj: dict,
    owner: Optional[dict],
    state_label: Optional[str],
) -> tuple[dict, bool]:
    obj = copy.deepcopy(obj)
    meta = obj.setdefault("metadata", {})
    if state_label:
        meta.setdefault("labels", {})[consts.STATE_LABEL] = state_label
    if owner is not None:
        obj_api.set_owner_reference(obj, owner)
    h = desired_hash(obj)
    meta.setdefault("annotations", {})[consts.LAST_APPLIED_HASH_ANNOTATION] = h

    gvk = obj_api.gvk_of(obj)
    # conflict/race recovery must re-read the apiserver, not the informer
    # store — with a CachedReader the cached copy IS the stale copy
    live_client = getattr(client, "live", client)

    # The GET is served from the informer cache when the client is a
    # CachedReader watching this GVK: a steady-state pass whose cached copy
    # already carries the desired hash costs ZERO API requests.
    live: Optional[dict] = None
    try:
        live = await client.get(gvk.group, gvk.kind, meta["name"], meta.get("namespace"))
    except ApiError as e:
        if not e.not_found:
            raise

    # Up to three rounds of create-if-absent / hash-skip / replace.  Every
    # recoverable race — a lost get-before-create (409 AlreadyExists), a
    # stale resourceVersion (informer lag or a concurrent writer, 409
    # Conflict), or the object deleted under us (404 on PUT, or the 409'd
    # creation finishing its termination) — re-reads LIVE and retries; the
    # final round surfaces whatever the apiserver says.  A recreate after a
    # deletion must start from the PRISTINE desired object: _prepare_update
    # grafts server-allocated fields (Service clusterIP, SA secrets) from
    # the now-deleted live copy, and resurrecting those in a POST is a 422.
    pristine = copy.deepcopy(obj)
    for round_ in range(3):
        last = round_ == 2
        if live is None:
            try:
                created = await client.create(obj)
                log.info("created %s %s/%s", gvk.kind, meta.get("namespace", ""), meta["name"])
                return created, True
            except ApiError as e:
                if not e.already_exists or last:
                    raise
                # another pass/replica won the race; adopt the winner
                try:
                    live = await live_client.get(gvk.group, gvk.kind, meta["name"], meta.get("namespace"))
                except ApiError as e2:
                    if not e2.not_found:
                        raise
                    # the 409 came from an object mid-termination that has
                    # since finished deleting; create again next round
                continue
        live_hash = (live.get("metadata", {}).get("annotations") or {}).get(
            consts.LAST_APPLIED_HASH_ANNOTATION
        )
        if live_hash == h:
            return live, False
        _prepare_update(obj, live, gvk)
        try:
            updated = await client.update(obj)
        except ApiError as e:
            if e.not_found and not last:
                # deleted under us (cached copy outlived the object)
                obj = copy.deepcopy(pristine)
                live = None
                continue
            if not e.conflict or last:
                raise
            try:
                live = await live_client.get(gvk.group, gvk.kind, meta["name"], meta.get("namespace"))
            except ApiError as e2:
                if not e2.not_found:
                    raise
                obj = copy.deepcopy(pristine)
                live = None
            continue
        log.info("updated %s %s/%s", gvk.kind, meta.get("namespace", ""), meta["name"])
        return updated, True
    raise AssertionError("unreachable: final round returns or raises")


async def delete_if_exists(client: ApiClient, obj: dict) -> None:
    gvk = obj_api.gvk_of(obj)
    meta = obj.get("metadata", {})
    await client.delete(gvk.group, gvk.kind, meta["name"], meta.get("namespace"))
