"""Informer-backed cached read layer.

Controller-runtime's split-client analogue: reads (get/list) are served from
the shared informer stores when a live informer watches the requested GVK at
the requested scope, with live-API fallback on any miss; writes always pass
through to the real :class:`~tpu_operator.k8s.client.ApiClient`.  Steady-state
reconcile passes become nearly API-free — the fan-out that used to pay one
GET/LIST round-trip per object per pass reads local memory instead (see
docs/PERFORMANCE.md for the measured budget).

Correctness model: the cache may lag the apiserver by the watch-event
propagation delay.  Readers that *mutate* based on a cached copy recover from
staleness at write time — an optimistic-concurrency 409 re-reads live and
retries (``k8s/apply.py``, ``_update_status``) — and a cached *miss* (object
not in the store) always falls back to a live GET, so a just-created object
is never misread as absent.
"""

from __future__ import annotations

import asyncio
import contextlib
import copy
import time
from typing import Any, Iterator, Optional

from tpu_operator.k8s import objects as obj_api
from tpu_operator.k8s import selectors
from tpu_operator.k8s.client import ApiClient
from tpu_operator.k8s.informer import Informer

VERSION_TTL_SECONDS = 600.0


class _UnionCache:
    """Write-through router for a :class:`PartitionedView`: an object
    written through lands in the part whose selector its labels match NOW
    (and leaves any part it no longer matches — a shard re-stamp moves the
    cached copy between views the same instant the write succeeds, without
    waiting for the synthesized watch delete/add round trip)."""

    def __init__(self, view: "PartitionedView"):
        self._view = view

    def __setitem__(self, key: tuple[str, str], obj: dict) -> None:
        labels = (obj.get("metadata") or {}).get("labels") or {}
        for part in self._view.parts.values():
            if not part.synced.is_set():
                continue
            if selectors.matches(part.label_selector or "", labels):
                part.cache[key] = obj
            else:
                part.cache.pop(key, None)

    def pop(self, key: tuple[str, str], default=None):
        out = default
        for part in self._view.parts.values():
            hit = part.cache.pop(key, None)
            if hit is not None:
                out = hit
        return out


class PartitionedView:
    """Union read view over selector-partitioned informers of ONE kind.

    The multi-replica sharded plane watches Nodes one owned shard at a
    time (``label_selector=tpu.google.com/shard=<sid>``) plus an intake
    view of not-yet-stamped nodes; no single informer can serve reads of
    the kind, but their union is this replica's entire serviceable scope.
    This composite presents the ``Informer`` read surface (``synced`` /
    ``get`` / ``items`` / ``cache``) so a :class:`CachedReader` serves
    node reads from the owned arcs; a read outside them simply misses and
    falls back live — the CachedReader miss contract already covers it.

    Honesty caveat (why the full manager never registers one of these):
    ``items()``/``list`` answer with the UNION OF OWNED ARCS, not the
    fleet.  Only consumers scoped to this replica's arcs — the per-node
    delta reconciler, per-arc priming — may read through it; a full-walk
    controller needs an unfiltered informer.
    """

    def __init__(self, group: str, kind: str):
        self.group = group
        self.kind = kind
        # Informer-surface fields the CachedReader inspects: the union
        # serves kind-wide point reads (scope-miss falls back live)
        self.namespace: Optional[str] = None
        self.label_selector: Optional[str] = None
        self.required = False
        self.parts: dict[str, Informer] = {}
        self.synced = asyncio.Event()
        self._cache = _UnionCache(self)

    @property
    def cache(self) -> _UnionCache:
        return self._cache

    def add_part(self, key: str, informer: Informer) -> None:
        self.parts[key] = informer
        if informer.synced.is_set():
            self.synced.set()

    def mark_synced(self) -> None:
        """Called once a newly-added part finishes its first relist."""
        if any(p.synced.is_set() for p in self.parts.values()):
            self.synced.set()

    def remove_part(self, key: str) -> Optional[Informer]:
        part = self.parts.pop(key, None)
        if not any(p.synced.is_set() for p in self.parts.values()):
            self.synced.clear()
        return part

    def get(self, name: str, namespace: str = "") -> Optional[dict]:
        for part in self.parts.values():
            obj = part.get(name, namespace)
            if obj is not None:
                return obj
        return None

    def items(self) -> list[dict]:
        out: list[dict] = []
        for part in self.parts.values():
            out.extend(part.items())
        return out


class CachedReader:
    """Read-through cache over an ``ApiClient`` plus registered informers.

    Drop-in for ``ApiClient`` anywhere in the reconcile chain: ``get`` /
    ``list`` / ``list_items`` are intercepted; every other attribute
    (create/update/patch/delete/update_status/watch/...) delegates to the
    live client.  ``live`` exposes the raw client for reads that must bypass
    the cache (conflict recovery).
    """

    def __init__(self, client: ApiClient, metrics: Optional[Any] = None):
        self.live = client
        self.metrics = metrics
        self._informers: dict[tuple[str, str], Informer] = {}
        self._version: Optional[str] = None
        self._version_at = 0.0

    def add_informer(self, informer: Informer) -> None:
        self._informers[(informer.group, informer.kind)] = informer

    def informer_for(self, group: str, kind: str, namespace: Optional[str]) -> Optional[Informer]:
        """The informer able to serve reads of (group, kind) at ``namespace``
        scope, or None (not watched / not yet synced / scope or selector
        narrower than the request)."""
        inf = self._informers.get((group, kind))
        if inf is None or not inf.synced.is_set():
            return None
        if inf.namespace and inf.namespace != namespace:
            return None
        if inf.label_selector:
            # a filtered watch cannot answer arbitrary reads of the kind
            return None
        return inf

    # ------------------------------------------------------------------
    def _hit(self, kind: str) -> None:
        if self.metrics is not None:
            self.metrics.cache_hits_total.labels(kind=kind).inc()

    def _miss(self, kind: str) -> None:
        if self.metrics is not None:
            self.metrics.cache_misses_total.labels(kind=kind).inc()

    @contextlib.contextmanager
    def inflight_apply(self) -> Iterator[None]:
        """Tracks tpu_operator_inflight_applies around one create_or_update
        (the apply layer picks this up by duck-typing on its client)."""
        gauge = getattr(self.metrics, "inflight_applies", None)
        if gauge is not None:
            gauge.inc()
        try:
            yield
        finally:
            if gauge is not None:
                gauge.dec()

    # ------------------------------------------------------------------
    async def get(
        self,
        group: str,
        kind: str,
        name: str,
        namespace: Optional[str] = None,
        copy_result: bool = True,
    ) -> dict:
        inf = self.informer_for(group, kind, namespace)
        if inf is not None:
            obj = inf.get(name, namespace or "")
            if obj is not None:
                self._hit(kind)
                # deepcopy: callers mutate (hash stamping, status edits) and
                # must never write into the informer's store.
                # ``copy_result=False`` is the READ-ONLY fast path for
                # per-key sweeps at fleet scale (the node delta reconciler
                # reads thousands of nodes per resync and mutates none) —
                # callers opting in must never write into the result.
                return copy.deepcopy(obj) if copy_result else obj
            # absent from the store is NOT proof of absence (informer lag on
            # a fresh create); only a live GET may conclude NotFound
        self._miss(kind)
        return await self.live.get(group, kind, name, namespace)

    async def list(
        self,
        group: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
        limit: Optional[int] = None,
        continue_token: Optional[str] = None,
    ) -> dict:
        if limit is not None or continue_token:
            # chunked listing is a live-API protocol (continue tokens are
            # server state); cached callers never paginate
            return await self.live.list(
                group, kind, namespace, label_selector, field_selector,
                limit=limit, continue_token=continue_token,
            )
        inf = self.informer_for(group, kind, namespace)
        if inf is not None and field_selector is None:
            self._hit(kind)
            items = inf.items()
            if namespace:
                items = [
                    o for o in items if o.get("metadata", {}).get("namespace") == namespace
                ]
            if label_selector:
                reqs = selectors.parse(label_selector)
                items = [
                    o for o in items
                    if all(r.matches(o.get("metadata", {}).get("labels") or {}) for r in reqs)
                ]
            return {"items": copy.deepcopy(items)}
        self._miss(kind)
        return await self.live.list(group, kind, namespace, label_selector, field_selector)

    async def list_items(self, *args, **kwargs) -> list[dict]:
        return (await self.list(*args, **kwargs)).get("items", [])

    async def get_version(self) -> str:
        """TTL-memoized /version: one live probe per TTL window instead of
        one per reconcile pass."""
        now = time.monotonic()
        if self._version is None or now - self._version_at > VERSION_TTL_SECONDS:
            self._version = await self.live.get_version()
            self._version_at = now
        return self._version

    # ------------------------------------------------------------------
    # Read-your-writes: successful mutations are written through into the
    # backing informer store immediately.  Without this, the pass AFTER a
    # write reads the pre-write cache (watch-event lag) and re-issues the
    # same mutation as a wasted no-op request; the watch later delivers the
    # same object and the store converges regardless.

    def _write_through(self, obj: Optional[dict]) -> None:
        if not isinstance(obj, dict):
            return
        meta = obj.get("metadata") or {}
        try:
            gvk = obj_api.gvk_of(obj)
        except Exception:  # noqa: BLE001 — unregistered kind: nothing watches it
            return
        inf = self._informers.get((gvk.group, gvk.kind))
        if inf is None or not inf.synced.is_set() or not meta.get("name"):
            return
        inf.cache[(meta.get("namespace", "") or "", meta["name"])] = copy.deepcopy(obj)

    async def create(self, obj: dict) -> dict:
        created = await self.live.create(obj)
        self._write_through(created)
        return created

    async def update(self, obj: dict) -> dict:
        updated = await self.live.update(obj)
        self._write_through(updated)
        return updated

    async def update_status(self, obj: dict) -> dict:
        updated = await self.live.update_status(obj)
        self._write_through(updated)
        return updated

    async def patch(self, group: str, kind: str, name: str, patch: Any, **kwargs) -> dict:
        patched = await self.live.patch(group, kind, name, patch, **kwargs)
        self._write_through(patched)
        return patched

    async def delete(self, group: str, kind: str, name: str,
                     namespace: Optional[str] = None, **kwargs) -> Optional[dict]:
        result = await self.live.delete(group, kind, name, namespace, **kwargs)
        inf = self._informers.get((group, kind))
        if inf is not None:
            inf.cache.pop((namespace or "", name), None)
        return result

    # everything else passes straight through
    def __getattr__(self, name: str):
        return getattr(self.live, name)
