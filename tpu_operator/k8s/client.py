"""Async Kubernetes REST client (CRUD + status + list + watch).

Fills the role controller-runtime's client plays for the reference
(controllers use Get/List/Create/Update/Delete + watches).  Speaks plain
HTTPS/JSON to the API server: in-cluster config from the service-account
token, kubeconfig-less by design (the operator always runs in a pod; tests
point it at the in-process fake apiserver via ``Config(base_url=...)``).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import ssl
import time
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Iterator, Optional

import aiohttp

from tpu_operator import consts
from tpu_operator.k8s import objects as obj_api
from tpu_operator.obs import trace
from tpu_operator.utils import bounded_gather

log = logging.getLogger("tpu_operator.k8s")

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


@dataclass
class Config:
    base_url: str
    token: Optional[str] = None
    token_file: Optional[str] = None  # re-read periodically (bound SA tokens rotate ~1h)
    ca_file: Optional[str] = None
    verify_ssl: bool = True

    @classmethod
    def in_cluster(cls) -> "Config":
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        token_path = os.path.join(SERVICE_ACCOUNT_DIR, "token")
        ca_path = os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
        token = None
        if os.path.exists(token_path):
            with open(token_path) as f:
                token = f.read().strip()
        return cls(
            base_url=f"https://{host}:{port}",
            token=token,
            token_file=token_path if os.path.exists(token_path) else None,
            ca_file=ca_path if os.path.exists(ca_path) else None,
        )

    @classmethod
    def from_env(cls) -> "Config":
        """KUBERNETES_API_URL override (tests / out-of-cluster), else in-cluster."""
        url = os.environ.get("KUBERNETES_API_URL")
        if url:
            return cls(base_url=url, token=os.environ.get("KUBERNETES_API_TOKEN"))
        return cls.in_cluster()


class ApiError(Exception):
    def __init__(self, status: int, reason: str, body: Any = None):
        self.status = status
        self.reason = reason
        self.body = body
        super().__init__(f"{status} {reason}")

    @property
    def not_found(self) -> bool:
        return self.status == 404

    # A 409 is two distinct situations the apiserver distinguishes by reason:
    # an optimistic-concurrency resourceVersion conflict ("Conflict") vs a
    # get-before-create race lost to another writer ("AlreadyExists").  The
    # recovery differs — conflict re-reads and retries, already-exists adopts
    # the existing object — so the predicates must not alias.
    @property
    def conflict(self) -> bool:
        return self.status == 409 and self.reason != "AlreadyExists"

    @property
    def already_exists(self) -> bool:
        return self.status == 409 and self.reason == "AlreadyExists"


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED | BOOKMARK | ERROR
    object: dict


class RequestCounter:
    """Mutable per-context API-request tally (see ``count_api_requests``)."""

    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0


# Ambient request tally: a reconcile pass installs a counter here and every
# ApiClient._request within that task tree (child tasks copy the context and
# share the same counter object) increments it — informer background watches
# run outside the pass's context and are excluded by construction.  Feeds
# tpu_operator_k8s_requests_per_reconcile.
_REQUEST_COUNTER: ContextVar[Optional[RequestCounter]] = ContextVar(
    "tpu_operator_k8s_request_counter", default=None
)


@contextlib.contextmanager
def count_api_requests() -> Iterator[RequestCounter]:
    counter = RequestCounter()
    token = _REQUEST_COUNTER.set(counter)
    try:
        yield counter
    finally:
        _REQUEST_COUNTER.reset(token)


class ApiClient:
    TOKEN_REFRESH_SECONDS = 60.0

    def __init__(self, config: Optional[Config] = None):
        self.config = config or Config.from_env()
        self._session: Optional[aiohttp.ClientSession] = None
        self._token_checked_at = 0.0
        self._pending_closes: set[asyncio.Task] = set()

    async def __aenter__(self) -> "ApiClient":
        await self.session()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    def _maybe_refresh_token(self) -> None:
        """Pick up rotated bound service-account tokens (client-go behaviour)."""
        if not self.config.token_file:
            return
        now = time.monotonic()
        if now - self._token_checked_at < self.TOKEN_REFRESH_SECONDS:
            return
        self._token_checked_at = now
        try:
            with open(self.config.token_file) as f:
                token = f.read().strip()
        except OSError:
            return
        if token and token != self.config.token:
            self.config.token = token
            if self._session and not self._session.closed:
                # rebuild the session so the new Authorization header applies;
                # hold a strong ref to the close task or it may be GC'd unrun
                task = asyncio.get_running_loop().create_task(self._session.close())
                self._pending_closes.add(task)
                task.add_done_callback(self._pending_closes.discard)
                self._session = None

    async def session(self) -> aiohttp.ClientSession:
        self._maybe_refresh_token()
        if self._session is None or self._session.closed:
            headers = {"Accept": "application/json"}
            if self.config.token:
                headers["Authorization"] = f"Bearer {self.config.token}"
            ssl_ctx: Any = None
            if self.config.base_url.startswith("https"):
                if self.config.ca_file:
                    ssl_ctx = ssl.create_default_context(cafile=self.config.ca_file)
                elif not self.config.verify_ssl:
                    ssl_ctx = False
            connector = aiohttp.TCPConnector(ssl=ssl_ctx) if ssl_ctx is not None else None
            self._session = aiohttp.ClientSession(
                base_url=self.config.base_url,
                headers=headers,
                connector=connector,
            )
        return self._session

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()
        self._session = None

    # ------------------------------------------------------------------
    async def _request(
        self,
        method: str,
        path: str,
        *,
        params: Optional[dict] = None,
        body: Any = None,
        content_type: str = "application/json",
    ) -> Any:
        sess = await self.session()
        counter = _REQUEST_COUNTER.get()
        if counter is not None:
            counter.n += 1
        data = None
        headers = {}
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = content_type
        # no-op unless a tracer is ambient (reconcile pass / activated CLI);
        # feeds k8s_request_duration_seconds{verb} and the span tree
        error: Optional[ApiError] = None
        with trace.span(
            f"k8s/{method}", kind=trace.KIND_K8S, verb=method, path=path
        ) as sp:
            async with sess.request(
                method, path, params=params, data=data, headers=headers
            ) as resp:
                text = await resp.text()
                payload: Any = None
                if text:
                    try:
                        payload = json.loads(text)
                    except json.JSONDecodeError:
                        payload = text
                if sp is not None:
                    sp.attrs["status"] = resp.status
                if resp.status >= 400:
                    reason = payload.get("reason", resp.reason) if isinstance(payload, dict) else str(resp.reason)
                    # raised OUTSIDE the span so routine control-flow 4xx
                    # (get-before-create 404s, status conflicts) don't
                    # error-flag healthy traces; server-side 5xx is a real
                    # failure worth surfacing in /debug/traces
                    error = ApiError(resp.status, str(reason), payload)
                    if sp is not None and resp.status >= 500:
                        sp.error = f"ApiError: {error}"
        if error is not None:
            raise error
        return payload

    # ------------------------------------------------------------------
    # Typed-by-kind convenience API. All objects are plain dicts
    # ("unstructured") with apiVersion/kind/metadata.

    async def get_version(self) -> str:
        """Server version string (overridden with a TTL memo by CachedReader;
        the version of a running control plane effectively never changes)."""
        info = await self._request("GET", "/version")
        return info.get("gitVersion", "") if isinstance(info, dict) else ""

    async def get(self, group: str, kind: str, name: str, namespace: Optional[str] = None) -> dict:
        info = obj_api.lookup(group, kind)
        path = obj_api.resource_path(
            info.gvk.group, info.gvk.version, info.plural, info.namespaced, namespace, name
        )
        return await self._request("GET", path)

    @staticmethod
    def _collection_path(info: obj_api.ResourceInfo, namespace: Optional[str]) -> str:
        """Collection URL; namespaced kinds with no namespace → all-namespaces."""
        if info.namespaced and namespace is None:
            return obj_api.resource_path(info.gvk.group, info.gvk.version, info.plural, False)
        ns = namespace if info.namespaced else None
        return obj_api.resource_path(info.gvk.group, info.gvk.version, info.plural, info.namespaced, ns)

    async def list(
        self,
        group: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
    ) -> dict:
        info = obj_api.lookup(group, kind)
        path = self._collection_path(info, namespace)
        params = {}
        if label_selector:
            params["labelSelector"] = label_selector
        if field_selector:
            params["fieldSelector"] = field_selector
        return await self._request("GET", path, params=params)

    async def list_items(self, *args, **kwargs) -> list[dict]:
        return (await self.list(*args, **kwargs)).get("items", [])

    async def create(self, obj: dict) -> dict:
        info = obj_api.info_of(obj)
        meta = obj.get("metadata", {})
        path = obj_api.resource_path(
            info.gvk.group, info.gvk.version, info.plural, info.namespaced, meta.get("namespace")
        )
        return await self._request("POST", path, body=obj)

    async def update(self, obj: dict) -> dict:
        return await self._request("PUT", obj_api.object_path(obj), body=obj)

    async def update_status(self, obj: dict) -> dict:
        return await self._request("PUT", obj_api.object_path(obj, "status"), body=obj)

    async def patch(
        self, group: str, kind: str, name: str, patch: Any,
        namespace: Optional[str] = None,
        patch_type: str = "application/merge-patch+json",
        subresource: Optional[str] = None,
    ) -> dict:
        info = obj_api.lookup(group, kind)
        path = obj_api.resource_path(
            info.gvk.group, info.gvk.version, info.plural, info.namespaced, namespace, name, subresource
        )
        return await self._request("PATCH", path, body=patch, content_type=patch_type)

    async def delete(
        self, group: str, kind: str, name: str, namespace: Optional[str] = None,
        ignore_not_found: bool = True,
    ) -> Optional[dict]:
        info = obj_api.lookup(group, kind)
        path = obj_api.resource_path(
            info.gvk.group, info.gvk.version, info.plural, info.namespaced, namespace, name
        )
        try:
            return await self._request("DELETE", path)
        except ApiError as e:
            if e.not_found and ignore_not_found:
                return None
            raise

    async def delete_collection(
        self, group: str, kind: str, namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
    ) -> None:
        # items of one collection are independent; bounded fan-out
        await bounded_gather(
            (
                self.delete(
                    group, kind,
                    item.get("metadata", {})["name"],
                    item.get("metadata", {}).get("namespace"),
                )
                for item in await self.list_items(group, kind, namespace, label_selector)
            ),
            limit=consts.DELETE_CONCURRENCY,
        )

    # ------------------------------------------------------------------
    async def watch(
        self,
        group: str,
        kind: str,
        namespace: Optional[str] = None,
        resource_version: Optional[str] = None,
        label_selector: Optional[str] = None,
        timeout_seconds: Optional[float] = None,
    ) -> AsyncIterator[WatchEvent]:
        """Single watch stream; see Informer for resumable cached watches."""
        info = obj_api.lookup(group, kind)
        path = self._collection_path(info, namespace)
        params: dict[str, str] = {"watch": "1", "allowWatchBookmarks": "true"}
        if resource_version:
            params["resourceVersion"] = resource_version
        if label_selector:
            params["labelSelector"] = label_selector
        sess = await self.session()
        timeout = aiohttp.ClientTimeout(total=timeout_seconds, sock_read=timeout_seconds)
        async with sess.get(path, params=params, timeout=timeout) as resp:
            if resp.status >= 400:
                raise ApiError(resp.status, str(resp.reason))
            buf = b""
            async for chunk in resp.content.iter_any():
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    line = line.strip()
                    if not line:
                        continue
                    evt = json.loads(line)
                    yield WatchEvent(evt["type"], evt.get("object", {}))
