"""Async Kubernetes REST client (CRUD + status + list + watch).

Fills the role controller-runtime's client plays for the reference
(controllers use Get/List/Create/Update/Delete + watches).  Speaks plain
HTTPS/JSON to the API server: in-cluster config from the service-account
token, kubeconfig-less by design (the operator always runs in a pod; tests
point it at the in-process fake apiserver via ``Config(base_url=...)``).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import ssl
import time
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Iterator, Optional

import aiohttp

from tpu_operator import consts
from tpu_operator.k8s import objects as obj_api
from tpu_operator.k8s import retry as retry_api
from tpu_operator.obs import trace
from tpu_operator.utils import bounded_gather

log = logging.getLogger("tpu_operator.k8s")

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


@dataclass
class Config:
    base_url: str
    token: Optional[str] = None
    token_file: Optional[str] = None  # re-read periodically (bound SA tokens rotate ~1h)
    ca_file: Optional[str] = None
    verify_ssl: bool = True

    @classmethod
    def in_cluster(cls) -> "Config":
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        token_path = os.path.join(SERVICE_ACCOUNT_DIR, "token")
        ca_path = os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
        token = None
        if os.path.exists(token_path):
            with open(token_path) as f:
                token = f.read().strip()
        return cls(
            base_url=f"https://{host}:{port}",
            token=token,
            token_file=token_path if os.path.exists(token_path) else None,
            ca_file=ca_path if os.path.exists(ca_path) else None,
        )

    @classmethod
    def from_env(cls) -> "Config":
        """KUBERNETES_API_URL override (tests / out-of-cluster), else in-cluster."""
        url = os.environ.get("KUBERNETES_API_URL")
        if url:
            return cls(base_url=url, token=os.environ.get("KUBERNETES_API_TOKEN"))
        return cls.in_cluster()


class ApiError(Exception):
    def __init__(self, status: int, reason: str, body: Any = None,
                 retry_after: Optional[float] = None):
        self.status = status
        self.reason = reason
        self.body = body
        # parsed Retry-After (seconds) from a 429/503, honored by RetryPolicy
        self.retry_after = retry_after
        super().__init__(f"{status} {reason}")

    @property
    def not_found(self) -> bool:
        return self.status == 404

    # A 409 is two distinct situations the apiserver distinguishes by reason:
    # an optimistic-concurrency resourceVersion conflict ("Conflict") vs a
    # get-before-create race lost to another writer ("AlreadyExists").  The
    # recovery differs — conflict re-reads and retries, already-exists adopts
    # the existing object — so the predicates must not alias.
    @property
    def conflict(self) -> bool:
        return self.status == 409 and self.reason != "AlreadyExists"

    @property
    def already_exists(self) -> bool:
        return self.status == 409 and self.reason == "AlreadyExists"


class BreakerOpenError(ApiError):
    """Failed fast client-side: the circuit breaker is OPEN.

    An ApiError subclass (status 503) so existing taxonomy — workqueue
    backoff on reconcile failure, informer transient handling, best-effort
    Event dropping — applies without new call-site cases."""

    def __init__(self, path: str = ""):
        super().__init__(503, "CircuitBreakerOpen",
                         f"api circuit breaker open; failing fast ({path})")


def _parse_retry_after(value: Optional[str]) -> Optional[float]:
    """Seconds form of Retry-After only (the apiserver emits integers;
    HTTP-date form is not worth a date parser here)."""
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        return None


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED | BOOKMARK | ERROR
    object: dict


class RequestCounter:
    """Mutable per-context API-request tally (see ``count_api_requests``)."""

    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0


# Ambient request tally: a reconcile pass installs a counter here and every
# ApiClient._request within that task tree (child tasks copy the context and
# share the same counter object) increments it — informer background watches
# run outside the pass's context and are excluded by construction.  Feeds
# tpu_operator_k8s_requests_per_reconcile.
_REQUEST_COUNTER: ContextVar[Optional[RequestCounter]] = ContextVar(
    "tpu_operator_k8s_request_counter", default=None
)


@contextlib.contextmanager
def count_api_requests() -> Iterator[RequestCounter]:
    counter = RequestCounter()
    token = _REQUEST_COUNTER.set(counter)
    try:
        yield counter
    finally:
        _REQUEST_COUNTER.reset(token)


# Per-task RetryPolicy override (flows through the task tree like the request
# counter).  The leader elector uses it to cap each lease call well inside its
# renew deadline — a hung renew must surface before step-down, not after the
# client-wide 60s total budget.
_REQUEST_POLICY: ContextVar[Optional["retry_api.RetryPolicy"]] = ContextVar(
    "tpu_operator_k8s_request_policy", default=None
)


@contextlib.contextmanager
def request_policy(policy: retry_api.RetryPolicy) -> Iterator[None]:
    token = _REQUEST_POLICY.set(policy)
    try:
        yield
    finally:
        _REQUEST_POLICY.reset(token)


# Ambient (per-task) write fence, checked AFTER the client-wide leader fence.
# The sharded reconcile plane installs one per shard reconcile: mutating
# verbs are refused the instant the hash ring reassigns the key to another
# shard, so a handoff can never double-actuate a drain or duplicate a create
# (k8s/sharding.py; docs/PERFORMANCE.md "Delta reconcile & sharding").
_REQUEST_FENCE: ContextVar[Optional["retry_api.WriteFence"]] = ContextVar(
    "tpu_operator_k8s_request_fence", default=None
)


@contextlib.contextmanager
def request_fence(fence: retry_api.WriteFence) -> Iterator[None]:
    token = _REQUEST_FENCE.set(fence)
    try:
        yield
    finally:
        _REQUEST_FENCE.reset(token)


class ApiClient:
    TOKEN_REFRESH_SECONDS = 60.0

    def __init__(
        self,
        config: Optional[Config] = None,
        retry_policy: Optional[retry_api.RetryPolicy] = None,
        breaker: Optional[retry_api.CircuitBreaker] = None,
    ):
        self.config = config or Config.from_env()
        self._session: Optional[aiohttp.ClientSession] = None
        self._token_checked_at = 0.0
        self._pending_closes: set[asyncio.Task] = set()
        # resilience envelope (k8s/retry.py): every non-watch request runs
        # under a per-try timeout + bounded retries; the shared budget stops
        # retry storms; the breaker flips the manager into degraded mode
        self.retry_policy = retry_policy or retry_api.RetryPolicy(
            budget=retry_api.RetryBudget(ratio=consts.K8S_RETRY_BUDGET_RATIO)
        )
        self.breaker = breaker if breaker is not None else retry_api.CircuitBreaker()
        # installed by the manager under leader election; checked per request
        self.fence: Optional[retry_api.WriteFence] = None
        # OperatorMetrics for k8s_request_retries_total (wired by whoever
        # owns both, e.g. ClusterPolicyReconciler / the operator binary)
        self.metrics: Optional[Any] = None

    async def __aenter__(self) -> "ApiClient":
        await self.session()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    def _maybe_refresh_token(self) -> None:
        """Pick up rotated bound service-account tokens (client-go behaviour)."""
        if not self.config.token_file:
            return
        now = time.monotonic()
        if now - self._token_checked_at < self.TOKEN_REFRESH_SECONDS:
            return
        self._token_checked_at = now
        try:
            with open(self.config.token_file) as f:
                token = f.read().strip()
        except OSError:
            return
        if token and token != self.config.token:
            self.config.token = token
            if self._session and not self._session.closed:
                # rebuild the session so the new Authorization header applies;
                # hold a strong ref to the close task or it may be GC'd unrun
                task = asyncio.get_running_loop().create_task(self._session.close())
                self._pending_closes.add(task)
                task.add_done_callback(self._pending_closes.discard)
                self._session = None

    async def session(self) -> aiohttp.ClientSession:
        self._maybe_refresh_token()
        if self._session is None or self._session.closed:
            headers = {"Accept": "application/json"}
            if self.config.token:
                headers["Authorization"] = f"Bearer {self.config.token}"
            ssl_ctx: Any = None
            if self.config.base_url.startswith("https"):
                if self.config.ca_file:
                    ssl_ctx = ssl.create_default_context(cafile=self.config.ca_file)
                elif not self.config.verify_ssl:
                    ssl_ctx = False
            connector = aiohttp.TCPConnector(ssl=ssl_ctx) if ssl_ctx is not None else None
            self._session = aiohttp.ClientSession(
                base_url=self.config.base_url,
                headers=headers,
                connector=connector,
            )
        return self._session

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()
        self._session = None

    # ------------------------------------------------------------------
    async def _request(
        self,
        method: str,
        path: str,
        *,
        params: Optional[dict] = None,
        body: Any = None,
        content_type: str = "application/json",
    ) -> Any:
        """One logical request = bounded attempts under a RetryPolicy.

        Fence first (a deposed leader must not mutate), breaker second (an
        open breaker fails fast without touching the wire), then attempts
        with full-jitter backoff between them.  Non-idempotent verbs (POST)
        are never replayed after an ambiguous failure — the apply layer's
        get/adopt path recovers instead of risking duplicate side effects.
        """
        # The ambient (per-task) shard fence, when installed, REPLACES the
        # client-wide leader fence for this request: a shard reconcile's
        # authority is its shard Lease, not the manager's global lease — a
        # replica that is not the global leader must still write for the
        # shards it holds (multi-replica sharded plane), and the in-process
        # plane's fence predicate folds the manager's leadership back in
        # via NodePlane.write_gate, so no path weakens.
        ambient_fence = _REQUEST_FENCE.get()
        if ambient_fence is not None:
            ambient_fence.check(method, path)
        elif self.fence is not None:
            self.fence.check(method, path)
        policy = _REQUEST_POLICY.get() or self.retry_policy
        deadline = (
            time.monotonic() + policy.total_timeout
            if policy.total_timeout is not None
            else None
        )
        data = None
        headers = {}
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = content_type

        attempt = 0
        while True:
            attempt += 1
            if self.breaker is not None and not self.breaker.allow():
                raise BreakerOpenError(path)
            try:
                return await self._attempt(method, path, params, data, headers, policy)
            except asyncio.CancelledError:
                # the task died without a verdict — never leave a half-open
                # probe slot held, or the breaker wedges permanently
                if self.breaker is not None:
                    self.breaker.release_probe()
                raise
            except ApiError as e:
                if self.breaker is not None:
                    # only 5xx counts toward tripping the breaker; other 4xx
                    # proves the server alive and parsing; 429 is NEUTRAL —
                    # a throttling server must not close the breaker from
                    # half-open nor break a 500,429,500 failure streak
                    if e.status >= 500:
                        self.breaker.record_failure()
                    elif e.status == 429:
                        self.breaker.record_neutral()
                    else:
                        self.breaker.record_success()
                if not (e.status >= 500 or e.status == 429):
                    raise  # logical outcome (404/409/422/...): caller's business
                if not self._may_retry(policy, method, e.status, attempt, deadline):
                    raise
                delay = policy.backoff(attempt, retry_after=e.retry_after)
            except (aiohttp.ClientError, OSError, asyncio.TimeoutError):
                # transport-level: connection refused/reset, hung socket
                if self.breaker is not None:
                    self.breaker.record_failure()
                if not self._may_retry(policy, method, None, attempt, deadline):
                    raise
                delay = policy.backoff(attempt)
            if deadline is not None:
                delay = min(delay, max(0.0, deadline - time.monotonic()))
            if self.metrics is not None:
                self.metrics.k8s_request_retries_total.labels(verb=method).inc()
            log.debug("retrying %s %s (attempt %d) in %.3fs", method, path, attempt, delay)
            await asyncio.sleep(delay)

    def _may_retry(
        self,
        policy: retry_api.RetryPolicy,
        method: str,
        status: Optional[int],
        attempt: int,
        deadline: Optional[float],
    ) -> bool:
        if attempt >= policy.max_attempts:
            return False
        if deadline is not None and time.monotonic() >= deadline:
            return False
        if not policy.retryable_verb(method, status):
            return False
        return policy.budget is None or policy.budget.allow_retry()

    async def _attempt(
        self,
        method: str,
        path: str,
        params: Optional[dict],
        data: Optional[bytes],
        headers: dict,
        policy: retry_api.RetryPolicy,
    ) -> Any:
        sess = await self.session()
        counter = _REQUEST_COUNTER.get()
        if counter is not None:
            counter.n += 1
        if policy.budget is not None:
            policy.budget.record_request()
        # an explicit timeout=None would DISABLE aiohttp's session default
        # (not inherit it) — only pass the kwarg when the policy sets one
        timeout_kw: dict = {}
        if policy.per_try_timeout is not None:
            timeout_kw["timeout"] = aiohttp.ClientTimeout(total=policy.per_try_timeout)
        # no-op unless a tracer is ambient (reconcile pass / activated CLI);
        # feeds k8s_request_duration_seconds{verb} and the span tree —
        # one span per attempt so retries are visible in /debug/traces
        error: Optional[ApiError] = None
        with trace.span(
            f"k8s/{method}", kind=trace.KIND_K8S, verb=method, path=path
        ) as sp:
            async with sess.request(
                method, path, params=params, data=data, headers=headers,
                **timeout_kw,
            ) as resp:
                text = await resp.text()
                payload: Any = None
                if text:
                    try:
                        payload = json.loads(text)
                    except json.JSONDecodeError:
                        payload = text
                if sp is not None:
                    sp.attrs["status"] = resp.status
                if resp.status >= 400:
                    reason = payload.get("reason", resp.reason) if isinstance(payload, dict) else str(resp.reason)
                    # raised OUTSIDE the span so routine control-flow 4xx
                    # (get-before-create 404s, status conflicts) don't
                    # error-flag healthy traces; server-side 5xx is a real
                    # failure worth surfacing in /debug/traces
                    error = ApiError(
                        resp.status, str(reason), payload,
                        retry_after=_parse_retry_after(resp.headers.get("Retry-After")),
                    )
                    if sp is not None and resp.status >= 500:
                        sp.error = f"ApiError: {error}"
        if error is not None:
            raise error
        if self.breaker is not None:
            self.breaker.record_success()
        return payload

    # ------------------------------------------------------------------
    # Typed-by-kind convenience API. All objects are plain dicts
    # ("unstructured") with apiVersion/kind/metadata.

    async def get_version(self) -> str:
        """Server version string (overridden with a TTL memo by CachedReader;
        the version of a running control plane effectively never changes)."""
        info = await self._request("GET", "/version")
        return info.get("gitVersion", "") if isinstance(info, dict) else ""

    async def get(self, group: str, kind: str, name: str, namespace: Optional[str] = None) -> dict:
        info = obj_api.lookup(group, kind)
        path = obj_api.resource_path(
            info.gvk.group, info.gvk.version, info.plural, info.namespaced, namespace, name
        )
        return await self._request("GET", path)

    @staticmethod
    def _collection_path(info: obj_api.ResourceInfo, namespace: Optional[str]) -> str:
        """Collection URL; namespaced kinds with no namespace → all-namespaces."""
        if info.namespaced and namespace is None:
            return obj_api.resource_path(info.gvk.group, info.gvk.version, info.plural, False)
        ns = namespace if info.namespaced else None
        return obj_api.resource_path(info.gvk.group, info.gvk.version, info.plural, info.namespaced, ns)

    async def list(
        self,
        group: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
        limit: Optional[int] = None,
        continue_token: Optional[str] = None,
    ) -> dict:
        """One LIST page.  ``limit``/``continue_token`` are the apiserver
        chunking protocol: a limited response carries ``metadata.continue``
        when more items remain; resuming with an expired token gets a 410
        ``Expired`` and the caller must relist from scratch (the informer's
        410 taxonomy already does exactly that)."""
        info = obj_api.lookup(group, kind)
        path = self._collection_path(info, namespace)
        params = {}
        if label_selector:
            params["labelSelector"] = label_selector
        if field_selector:
            params["fieldSelector"] = field_selector
        if limit is not None:
            params["limit"] = str(limit)
        if continue_token:
            params["continue"] = continue_token
        return await self._request("GET", path, params=params)

    async def iter_pages(
        self,
        group: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        page_size: int = consts.LIST_PAGE_SIZE,
    ) -> AsyncIterator[dict]:
        """Chunked listing as an async page stream: consumers that only
        need to SEE each item (the sharded plane's intake sweeps, lean
        informer relists) process one ``limit``-sized page at a time
        instead of materializing the fleet — at 100k nodes the assembled
        listing alone is hundreds of MB per consumer, the exact spike the
        partitioned-RSS bound forbids.  A mid-pagination 410 (continue
        token expired) propagates; relist-from-scratch is the protocol
        answer."""
        continue_token: Optional[str] = None
        while True:
            page = await self.list(
                group, kind, namespace, label_selector,
                limit=page_size, continue_token=continue_token,
            )
            yield page
            continue_token = (page.get("metadata") or {}).get("continue")
            if not continue_token:
                return

    async def list_paged(
        self,
        group: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        page_size: int = consts.LIST_PAGE_SIZE,
    ) -> dict:
        """Full listing assembled from ``limit``-sized pages so a 10k-node
        relist never materializes one giant response on the apiserver.  The
        returned dict mimics a single List (items + the FINAL page's
        resourceVersion — on a real apiserver every chunk is served at the
        first page's snapshot rv, so any page's rv is the listing's rv).
        Prefer :meth:`iter_pages` when items are processed-and-dropped."""
        items: list[dict] = []
        page: dict = {}
        async for page in self.iter_pages(
            group, kind, namespace, label_selector, page_size
        ):
            items.extend(page.get("items", []))
        page["items"] = items
        return page

    async def list_items(self, *args, **kwargs) -> list[dict]:
        return (await self.list(*args, **kwargs)).get("items", [])

    async def create(self, obj: dict) -> dict:
        info = obj_api.info_of(obj)
        meta = obj.get("metadata", {})
        path = obj_api.resource_path(
            info.gvk.group, info.gvk.version, info.plural, info.namespaced, meta.get("namespace")
        )
        return await self._request("POST", path, body=obj)

    async def update(self, obj: dict) -> dict:
        return await self._request("PUT", obj_api.object_path(obj), body=obj)

    async def update_status(self, obj: dict) -> dict:
        return await self._request("PUT", obj_api.object_path(obj, "status"), body=obj)

    async def patch(
        self, group: str, kind: str, name: str, patch: Any,
        namespace: Optional[str] = None,
        patch_type: str = "application/merge-patch+json",
        subresource: Optional[str] = None,
    ) -> dict:
        info = obj_api.lookup(group, kind)
        path = obj_api.resource_path(
            info.gvk.group, info.gvk.version, info.plural, info.namespaced, namespace, name, subresource
        )
        return await self._request("PATCH", path, body=patch, content_type=patch_type)

    async def delete(
        self, group: str, kind: str, name: str, namespace: Optional[str] = None,
        ignore_not_found: bool = True,
        grace_period_seconds: Optional[int] = None,
    ) -> Optional[dict]:
        info = obj_api.lookup(group, kind)
        path = obj_api.resource_path(
            info.gvk.group, info.gvk.version, info.plural, info.namespaced, namespace, name
        )
        # DeleteOptions subset: None keeps the object's own grace (the
        # apiserver default); 0 is an immediate delete
        params = (
            {"gracePeriodSeconds": str(grace_period_seconds)}
            if grace_period_seconds is not None
            else None
        )
        try:
            return await self._request("DELETE", path, params=params)
        except ApiError as e:
            if e.not_found and ignore_not_found:
                return None
            raise

    async def delete_collection(
        self, group: str, kind: str, namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
    ) -> None:
        # items of one collection are independent; bounded fan-out
        await bounded_gather(
            (
                self.delete(
                    group, kind,
                    item.get("metadata", {})["name"],
                    item.get("metadata", {}).get("namespace"),
                )
                for item in await self.list_items(group, kind, namespace, label_selector)
            ),
            limit=consts.DELETE_CONCURRENCY,
        )

    # ------------------------------------------------------------------
    async def watch(
        self,
        group: str,
        kind: str,
        namespace: Optional[str] = None,
        resource_version: Optional[str] = None,
        label_selector: Optional[str] = None,
        timeout_seconds: Optional[float] = None,
    ) -> AsyncIterator[WatchEvent]:
        """Single watch stream; see Informer for resumable cached watches."""
        info = obj_api.lookup(group, kind)
        path = self._collection_path(info, namespace)
        params: dict[str, str] = {"watch": "1", "allowWatchBookmarks": "true"}
        if resource_version:
            params["resourceVersion"] = resource_version
        if label_selector:
            params["labelSelector"] = label_selector
        sess = await self.session()
        timeout = aiohttp.ClientTimeout(total=timeout_seconds, sock_read=timeout_seconds)
        async with sess.get(path, params=params, timeout=timeout) as resp:
            if resp.status >= 400:
                raise ApiError(resp.status, str(resp.reason))
            buf = b""
            async for chunk in resp.content.iter_any():
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    line = line.strip()
                    if not line:
                        continue
                    evt = json.loads(line)
                    yield WatchEvent(evt["type"], evt.get("object", {}))
