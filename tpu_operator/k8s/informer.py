"""List+watch informer with local cache and event handlers.

Controller-runtime cache analogue: reconnects with resourceVersion resume and
feeds controller workqueues (see tpu_operator.controllers.manager).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Awaitable, Callable, Optional

from tpu_operator.k8s import objects as obj_api
from tpu_operator.k8s.client import ApiClient, ApiError

log = logging.getLogger("tpu_operator.k8s.informer")

Handler = Callable[[str, dict], Awaitable[None]]  # (event_type, object)

# An API that answers 404/405 is not served in this cluster (e.g.
# ServiceMonitor without prometheus-operator).  Poll for it appearing
# (CRD installed later) at CRD-install cadence, not at the hot relist cap.
ABSENT_API_RETRY_SECONDS = 300.0


class Informer:
    def __init__(
        self,
        client: ApiClient,
        group: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        resync_seconds: float = 600.0,
        required: bool = True,
    ):
        self.client = client
        self.group = group
        self.kind = kind
        self.namespace = namespace
        self.label_selector = label_selector
        self.resync_seconds = resync_seconds
        # required informers gate manager start/readyz; optional ones back
        # the CachedReader opportunistically — a kind whose API is absent
        # (ServiceMonitor without prometheus-operator) must neither hang
        # startup nor wedge readiness, reads just stay live until synced
        self.required = required
        self.cache: dict[tuple[str, str], dict] = {}
        self.handlers: list[Handler] = []
        self._task: Optional[asyncio.Task] = None
        self.synced = asyncio.Event()

    def add_handler(self, handler: Handler) -> None:
        self.handlers.append(handler)

    def _stamp(self, item: dict) -> dict:
        """LIST responses omit per-item TypeMeta on a real apiserver (it
        lives on the List object); cache consumers — readiness checks,
        update_status path building — need it, so stamp at ingest exactly
        like the live-list path in state/skel.py does."""
        item.setdefault("kind", self.kind)
        try:
            item.setdefault("apiVersion", obj_api.lookup(self.group, self.kind).gvk.api_version)
        except KeyError:
            pass
        return item

    def get(self, name: str, namespace: str = "") -> Optional[dict]:
        return self.cache.get((namespace, name))

    def items(self) -> list[dict]:
        return list(self.cache.values())

    async def start(self, wait: bool = True) -> None:
        self._task = asyncio.create_task(self._run(), name=f"informer-{self.kind}")
        if wait:
            await self.synced.wait()

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass

    async def _dispatch(self, event_type: str, obj: dict) -> None:
        for handler in self.handlers:
            try:
                await handler(event_type, obj)
            except Exception:  # noqa: BLE001
                log.exception("informer handler failed for %s %s", self.kind, event_type)

    async def _run(self) -> None:
        backoff = 0.05
        while True:
            watch_started = 0.0
            try:
                listing = await self.client.list(
                    self.group, self.kind, self.namespace, self.label_selector
                )
                rv = listing.get("metadata", {}).get("resourceVersion")
                fresh: dict[tuple[str, str], dict] = {}
                for item in listing.get("items", []):
                    meta = item.get("metadata", {})
                    fresh[(meta.get("namespace", ""), meta["name"])] = self._stamp(item)
                # diff against cache → synthetic events; keep the cache
                # consistent with each event *before* handlers observe it
                for key, item in fresh.items():
                    old = self.cache.get(key)
                    if old is None:
                        self.cache[key] = item
                        await self._dispatch("ADDED", item)
                    elif old.get("metadata", {}).get("resourceVersion") != item["metadata"].get("resourceVersion"):
                        self.cache[key] = item
                        await self._dispatch("MODIFIED", item)
                for key, old in list(self.cache.items()):
                    if key not in fresh:
                        del self.cache[key]
                        await self._dispatch("DELETED", old)
                self.synced.set()
                watch_started = time.monotonic()
                async for evt in self.client.watch(
                    self.group,
                    self.kind,
                    self.namespace,
                    resource_version=rv,
                    label_selector=self.label_selector,
                    timeout_seconds=self.resync_seconds,
                ):
                    if evt.type == "BOOKMARK":
                        continue
                    if evt.type == "ERROR":
                        break
                    meta = evt.object.get("metadata", {})
                    key = (meta.get("namespace", ""), meta.get("name", ""))
                    if evt.type == "DELETED":
                        self.cache.pop(key, None)
                    else:
                        self.cache[key] = self._stamp(evt.object)
                    await self._dispatch(evt.type, evt.object)
            except asyncio.CancelledError:
                raise
            except (ApiError, OSError, asyncio.TimeoutError, Exception) as e:  # noqa: BLE001
                log.debug("informer %s stream reset; relisting", self.kind, exc_info=True)
                # only optional informers slow-poll an unserved API; a
                # required one hitting the operator-install CRD race must
                # keep the fast backoff or manager start stalls for minutes
                if isinstance(e, ApiError) and e.status in (404, 405) and not self.required:
                    await asyncio.sleep(ABSENT_API_RETRY_SECONDS)
                    continue
            # Only treat the cycle as healthy (reset backoff) if the watch ran
            # for a while; a watch that dies instantly (e.g. RBAC 403) must
            # keep backing off or we relist-hammer the apiserver.
            if watch_started and time.monotonic() - watch_started >= 1.0:
                backoff = 0.05
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 5.0)
