"""List+watch informer with local cache and event handlers.

Controller-runtime cache analogue: reconnects with resourceVersion resume and
feeds controller workqueues (see tpu_operator.controllers.manager).
"""

from __future__ import annotations

import asyncio
import logging
import sys
import time
from typing import Awaitable, Callable, Optional

import aiohttp

from tpu_operator import consts
from tpu_operator.k8s import objects as obj_api
from tpu_operator.k8s.client import ApiClient, ApiError

log = logging.getLogger("tpu_operator.k8s.informer")

Handler = Callable[[str, dict], Awaitable[None]]  # (event_type, object)

# An API that answers 404/405 is not served in this cluster (e.g.
# ServiceMonitor without prometheus-operator).  Poll for it appearing
# (CRD installed later) at CRD-install cadence, not at the hot relist cap.
ABSENT_API_RETRY_SECONDS = 300.0


class Informer:
    def __init__(
        self,
        client: ApiClient,
        group: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        resync_seconds: float = 600.0,
        required: bool = True,
        page_size: Optional[int] = None,
        cache_objects: bool = True,
    ):
        self.client = client
        self.group = group
        self.kind = kind
        self.namespace = namespace
        self.label_selector = label_selector
        self.resync_seconds = resync_seconds
        # cache_objects=False = event tap: handlers fire but nothing is
        # retained (every relist re-dispatches ADDED for all items).  The
        # sharded plane's intake watch (`!shard` — nodes not yet stamped
        # into an arc) uses this: during a 100k-node mass join EVERY
        # replica sees every unstamped node, and caching them would give
        # each replica a transient full-fleet RSS spike — the exact thing
        # partitioned views exist to prevent.
        self.cache_objects = cache_objects
        # LIST chunk size for relists (None -> consts.LIST_PAGE_SIZE);
        # injectable so tests can force multi-page relists on small fleets
        self.page_size = page_size
        # required informers gate manager start/readyz; optional ones back
        # the CachedReader opportunistically — a kind whose API is absent
        # (ServiceMonitor without prometheus-operator) must neither hang
        # startup nor wedge readiness, reads just stay live until synced
        self.required = required
        self.cache: dict[tuple[str, str], dict] = {}
        self.handlers: list[Handler] = []
        self._task: Optional[asyncio.Task] = None
        self.synced = asyncio.Event()

    def add_handler(self, handler: Handler) -> None:
        self.handlers.append(handler)

    @staticmethod
    def _intern_strings(obj):
        """Dedup the strings a cached object is made of: every node in a
        25k-node arc repeats the same ~25 label keys (and most values —
        "true", counts, pool names), and ``json.loads`` materializes a
        fresh str per occurrence.  Interning at ingest collapses them to
        one instance each, cutting tens of MB per replica at fleet scale
        (the partitioned-views RSS bound is measured against this cache)."""
        if isinstance(obj, dict):
            return {
                (sys.intern(k) if type(k) is str else k):
                    Informer._intern_strings(v)
                for k, v in obj.items()
            }
        if isinstance(obj, list):
            return [Informer._intern_strings(x) for x in obj]
        if type(obj) is str and len(obj) <= 64:
            return sys.intern(obj)
        return obj

    def _stamp(self, item: dict) -> dict:
        """LIST responses omit per-item TypeMeta on a real apiserver (it
        lives on the List object); cache consumers — readiness checks,
        update_status path building — need it, so stamp at ingest exactly
        like the live-list path in state/skel.py does.  Cached ingest also
        string-interns the object (see _intern_strings)."""
        if self.cache_objects:
            item = self._intern_strings(item)
        item.setdefault("kind", self.kind)
        try:
            item.setdefault("apiVersion", obj_api.lookup(self.group, self.kind).gvk.api_version)
        except KeyError:
            pass
        return item

    def get(self, name: str, namespace: str = "") -> Optional[dict]:
        return self.cache.get((namespace, name))

    def items(self) -> list[dict]:
        return list(self.cache.values())

    async def start(self, wait: bool = True) -> None:
        self._task = asyncio.create_task(self._run(), name=f"informer-{self.kind}")
        if wait:
            await self.synced.wait()

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:  # noqa: BLE001
                log.debug("informer %s task errored during stop", self.kind, exc_info=True)

    async def _dispatch(self, event_type: str, obj: dict) -> None:
        for handler in self.handlers:
            try:
                await handler(event_type, obj)
            except Exception:  # noqa: BLE001
                log.exception("informer handler failed for %s %s", self.kind, event_type)

    async def _run(self) -> None:
        """List+watch forever, with an explicit failure taxonomy:

        - ``410 Gone`` (watch window expired — as an ERROR event mid-stream
          or a status on the watch GET) is PROTOCOL, not failure: relist
          immediately with a fresh resourceVersion, no backoff (client-go
          reflector semantics).  Consecutive 410s still yield briefly so a
          chaos-saturated apiserver isn't relist-hammered in a hot loop.
        - transient errors (API 5xx/429, connection resets, timeouts) back
          off exponentially; an unserved API (404/405) on an OPTIONAL
          informer slow-polls at CRD-install cadence.
        - anything else is a bug worth a loud log, but the informer keeps
          running — a watch loop that dies silently starves every
          controller fed by it.
        """
        backoff = 0.05
        consecutive_gone = 0
        while True:
            watch_started = 0.0
            served = False  # did this cycle's watch deliver anything?
            try:
                # paginated relist (limit/continue): a 10k-object listing
                # streams in LIST_PAGE_SIZE chunks; a continue token that
                # expires mid-pagination surfaces as a 410, handled below by
                # the same relist-from-scratch branch as a watch expiry.
                # Pages are consumed AS A STREAM — an event-tap informer
                # (cache_objects=False) dispatches each page and drops it,
                # so a 100k-object relist never materializes in its RSS.
                rv = None
                fresh: dict[tuple[str, str], dict] = {}
                async for page in self.client.iter_pages(
                    self.group, self.kind, self.namespace, self.label_selector,
                    page_size=self.page_size or consts.LIST_PAGE_SIZE,
                ):
                    rv = page.get("metadata", {}).get("resourceVersion") or rv
                    if not self.cache_objects:
                        for item in page.get("items", []):
                            await self._dispatch("ADDED", self._stamp(item))
                        continue
                    for item in page.get("items", []):
                        meta = item.get("metadata", {})
                        fresh[(meta.get("namespace", ""), meta["name"])] = self._stamp(item)
                # large-relist etiquette: awaiting a handler that never
                # suspends does NOT yield to the loop, so a 25k-item diff
                # would run as one synchronous slab — starving everything
                # else on the loop (on a shard replica, the Lease renewals
                # whose expiry deposes it).  Breathe every few hundred.
                dispatched = 0

                async def _breathe():
                    nonlocal dispatched
                    dispatched += 1
                    if dispatched % 256 == 0:
                        await asyncio.sleep(0)

                if not self.cache_objects:
                    # event tap: items were already announced page by page
                    # above (handlers own dedup); nothing is retained
                    self.synced.set()
                else:
                    # diff against cache → synthetic events; keep the cache
                    # consistent with each event *before* handlers observe it
                    for key, item in fresh.items():
                        old = self.cache.get(key)
                        if old is None:
                            self.cache[key] = item
                            await self._dispatch("ADDED", item)
                            await _breathe()
                        elif old.get("metadata", {}).get("resourceVersion") != item["metadata"].get("resourceVersion"):
                            self.cache[key] = item
                            await self._dispatch("MODIFIED", item)
                            await _breathe()
                    for key, old in list(self.cache.items()):
                        if key not in fresh:
                            del self.cache[key]
                            await self._dispatch("DELETED", old)
                            await _breathe()
                    self.synced.set()
                watch_started = time.monotonic()
                async for evt in self.client.watch(
                    self.group,
                    self.kind,
                    self.namespace,
                    resource_version=rv,
                    label_selector=self.label_selector,
                    timeout_seconds=self.resync_seconds,
                ):
                    if evt.type == "BOOKMARK":
                        continue
                    if evt.type == "ERROR":
                        # the apiserver closes the window with a Status
                        # object; code 410 means our resourceVersion expired
                        if (evt.object or {}).get("code") == 410:
                            raise ApiError(410, "Expired")
                        break
                    # only REAL object events count as a healthy watch: a
                    # stream that serves one bookmark (or an error status)
                    # then dies must keep backing off, not reset it
                    served = True
                    meta = evt.object.get("metadata", {})
                    key = (meta.get("namespace", ""), meta.get("name", ""))
                    # dispatch the SAME object _stamp returned: on the cached
                    # path interning copies, so stamping the copy and
                    # dispatching the original would hand handlers an
                    # un-TypeMeta'd object on live watch events only
                    obj = evt.object
                    if not self.cache_objects:
                        self._stamp(obj)
                    elif evt.type == "DELETED":
                        self.cache.pop(key, None)
                    else:
                        obj = self._stamp(obj)
                        self.cache[key] = obj
                    await self._dispatch(evt.type, obj)
            except asyncio.CancelledError:
                raise
            except ApiError as e:
                if e.status == 410:
                    # relist-with-fresh-rv is the protocol answer; only
                    # repeated Gones (chaos, hot relist) earn a short yield
                    consecutive_gone += 1
                    log.debug("informer %s watch expired (410); relisting", self.kind)
                    if consecutive_gone > 1:
                        await asyncio.sleep(min(0.05 * consecutive_gone, 1.0))
                    continue
                # only optional informers slow-poll an unserved API; a
                # required one hitting the operator-install CRD race must
                # keep the fast backoff or manager start stalls for minutes
                if e.status in (404, 405) and not self.required:
                    await asyncio.sleep(ABSENT_API_RETRY_SECONDS)
                    continue
                log.debug("informer %s API error; backing off", self.kind, exc_info=True)
            except (OSError, asyncio.TimeoutError, aiohttp.ClientError):
                log.debug("informer %s stream reset; relisting", self.kind, exc_info=True)
            except Exception:  # noqa: BLE001 — unexpected: loud, but keep serving
                log.exception("informer %s unexpected error; backing off", self.kind)
            consecutive_gone = 0
            # Reset backoff only for a cycle whose watch proved healthy: it
            # served at least one event, or survived to (near) its natural
            # resync timeout.  A watch that dies quickly WITHOUT serving
            # anything (RBAC 403, chaos drop-on-connect) keeps backing off —
            # previously any stream that lived ≥1s reset the backoff and a
            # serve-nothing-die-young apiserver got relist-hammered.
            healthy_window = min(self.resync_seconds, 30.0)
            if served or (
                watch_started and time.monotonic() - watch_started >= healthy_window
            ):
                backoff = 0.05
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 5.0)
