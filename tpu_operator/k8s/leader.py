"""Lease-based leader election.

Reference analogue: cmd/gpu-operator/main.go:105-115 (controller-runtime
leader election with id 53822513.nvidia.com and a configurable
lease-renew-deadline).  Standard coordination.k8s.io/v1 Lease protocol:
acquire if unheld/expired, renew at renew_interval, yield on loss.
"""

from __future__ import annotations

import asyncio
import datetime
import logging
import os
import random
import socket
import time as _time
from typing import Callable, Optional

from tpu_operator import consts
from tpu_operator.k8s import retry as retry_api
from tpu_operator.k8s.client import ApiClient, ApiError, request_policy

log = logging.getLogger("tpu_operator.k8s.leader")

# (is_leader: bool) sync callbacks fired on every leadership transition —
# the manager hooks these to fence writers / emit Events (client-go's
# LeaderCallbacks OnStartedLeading/OnStoppedLeading analogue)
TransitionCallback = Callable[[bool], None]

# Renewal jitter: each renew tick sleeps interval x U(1-j, 1+j).  With the
# multi-replica sharded plane every replica runs one candidacy per shard
# Lease (N replicas x NODE_SHARDS leases), and un-jittered ticks align into
# synchronized renewal bursts against the apiserver; the jitter keeps the
# candidacies spread while never eating into the renew-deadline ordering
# (interval * 1.1 stays well under the default 2/3-duration deadline).
RENEW_JITTER = 0.1


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def _parse(ts: str) -> datetime.datetime:
    for fmt in ("%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%SZ"):
        try:
            return datetime.datetime.strptime(ts, fmt).replace(tzinfo=datetime.timezone.utc)
        except ValueError:
            continue
    raise ValueError(f"bad timestamp {ts}")


class LeaderElector:
    def __init__(
        self,
        client: ApiClient,
        namespace: str,
        name: str = consts.LEADER_ELECTION_ID,
        identity: Optional[str] = None,
        lease_duration: float = 15.0,
        renew_interval: float = 5.0,
        renew_deadline: Optional[float] = None,
    ):
        self.client = client
        self.namespace = namespace
        self.name = name
        self.identity = identity or f"{socket.gethostname()}-{os.getpid()}"
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        # client-go's RenewDeadline analogue: step down once we cannot
        # prove a renew within this window.  Default mirrors client-go's
        # 10s/15s ratio; the ordering invariant is enforced because a
        # deadline past the lease duration opens a split-brain window (a
        # peer legally acquires the expired lease while we still act as
        # leader) — client-go rejects that configuration at construction
        self.renew_deadline = (
            renew_deadline if renew_deadline is not None else lease_duration * 2.0 / 3.0
        )
        if not (self.renew_interval < self.renew_deadline <= self.lease_duration):
            raise ValueError(
                f"lease timings must satisfy retry ({self.renew_interval}s) < "
                f"renew deadline ({self.renew_deadline}s) <= lease duration "
                f"({self.lease_duration}s)"
            )
        self.is_leader = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._last_renew = 0.0
        self.on_transition: list[TransitionCallback] = []
        # Lease calls run under a policy whose TOTAL budget fits inside one
        # renew tick: a hung renew must surface (and count against the renew
        # deadline) before step-down time, not after the client-wide 60s
        # default.  One attempt per tick — the renew loop IS the retry loop.
        self._lease_policy = retry_api.RetryPolicy(
            max_attempts=1,
            per_try_timeout=max(0.05, self.renew_interval * 0.9),
            total_timeout=max(0.05, self.renew_interval * 0.9),
        )
        # per-elector RNG: seeding off the (unique) identity + lease name
        # would correlate replicas that share a hostname template, so use
        # an independently-seeded instance per candidacy
        self._jitter_rng = random.Random()
        # Soft anti-affinity hook (multi-replica sharded plane): while
        # ``defer_acquire`` returns True this candidacy holds back from
        # taking a lease it does not already hold for ``acquire_defer``
        # seconds, giving less-loaded replicas first claim — then takes it
        # anyway, so an orphaned shard is never stranded behind a full
        # peer (bounded takeover: defer + renew cadence).  Renewals of a
        # HELD lease are never deferred.
        self.defer_acquire: Optional[Callable[[], bool]] = None
        self.acquire_defer = lease_duration * 2.0
        self._defer_until: Optional[float] = None
        # Shared across one replica's candidacies: serializes ACQUISITION
        # attempts (renewals skip it) so the defer_acquire load check sees
        # each prior acquisition land before the next candidacy consults
        # it — without this, N parallel first ticks all read "0 held" and
        # one replica grabs every shard Lease at startup.
        self.acquire_lock: Optional[asyncio.Lock] = None

    def _deferring(self) -> bool:
        if self.defer_acquire is None or not self.defer_acquire():
            self._defer_until = None
            return False
        now = _time.monotonic()
        if self._defer_until is None:
            self._defer_until = now + self.acquire_defer
        return now < self._defer_until

    def _renew_sleep(self) -> float:
        """Next renew-tick sleep: the base cadence (halved while not
        leader, so a waiting candidate notices an expiry promptly) spread
        by ±RENEW_JITTER so many candidacies never renew in lockstep."""
        base = (
            self.renew_interval
            if self.is_leader.is_set()
            else self.renew_interval / 2
        )
        return base * self._jitter_rng.uniform(1 - RENEW_JITTER, 1 + RENEW_JITTER)

    async def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name="leader-elector")

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:  # noqa: BLE001
                log.debug("leader elector task errored during stop", exc_info=True)
        self._set_leader(False)
        # best-effort release
        try:
            with request_policy(self._lease_policy):
                lease = await self.client.get(
                    "coordination.k8s.io", "Lease", self.name, self.namespace
                )
                if lease.get("spec", {}).get("holderIdentity") == self.identity:
                    lease["spec"]["holderIdentity"] = None
                    await self.client.update(lease)
        except (ApiError, OSError, asyncio.TimeoutError):
            pass

    def _set_leader(self, value: bool) -> None:
        """Single transition point: flips the event and notifies callbacks
        (fence/Events) synchronously, BEFORE any further await — a deposed
        leader must be fenced the same instant ``is_leader`` clears."""
        if value == self.is_leader.is_set():
            return
        if value:
            log.info("became leader (%s)", self.identity)
            self.is_leader.set()
        else:
            log.warning("lost leadership (%s)", self.identity)
            self.is_leader.clear()
        for cb in self.on_transition:
            try:
                cb(value)
            except Exception:  # noqa: BLE001
                log.exception("leadership transition callback failed")

    async def _run(self) -> None:
        while True:
            try:
                with request_policy(self._lease_policy):
                    acquired = await self._try_acquire_or_renew()
                if acquired:
                    self._last_renew = _time.monotonic()
                    self._set_leader(True)
                else:
                    self._set_leader(False)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                if isinstance(e, (ApiError, OSError, asyncio.TimeoutError)):
                    # expected while the apiserver is unhealthy (incl. the
                    # breaker failing fast); the step-down guard below is the
                    # real handling — no traceback spam every renew tick
                    log.warning("leader election error: %s", e)
                else:
                    log.exception("leader election error")
                # Step down if we cannot prove we still hold the lease: once
                # our last successful renew is older than the lease duration,
                # another replica may legitimately acquire it (split-brain
                # guard mirroring client-go's leaderelection renew deadline).
                if (
                    self.is_leader.is_set()
                    and _time.monotonic() - self._last_renew > self.renew_deadline
                ):
                    log.warning("renew deadline exceeded; stepping down (%s)", self.identity)
                    self._set_leader(False)
            await asyncio.sleep(self._renew_sleep())

    async def _try_acquire_or_renew(self) -> bool:
        if not self.is_leader.is_set() and self.acquire_lock is not None:
            async with self.acquire_lock:
                return await self._acquire_or_renew()
        return await self._acquire_or_renew()

    async def _acquire_or_renew(self) -> bool:
        spec = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_duration),
            "renewTime": _now(),
        }
        try:
            lease = await self.client.get("coordination.k8s.io", "Lease", self.name, self.namespace)
        except ApiError as e:
            if not e.not_found:
                raise
            if self._deferring():
                return False
            lease = {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": self.name, "namespace": self.namespace},
                "spec": {**spec, "acquireTime": spec["renewTime"]},
            }
            try:
                await self.client.create(lease)
                self._defer_until = None
                return True
            except ApiError as e2:
                if e2.already_exists:
                    # another replica created the lease between our GET and
                    # POST — it holds leadership until the lease expires
                    return False
                raise

        holder = lease.get("spec", {}).get("holderIdentity")
        renew = lease.get("spec", {}).get("renewTime")
        expired = True
        if holder and renew:
            age = (
                datetime.datetime.now(datetime.timezone.utc) - _parse(renew)
            ).total_seconds()
            expired = age > lease["spec"].get("leaseDurationSeconds", self.lease_duration)
        if holder == self.identity or holder is None or expired:
            if holder != self.identity:
                if self._deferring():
                    return False
                spec["acquireTime"] = spec["renewTime"]
            lease["spec"].update(spec)
            try:
                await self.client.update(lease)
                self._defer_until = None
                return True
            except ApiError as e:
                if e.conflict:
                    return False
                raise
        # legitimately held by an unexpired peer: any deferral window we
        # were running is over — the NEXT free episode starts a fresh one
        # (a stale expired window would let a full replica take instantly)
        self._defer_until = None
        return False
