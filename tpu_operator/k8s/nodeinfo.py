"""Typed node-attribute provider + label-filter builders.

Reference analogue: ``internal/nodeinfo/`` — attribute extraction
(node_info.go:34-37, attributes.go:108-121) and the filter builders of
filter.go:22-143.  One source of truth for parsing TPU node attributes out
of labels/status; the label engine, pool partitioner, upgrade controller,
and feature discovery all consume this instead of re-deriving ad hoc.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from tpu_operator import consts
from tpu_operator.utils import deep_get, parse_topology, topology_chips


# ---------------------------------------------------------------------------
# Accelerator catalogue — the one table mapping GKE accelerator label values
# to chip generation, HBM per chip, and default chips per host.


@dataclass(frozen=True)
class AcceleratorInfo:
    generation: str          # v4 | v5e | v5p | v6e
    hbm_gb: int              # HBM per chip (GiB)
    chips_per_host: int      # default host chip count for this machine shape
    peak_bf16_tflops: float  # per-chip dense MXU peak (bf16 in, f32 acc)
    ici_gbps: float          # per-chip aggregate ICI bandwidth, GB/s
                             # (GKE per-chip interconnect spec / 8)
    hbm_gbps: float = 0.0    # per-chip HBM bandwidth, GB/s (published spec)
    ici_links: int = 4       # ICI links per chip (torus degree: 2D=4, 3D=6);
                             # per-LINK bandwidth = ici_gbps / ici_links
    dcn_gbps: float = 0.0    # per-HOST data-center-network bandwidth, GB/s
                             # (the NIC line rate of the generation's VM
                             # shape — the ceiling for cross-slice traffic;
                             # 0 = unknown, keeps DCN gates report-only)

    @property
    def ici_link_gbps(self) -> float:
        """Per-link ICI bandwidth — the ring diagnostic's denominator.  The
        aggregate number divided by the torus degree: a single healthy link
        carries aggregate/links, so per-link floors must derive from THIS,
        never from the multi-link aggregate."""
        return self.ici_gbps / max(1, self.ici_links)


# Per-generation perf envelope: peak TFLOPs are the published per-chip dense
# bf16 numbers (v4 275, v5e 197, v5p 459, v6e 918); ICI GB/s is the per-chip
# interchip-interconnect spec (v4 2400 Gbps, v5e 1600, v5p 4800, v6e 3584);
# HBM GB/s is the published per-chip memory bandwidth (v4 1228, v5e 819,
# v5p 2765, v6e 1640) — the denominator for the streaming benchmark
# (workloads/hbm_bench.py).
# These drive the MFU denominator (workloads/matmul_bench.py) and the
# allreduce bandwidth gate (validator components.py).
ACCELERATORS: dict[str, AcceleratorInfo] = {
    # ici_links: torus degree per chip — v4/v5p are 3D tori (6 links),
    # v5e/v6e are 2D (4 links); per-link bw = aggregate / links (v4
    # 300/6=50, v5e 200/4=50, v5p 600/6=100, v6e 448/4=112 GB/s).
    # dcn_gbps: the host NIC line rate of the generation's standard VM
    # shape (100 Gbps = 12.5 GB/s for v4/v5e hosts, 200 Gbps = 25 GB/s
    # for v5p/v6e) — deliberately the BASE shape's rate: multi-NIC
    # variants only raise the true ceiling above the floor derived here
    "tpu-v4-podslice": AcceleratorInfo("v4", 32, 4, 275.0, 300.0, 1228.0, 6, 12.5),
    "tpu-v5-lite-podslice": AcceleratorInfo("v5e", 16, 4, 197.0, 200.0, 819.0, 4, 12.5),
    "tpu-v5-lite-device": AcceleratorInfo("v5e", 16, 8, 197.0, 200.0, 819.0, 4, 12.5),
    "tpu-v5p-slice": AcceleratorInfo("v5p", 95, 4, 459.0, 600.0, 2765.0, 6, 25.0),
    "tpu-v6e-slice": AcceleratorInfo("v6e", 32, 4, 918.0, 448.0, 1640.0, 4, 25.0),
    "tpu-v6e-device": AcceleratorInfo("v6e", 32, 8, 918.0, 448.0, 1640.0, 4, 25.0),
}

UNKNOWN_ACCELERATOR = AcceleratorInfo("unknown", 0, 4, 0.0, 0.0, 0.0)


def accelerator_info(accelerator: str) -> AcceleratorInfo:
    return ACCELERATORS.get(accelerator, UNKNOWN_ACCELERATOR)


def generation_info(generation: str) -> AcceleratorInfo:
    """Perf envelope by chip generation (the axis the matmul/allreduce
    benchmarks detect at runtime via PJRT device_kind)."""
    for info in ACCELERATORS.values():
        if info.generation == generation:
            return info
    return UNKNOWN_ACCELERATOR


# ---------------------------------------------------------------------------
# Attribute extraction.


@dataclass(frozen=True)
class NodeAttributes:
    """Everything the operator derives from one Node object."""

    name: str
    is_tpu: bool
    accelerator: str          # GKE accelerator label value ("" on CPU nodes)
    topology: str             # ICI topology label ("2x4", "4x4x4", "")
    generation: str           # chip generation ("v5e", ... or "unknown")
    hbm_gb: int               # HBM per chip
    chips_per_host: int       # chips this host actually exposes
    slice_hosts: int          # hosts forming the slice (1 = single-host)
    worker_id: str            # slice worker index label ("" when absent)
    nodepool: str             # GKE nodepool label (slice identity)
    runtime_version: str      # TFD-reported libtpu version label
    upgrade_state: str        # upgrade state-machine label
    os_image: str
    kernel: str
    container_runtime: str    # containerd | docker | crio ("" unknown)
    unschedulable: bool
    tpu_allocatable: int      # allocatable google.com/tpu count
    labels: dict = field(hash=False, default_factory=dict, repr=False)


def is_tpu(node: dict) -> bool:
    """GKE TPU node pools carry the accelerator label out of the box
    (NFD-PCI-label detection analogue, state_manager.go:117-121).  Keyed on
    the GKE input label, never the operator's own tpu.present output — else
    de-labelling would be unreachable."""
    return consts.GKE_TPU_ACCELERATOR_LABEL in (
        deep_get(node, "metadata", "labels", default={}) or {}
    )


def chips_per_host(node: dict) -> int:
    """Host chip count: accelerator-shape default, reduced for single-host
    sub-shapes (a 2x2 v5e VM holds 4 chips even on an 8-chip machine type);
    multi-host slices never go below the per-host base."""
    labels = deep_get(node, "metadata", "labels", default={}) or {}
    base = accelerator_info(labels.get(consts.GKE_TPU_ACCELERATOR_LABEL, "")).chips_per_host
    topo = labels.get(consts.GKE_TPU_TOPOLOGY_LABEL)
    if topo:
        try:
            if len(parse_topology(topo)) <= 2:
                return min(base, topology_chips(topo))
        except ValueError:
            pass
    return base


def slice_hosts(node: dict) -> int:
    """Hosts forming this node's slice (topology chips / chips per host)."""
    labels = deep_get(node, "metadata", "labels", default={}) or {}
    topo = labels.get(consts.GKE_TPU_TOPOLOGY_LABEL, "")
    if not topo:
        return 1
    try:
        return max(1, topology_chips(topo) // max(1, chips_per_host(node)))
    except ValueError:
        return 1


def tpu_allocatable(node: dict) -> int:
    alloc = deep_get(node, "status", "allocatable", default={}) or {}
    try:
        return int(alloc.get(consts.TPU_RESOURCE, "0"))
    except ValueError:
        return 0


def container_runtime(node: dict) -> str:
    """containerd://1.7.0 → containerd (getRuntimeString analogue,
    state_manager.go:584-599)."""
    version = deep_get(node, "status", "nodeInfo", "containerRuntimeVersion", default="")
    return version.split("://", 1)[0] if "://" in version else ""


def attributes(node: dict) -> NodeAttributes:
    labels = deep_get(node, "metadata", "labels", default={}) or {}
    accel = labels.get(consts.GKE_TPU_ACCELERATOR_LABEL, "")
    info = accelerator_info(accel)
    node_info = deep_get(node, "status", "nodeInfo", default={}) or {}
    return NodeAttributes(
        name=deep_get(node, "metadata", "name", default=""),
        is_tpu=bool(accel),
        accelerator=accel,
        topology=labels.get(consts.GKE_TPU_TOPOLOGY_LABEL, ""),
        generation=info.generation if accel else "",
        hbm_gb=info.hbm_gb if accel else 0,
        chips_per_host=chips_per_host(node) if accel else 0,
        slice_hosts=slice_hosts(node) if accel else 1,
        worker_id=str(
            labels.get(consts.TFD_SLICE_WORKER_ID_LABEL)
            or labels.get(consts.GKE_TPU_WORKER_ID_LABEL, "")
        ),
        nodepool=labels.get(consts.GKE_NODEPOOL_LABEL, ""),
        runtime_version=labels.get(consts.TFD_RUNTIME_VERSION_LABEL, ""),
        upgrade_state=labels.get(consts.UPGRADE_STATE_LABEL, ""),
        os_image=node_info.get("osImage", ""),
        kernel=node_info.get("kernelVersion", ""),
        container_runtime=container_runtime(node),
        unschedulable=bool(deep_get(node, "spec", "unschedulable")),
        tpu_allocatable=tpu_allocatable(node),
        labels=dict(labels),
    )


# ---------------------------------------------------------------------------
# Label-filter builders (filter.go:22-143 analogue).


class NodeFilter:
    """Composable node predicate that can also serialize to an apiserver
    label selector for the requirements expressible as one."""

    def __init__(self) -> None:
        self._eq: dict[str, str] = {}
        self._exists: list[str] = []
        self._absent: list[str] = []
        self._preds: list[Callable[[dict], bool]] = []

    # -- label requirements (selector-expressible) ---------------------
    def eq(self, key: str, value: str) -> "NodeFilter":
        self._eq[key] = value
        return self

    def exists(self, key: str) -> "NodeFilter":
        self._exists.append(key)
        return self

    def absent(self, key: str) -> "NodeFilter":
        self._absent.append(key)
        return self

    def selector(self, node_selector: Optional[dict]) -> "NodeFilter":
        """Add every key=value of a k8s nodeSelector map."""
        for k, v in (node_selector or {}).items():
            self.eq(k, v)
        return self

    # -- common TPU shorthands -----------------------------------------
    def tpu(self) -> "NodeFilter":
        return self.exists(consts.GKE_TPU_ACCELERATOR_LABEL)

    def accelerator(self, value: str) -> "NodeFilter":
        return self.eq(consts.GKE_TPU_ACCELERATOR_LABEL, value)

    def topology(self, value: str) -> "NodeFilter":
        return self.eq(consts.GKE_TPU_TOPOLOGY_LABEL, value)

    def upgrade_state(self, value: str) -> "NodeFilter":
        return self.eq(consts.UPGRADE_STATE_LABEL, value)

    # -- arbitrary predicates (client-side only) -----------------------
    def where(self, pred: Callable[[dict], bool]) -> "NodeFilter":
        self._preds.append(pred)
        return self

    def advertises_tpu(self) -> "NodeFilter":
        return self.where(lambda n: tpu_allocatable(n) > 0)

    def schedulable(self) -> "NodeFilter":
        return self.where(lambda n: not deep_get(n, "spec", "unschedulable"))

    # -- evaluation ----------------------------------------------------
    def matches(self, node: dict) -> bool:
        labels = deep_get(node, "metadata", "labels", default={}) or {}
        if any(labels.get(k) != v for k, v in self._eq.items()):
            return False
        if any(k not in labels for k in self._exists):
            return False
        if any(k in labels for k in self._absent):
            return False
        return all(p(node) for p in self._preds)

    def apply(self, nodes: Iterable[dict]) -> list[dict]:
        return [n for n in nodes if self.matches(n)]

    def label_selector(self) -> str:
        """Server-side selector string for the label requirements (the
        ``where`` predicates cannot be pushed down and are ignored here)."""
        parts = [f"{k}={v}" for k, v in sorted(self._eq.items())]
        parts += sorted(self._exists)
        parts += [f"!{k}" for k in sorted(self._absent)]
        return ",".join(parts)


class Provider:
    """Cached attribute provider over a node list (nodeinfo.Provider
    analogue, node_info.go:34-37)."""

    def __init__(self, nodes: list[dict]):
        self.nodes = nodes

    def tpu_nodes(self) -> list[dict]:
        return [n for n in self.nodes if is_tpu(n)]

    def attributes(self) -> list[NodeAttributes]:
        return [attributes(n) for n in self.nodes]

    def filtered(self, f: NodeFilter) -> list[NodeAttributes]:
        return [attributes(n) for n in f.apply(self.nodes)]

    def pools(self) -> dict[tuple[str, str], list[NodeAttributes]]:
        """TPU nodes grouped by (accelerator, topology) — the axes that
        differentiate the runtime payload (nodepool.go:55-133 analogue)."""
        out: dict[tuple[str, str], list[NodeAttributes]] = {}
        for attrs in (attributes(n) for n in self.tpu_nodes()):
            out.setdefault((attrs.accelerator, attrs.topology), []).append(attrs)
        return out
