"""Unstructured object helpers and GVK → REST path mapping.

The reference gets this from apimachinery's RESTMapper; we keep a static table
of every kind the operator touches (extensible at runtime for CRDs via
``register_kind``), mirroring the GVK whitelist idea of
internal/state/state_skel.go:62-165 (getSupportedGVKs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class GVK:
    group: str  # "" for core
    version: str
    kind: str

    @property
    def api_version(self) -> str:
        return self.version if not self.group else f"{self.group}/{self.version}"


@dataclass(frozen=True)
class ResourceInfo:
    gvk: GVK
    plural: str
    namespaced: bool


_REGISTRY: dict[tuple[str, str], ResourceInfo] = {}


def register_kind(group: str, version: str, kind: str, plural: str, namespaced: bool) -> None:
    _REGISTRY[(group, kind)] = ResourceInfo(GVK(group, version, kind), plural, namespaced)


# Core kinds the operator manages (getSupportedGVKs analogue).
for g, v, k, pl, ns in [
    ("", "v1", "Namespace", "namespaces", False),
    ("", "v1", "Node", "nodes", False),
    ("", "v1", "Pod", "pods", True),
    ("", "v1", "Service", "services", True),
    ("", "v1", "ServiceAccount", "serviceaccounts", True),
    ("", "v1", "ConfigMap", "configmaps", True),
    ("", "v1", "Secret", "secrets", True),
    ("", "v1", "Event", "events", True),
    ("apps", "v1", "DaemonSet", "daemonsets", True),
    ("apps", "v1", "Deployment", "deployments", True),
    ("apps", "v1", "ControllerRevision", "controllerrevisions", True),
    ("rbac.authorization.k8s.io", "v1", "Role", "roles", True),
    ("rbac.authorization.k8s.io", "v1", "RoleBinding", "rolebindings", True),
    ("rbac.authorization.k8s.io", "v1", "ClusterRole", "clusterroles", False),
    ("rbac.authorization.k8s.io", "v1", "ClusterRoleBinding", "clusterrolebindings", False),
    ("coordination.k8s.io", "v1", "Lease", "leases", True),
    ("monitoring.coreos.com", "v1", "ServiceMonitor", "servicemonitors", True),
    ("monitoring.coreos.com", "v1", "PrometheusRule", "prometheusrules", True),
    ("node.k8s.io", "v1", "RuntimeClass", "runtimeclasses", False),
    ("apiextensions.k8s.io", "v1", "CustomResourceDefinition", "customresourcedefinitions", False),
    ("policy", "v1", "PodDisruptionBudget", "poddisruptionbudgets", True),
    ("scheduling.k8s.io", "v1", "PriorityClass", "priorityclasses", False),
    # Operator CRDs (api/ package).
    ("tpu.google.com", "v1", "TPUClusterPolicy", "tpuclusterpolicies", False),
    ("tpu.google.com", "v1alpha1", "TPURuntime", "tpuruntimes", False),
    ("tpu.google.com", "v1alpha1", "TPUSliceRequest", "tpuslicerequests", False),
]:
    register_kind(g, v, k, pl, ns)


def gvk_of(obj: dict) -> GVK:
    api_version = obj.get("apiVersion", "")
    kind = obj.get("kind", "")
    if "/" in api_version:
        group, version = api_version.split("/", 1)
    else:
        group, version = "", api_version
    return GVK(group, version, kind)


def lookup(group: str, kind: str) -> ResourceInfo:
    try:
        return _REGISTRY[(group, kind)]
    except KeyError:
        raise KeyError(f"unregistered kind {group or 'core'}/{kind}; call register_kind()") from None


def info_of(obj: dict) -> ResourceInfo:
    gvk = gvk_of(obj)
    return lookup(gvk.group, gvk.kind)


def resource_path(
    group: str,
    version: str,
    plural: str,
    namespaced: bool,
    namespace: Optional[str] = None,
    name: Optional[str] = None,
    subresource: Optional[str] = None,
) -> str:
    base = f"/api/{version}" if not group else f"/apis/{group}/{version}"
    parts = [base]
    if namespaced:
        if not namespace:
            raise ValueError(f"namespace required for namespaced resource {plural}")
        parts.append(f"namespaces/{namespace}")
    parts.append(plural)
    if name:
        parts.append(name)
        if subresource:
            parts.append(subresource)
    return "/".join(parts)


def object_path(obj: dict, subresource: Optional[str] = None) -> str:
    info = info_of(obj)
    meta = obj.get("metadata", {})
    return resource_path(
        info.gvk.group,
        info.gvk.version,
        info.plural,
        info.namespaced,
        meta.get("namespace"),
        meta.get("name"),
        subresource,
    )


def set_owner_reference(obj: dict, owner: dict, controller: bool = True) -> None:
    """ctrl.SetControllerReference analogue (object_controls.go:4112)."""
    ref = {
        "apiVersion": owner["apiVersion"],
        "kind": owner["kind"],
        "name": owner["metadata"]["name"],
        "uid": owner["metadata"].get("uid", ""),
        "controller": controller,
        "blockOwnerDeletion": True,
    }
    refs = obj.setdefault("metadata", {}).setdefault("ownerReferences", [])
    for existing in refs:
        if existing.get("uid") == ref["uid"] and existing.get("name") == ref["name"]:
            existing.update(ref)
            return
    refs.append(ref)


def owned_by(obj: dict, owner_uid: str) -> bool:
    for ref in obj.get("metadata", {}).get("ownerReferences", []) or []:
        if ref.get("uid") == owner_uid:
            return True
    return False
