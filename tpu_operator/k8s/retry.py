"""API-request resilience: retry policy, retry budget, circuit breaker, fence.

Reference analogue: controller-runtime inherits client-go's rate limiters and
retry.OnError/RetryOnConflict helpers, and its manager stops serving when the
apiserver stays unreachable.  Our hand-rolled :class:`ApiClient` gets the same
discipline here, in one place, so every caller (reconcilers, informer relists,
leader election, event recording) shares the behaviour:

- :class:`RetryPolicy` — exponential backoff with FULL jitter (AWS
  architecture-blog style: ``sleep = rand(0, min(cap, base * 2**attempt))``),
  ``Retry-After`` honoring on 429/503, a per-attempt timeout so a hung
  connection cannot stall a reconcile pass, a total per-request deadline, and
  a verb classification that never blindly replays non-idempotent POSTs.
- :class:`RetryBudget` — a token bucket (client-go/finagle style) bounding the
  FRACTION of traffic that may be retries, so a degraded apiserver sees load
  shed instead of a retry storm multiplying it.
- :class:`CircuitBreaker` — consecutive infrastructure failures (5xx,
  timeouts, connection resets) trip it OPEN; requests then fail fast with
  :class:`BreakerOpenError` until the reset window elapses, after which
  HALF_OPEN admits one probe at a time; a probe success closes it.  The
  manager surfaces the state as degraded mode (``controllers/runtime.py``).
- :class:`WriteFence` — refuses mutating verbs the instant leadership is
  lost (lease renewal and Event posting stay exempt), closing the window
  between the elector clearing ``is_leader`` and in-flight reconciles being
  cancelled.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpu_operator import consts

# HTTP verbs that mutate; everything else is read-only.
MUTATING_VERBS = frozenset({"POST", "PUT", "PATCH", "DELETE"})
# Verbs safe to replay after an ambiguous failure: reads trivially; PUT and
# DELETE by named-object idempotence (a PUT replay hits a resourceVersion
# conflict at worst, a DELETE replay a 404 — both handled by callers); PATCH
# because the operator only issues merge patches (RFC 7386 is idempotent).
# POST is absent on purpose: a create that timed out may have COMMITTED, and
# replaying it mints a duplicate object (or a duplicate Event) — the apply
# layer recovers via its get/adopt path instead.
IDEMPOTENT_VERBS = frozenset({"GET", "PUT", "PATCH", "DELETE"})

# CircuitBreaker states (exported for the tpu_operator_api_breaker_state gauge:
# 0 is healthy so the alert rule is a simple `> 0`).
CLOSED = 0
HALF_OPEN = 1
OPEN = 2

STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half-open", OPEN: "open"}


class RetryBudget:
    """Token bucket bounding the retry fraction of total traffic.

    Each regular request earns ``ratio`` tokens (capped); each retry spends
    one.  With ratio 0.2 at most ~20% of sustained traffic can be retries —
    a hard-down apiserver gets probed, not hammered.
    """

    def __init__(self, ratio: float = 0.2, cap: float = 10.0):
        self.ratio = ratio
        self.cap = cap
        self.tokens = cap

    def record_request(self) -> None:
        self.tokens = min(self.cap, self.tokens + self.ratio)

    def allow_retry(self) -> bool:
        if self.tokens < 1.0:
            return False
        self.tokens -= 1.0
        return True


@dataclass
class RetryPolicy:
    """Per-request retry/timeout behaviour for ``ApiClient._request``.

    ``rng`` is injectable so chaos tests replay byte-identical schedules;
    the default is module-level randomness, which is exactly what production
    wants (fleet-wide jitter decorrelation).
    """

    max_attempts: int = consts.K8S_RETRY_MAX_ATTEMPTS
    backoff_base: float = consts.K8S_RETRY_BACKOFF_BASE_SECONDS
    backoff_cap: float = consts.K8S_RETRY_BACKOFF_CAP_SECONDS
    # per-attempt timeout: a hung connection surfaces as TimeoutError here
    # instead of stalling the reconcile pass until aiohttp's 5-minute default
    per_try_timeout: Optional[float] = consts.K8S_REQUEST_PER_TRY_TIMEOUT_SECONDS
    # wall-clock deadline across ALL attempts of one logical request
    total_timeout: Optional[float] = consts.K8S_REQUEST_TOTAL_TIMEOUT_SECONDS
    budget: Optional[RetryBudget] = None
    rng: random.Random = field(default_factory=random.Random)

    def retryable_verb(self, method: str, status: Optional[int]) -> bool:
        """May this (verb, outcome) be replayed?  429 is retryable for every
        verb — the server explicitly did not process the request; anything
        ambiguous (5xx, timeout, reset: ``status None``) only for verbs whose
        replay cannot duplicate a side effect."""
        if status == 429:
            return True
        return method.upper() in IDEMPOTENT_VERBS

    def backoff(self, attempt: int, retry_after: Optional[float] = None) -> float:
        """Sleep before retry ``attempt`` (1-based): full jitter over the
        exponential envelope, floored by any server-provided Retry-After."""
        envelope = min(self.backoff_cap, self.backoff_base * (2 ** (attempt - 1)))
        delay = self.rng.uniform(0.0, envelope)
        if retry_after is not None:
            delay = max(delay, retry_after)
        return delay


class CircuitBreaker:
    """Consecutive-failure breaker over apiserver infrastructure health.

    Logical outcomes (404, 409, 422 …) are SUCCESSES here — the server
    answered.  Only 5xx, timeouts, and connection failures count against the
    threshold; 429 is deliberately neutral (a throttling server is alive).
    """

    def __init__(
        self,
        failure_threshold: int = consts.K8S_BREAKER_FAILURE_THRESHOLD,
        reset_seconds: float = consts.K8S_BREAKER_RESET_SECONDS,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = failure_threshold
        self.reset_seconds = reset_seconds
        self._clock = clock
        self.state = CLOSED
        self.consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_started_at = 0.0
        # lifetime transition tally for tests/diagnostics
        self.opened_total = 0

    @property
    def state_name(self) -> str:
        return STATE_NAMES[self.state]

    def allow(self) -> bool:
        """May a request be issued right now?  OPEN fails fast until the
        reset window elapses, then HALF_OPEN admits exactly one probe at a
        time (concurrent requests keep failing fast until it reports)."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self._clock() - self._opened_at < self.reset_seconds:
                return False
            self.state = HALF_OPEN
            self._probe_inflight = False
        # HALF_OPEN: single probe.  A probe that never reported (its task
        # was cancelled mid-request, or it hung past any sane timeout) must
        # not hold the slot forever — reclaim after the reset window so the
        # breaker can never wedge permanently half-open.
        if (
            self._probe_inflight
            and self._clock() - self._probe_started_at >= self.reset_seconds
        ):
            self._probe_inflight = False
        if self._probe_inflight:
            return False
        self._probe_inflight = True
        self._probe_started_at = self._clock()
        return True

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self._probe_inflight = False
        self.state = CLOSED

    def record_neutral(self) -> None:
        """Server answered but proved neither health nor failure (429: it
        is alive yet shedding load).  Releases a probe slot without closing
        the breaker or touching the failure streak — interleaved
        500,429,500 traffic must still accumulate toward the threshold."""
        self._probe_inflight = False

    def release_probe(self) -> None:
        """The in-flight request died without a verdict (task cancelled):
        free the half-open slot immediately so the next request can probe."""
        self._probe_inflight = False

    def record_failure(self) -> None:
        self._probe_inflight = False
        if self.state == HALF_OPEN:
            # failed probe: straight back to OPEN for a fresh window
            self._trip()
            return
        self.consecutive_failures += 1
        if self.state == CLOSED and self.consecutive_failures >= self.failure_threshold:
            self._trip()

    def _trip(self) -> None:
        self.state = OPEN
        self._opened_at = self._clock()
        self.opened_total += 1
        self.consecutive_failures = 0


class FencedError(Exception):
    """A mutating request was refused because this replica is not leader.

    Raised client-side before anything reaches the wire; reconcile code
    treats it like any other request failure (workqueue backoff), but by the
    time it can fire the manager is already cancelling those workers."""

    def __init__(self, method: str, path: str):
        self.method = method
        self.path = path
        super().__init__(f"write fenced (not leader): {method} {path}")


class WriteFence:
    """Gate evaluated by ``ApiClient._request`` before every send.

    ``allow`` is consulted live (not cached at install time) so the fence
    engages the same instant ``LeaderElector.is_leader`` clears.  Lease
    traffic must stay exempt (the elector needs it to re-acquire) and so do
    Events (client-go replicas report leader-election transitions whether or
    not they lead).
    """

    def __init__(self, allow: Callable[[], bool]):
        self.allow = allow
        self.refused_total = 0

    @staticmethod
    def _exempt(path: str) -> bool:
        """True for Lease and Event traffic, matched on the RESOURCE
        COLLECTION segment of the URL — a substring test would also exempt
        any object merely *named* 'events' (e.g. a ConfigMap), reopening
        the split-brain window the fence closes."""
        segs = [s for s in path.split("?", 1)[0].split("/") if s]
        # /api/v1/[namespaces/<ns>/]<plural>[/name...]
        # /apis/<group>/<version>/[namespaces/<ns>/]<plural>[/name...]
        if not segs:
            return False
        if segs[0] == "api":
            rest, group = segs[2:], ""
        elif segs[0] == "apis" and len(segs) >= 3:
            rest, group = segs[3:], segs[1]
        else:
            return False
        if len(rest) >= 2 and rest[0] == "namespaces":
            rest = rest[2:]
        plural = rest[0] if rest else ""
        if plural == "leases" and group == "coordination.k8s.io":
            return True
        return plural == "events" and group in ("", "events.k8s.io")

    def check(self, method: str, path: str) -> None:
        if method.upper() not in MUTATING_VERBS:
            return
        if self._exempt(path):
            return
        if not self.allow():
            self.refused_total += 1
            raise FencedError(method, path)
