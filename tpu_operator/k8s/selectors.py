"""Label-selector parsing and matching (k8s.io/apimachinery/pkg/labels subset).

Supports the string forms the operator and its manifests use:
  ``k=v``, ``k==v``, ``k!=v``, ``k``, ``!k``, ``k in (a,b)``, ``k notin (a,b)``
plus the structured ``matchLabels``/``matchExpressions`` selector form used by
DaemonSets and node affinity.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Mapping, Optional

_SET_RE = re.compile(r"^\s*([A-Za-z0-9_./-]+)\s+(in|notin)\s+\(([^)]*)\)\s*$")


@dataclass(frozen=True)
class Requirement:
    key: str
    op: str  # =, !=, exists, !exists, in, notin, gt, lt
    values: tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        present = self.key in labels
        val = labels.get(self.key)
        if self.op == "exists":
            return present
        if self.op == "!exists":
            return not present
        if self.op == "=":
            return present and val == self.values[0]
        if self.op == "!=":
            return not present or val != self.values[0]
        if self.op == "in":
            return present and val in self.values
        if self.op == "notin":
            return not present or val not in self.values
        if self.op in ("gt", "lt"):
            if not present:
                return False
            try:
                n, bound = int(val), int(self.values[0])  # type: ignore[arg-type]
            except ValueError:
                return False
            return n > bound if self.op == "gt" else n < bound
        raise ValueError(f"unknown op {self.op}")


def _split_top_level(s: str) -> list[str]:
    """Split on commas not inside parentheses."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return parts


def parse(selector: str) -> list[Requirement]:
    reqs: list[Requirement] = []
    if not selector or not selector.strip():
        return reqs
    for part in _split_top_level(selector):
        part = part.strip()
        if not part:
            continue
        m = _SET_RE.match(part)
        if m:
            vals = tuple(v.strip() for v in m.group(3).split(",") if v.strip())
            reqs.append(Requirement(m.group(1), m.group(2), vals))
        elif "!=" in part:
            k, v = part.split("!=", 1)
            reqs.append(Requirement(k.strip(), "!=", (v.strip(),)))
        elif "==" in part:
            k, v = part.split("==", 1)
            reqs.append(Requirement(k.strip(), "=", (v.strip(),)))
        elif "=" in part:
            k, v = part.split("=", 1)
            reqs.append(Requirement(k.strip(), "=", (v.strip(),)))
        elif part.startswith("!"):
            reqs.append(Requirement(part[1:].strip(), "!exists"))
        else:
            reqs.append(Requirement(part, "exists"))
    return reqs


def matches(selector: str, labels: Optional[Mapping[str, str]]) -> bool:
    labels = labels or {}
    return all(r.matches(labels) for r in parse(selector))


_EXPR_OPS = {
    "In": "in",
    "NotIn": "notin",
    "Exists": "exists",
    "DoesNotExist": "!exists",
    "Gt": "gt",
    "Lt": "lt",
}


def matches_structured(selector: Optional[dict], labels: Optional[Mapping[str, str]]) -> bool:
    """Match a LabelSelector dict ({matchLabels, matchExpressions})."""
    labels = labels or {}
    if not selector:
        return True
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        op = _EXPR_OPS.get(expr.get("operator", ""))
        if op is None:
            return False
        req = Requirement(expr["key"], op, tuple(expr.get("values") or ()))
        if not req.matches(labels):
            return False
    return True


def matches_node_selector_terms(terms: list[dict], labels: Mapping[str, str]) -> bool:
    """NodeSelectorTerms are ORed; matchExpressions within a term are ANDed."""
    if not terms:
        return True
    for term in terms:
        ok = True
        for expr in term.get("matchExpressions") or []:
            op = _EXPR_OPS.get(expr.get("operator", ""))
            if op is None:
                ok = False
                break
            req = Requirement(expr["key"], op, tuple(expr.get("values") or ()))
            if not req.matches(labels):
                ok = False
                break
        if ok:
            return True
    return False
