"""Consistent hash-ring sharding for per-key reconcile work.

The delta reconcile plane (``controllers/plane.py``) partitions per-node
work across N worker shards: every key hashes to exactly one shard, so one
node's reconciles are always serialized (no key ever runs concurrently with
itself) while distinct nodes fan out across workers.  Consistent hashing —
each shard projected onto the ring at ``vnodes`` points — keeps a shard
add/remove from reshuffling more than ~1/N of the key space, which is what
bounds the work a handoff re-routes.

Ownership is the *fence* input: a shard worker actuates a key only while
``ring.owner(key)`` still names it (generalizing the PR-4 leader
``WriteFence`` to per-shard scope — ``k8s/client.py`` ``request_fence``).
In-process the ring mutates on the same event loop that checks it, so the
fence is exact: after a handoff the old shard's very next write for a moved
key is refused, never duplicated.
"""

from __future__ import annotations

import bisect
from typing import Optional, Sequence

from tpu_operator.utils import fnv1a_64


_MASK64 = 0xFFFFFFFFFFFFFFFF


def _hash(value: str) -> int:
    # FNV-1a is stable across processes/runs (unlike hash()) but has weak
    # avalanche on short common-prefix strings (node-0001 vs node-0002 land
    # on the same ring arc); the murmur3 fmix64 finalizer spreads them
    h = fnv1a_64(value.encode())
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & _MASK64
    h ^= h >> 33
    h = (h * 0xC4CEB9FE1A85EC53) & _MASK64
    h ^= h >> 33
    return h


class HashRing:
    """Consistent hash ring mapping string keys onto shard ids."""

    def __init__(self, shards: Sequence[str] = (), vnodes: int = 64):
        self.vnodes = vnodes
        self._points: list[tuple[int, str]] = []
        self._hashes: list[int] = []  # parallel to _points, for bisect
        self._shards: set[str] = set()
        for shard in shards:
            self.add(shard)

    def __len__(self) -> int:
        return len(self._shards)

    @property
    def shards(self) -> list[str]:
        return sorted(self._shards)

    def add(self, shard: str) -> None:
        if shard in self._shards:
            return
        self._shards.add(shard)
        for v in range(self.vnodes):
            self._points.append((_hash(f"{shard}#{v}"), shard))
        self._points.sort()
        self._hashes = [h for h, _ in self._points]

    def remove(self, shard: str) -> None:
        if shard not in self._shards:
            return
        self._shards.discard(shard)
        self._points = [(h, s) for h, s in self._points if s != shard]
        self._hashes = [h for h, _ in self._points]

    def owner(self, key: str) -> Optional[str]:
        """The shard owning ``key`` right now, or None on an empty ring."""
        if not self._points:
            return None
        idx = bisect.bisect_right(self._hashes, _hash(key))
        if idx == len(self._points):
            idx = 0  # wrap around
        return self._points[idx][1]
