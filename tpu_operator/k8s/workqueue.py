"""Shared priority/fairness workqueue for every reconcile loop.

client-go ``workqueue`` analogue (the layer controller-runtime builds its
per-controller queues on), grown for a 10k-node fleet:

- **Dedup/coalescing** — a key queued twice collapses to one pending entry
  (the reconcile reads current state, so one pass absorbs any number of
  triggering events).  A key re-added *while its reconcile runs* lands in a
  dirty set and re-queues the moment the run completes (client-go
  processing/dirty semantics) — with shared worker pools this is what keeps
  one key from ever reconciling concurrently with itself.
- **Priority classes** — :data:`PRIORITY_HIGH` (health/remediation
  actuation), :data:`PRIORITY_NORMAL` (event-driven deltas), and
  :data:`PRIORITY_LOW` (periodic full-resync sweeps).  ``get()`` always
  serves the highest class with work, so a node the health engine needs
  drained preempts a 10k-key label resync backlog; re-adding a pending key
  at a higher class upgrades it in place.
- **Fairness lanes** — within one priority class, keys are drawn
  round-robin across lanes (e.g. one lane per TPUClusterPolicy, or per
  slice group), so a storming source cannot starve a quiet one.
- **Rate-limited requeue** — ``fail(key)`` schedules the key back with
  per-item exponential backoff (base/cap mirror the old ``RateLimiter``);
  ``forget(key)`` resets the item's failure streak.
- **Scheduled requeue** — ``add_after(key, delay)`` with earlier-wins timer
  coalescing; the cancellable replacement for hand-rolled
  ``while True: sleep`` poll loops (``hack/check_delta_paths.py`` bans
  those under ``controllers/``).
- **Metrics** — depth/latency/requeues ride the PR-6 ``Controller`` gauges
  (labelled by queue name) plus the ``tpu_operator_workqueue_*`` families
  for the new dimensions (per-priority depth, coalesced adds, backoff
  retries) — docs/PERFORMANCE.md "Delta reconcile & sharding".
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Any, Optional

from tpu_operator import consts

# Priority classes, lowest number served first.  Deliberately a short enum:
# every extra class is another starvation relationship to reason about.
PRIORITY_HIGH = 0      # health/remediation actuation paths
PRIORITY_NORMAL = 1    # event-driven delta reconciles
PRIORITY_LOW = 2       # periodic full-resync safety-net sweeps

_PRIORITIES = (PRIORITY_HIGH, PRIORITY_NORMAL, PRIORITY_LOW)
PRIORITY_NAMES = {PRIORITY_HIGH: "high", PRIORITY_NORMAL: "normal", PRIORITY_LOW: "low"}

DEFAULT_LANE = ""


class ShutDown(Exception):
    """Raised by ``get()`` once the queue is shut down and drained."""


class WorkQueue:
    """Deduplicating delayed priority queue with fairness lanes.

    Single-event-loop discipline: every method is called from the loop that
    runs the workers (enqueue sites are informer handlers and reconcile
    returns, both loop-side), so plain dicts/deques need no locking.
    """

    def __init__(
        self,
        name: str = "",
        metrics: Optional[Any] = None,
        base: float = consts.RATE_LIMIT_BASE_SECONDS,
        cap: float = consts.RATE_LIMIT_MAX_SECONDS,
    ):
        self.name = name
        # OperatorMetrics (or None).  Mutable on purpose: the Manager stamps
        # controller metrics after construction (add_controller/start).
        self.metrics = metrics
        self.base = base
        self.cap = cap
        # priority -> lane -> deque of keys; _lane_rr holds the round-robin
        # rotation of non-empty lanes per priority
        self._lanes: dict[int, dict[str, deque[str]]] = {p: {} for p in _PRIORITIES}
        self._lane_rr: dict[int, deque[str]] = {p: deque() for p in _PRIORITIES}
        self._pending: dict[str, tuple[int, str]] = {}  # key -> (priority, lane)
        # incremental per-priority tally: depth reporting must stay O(1) per
        # add/pop — recomputing over pending would make a 10k-key resync
        # burst O(N^2) on the event loop
        self._pri_counts: dict[int, int] = {p: 0 for p in _PRIORITIES}
        self._enqueued_ts: dict[str, float] = {}
        self._processing: dict[str, tuple[int, str]] = {}  # key -> meta at pop
        self._dirty: dict[str, tuple[int, str]] = {}  # re-adds during processing
        self._timers: dict[str, asyncio.TimerHandle] = {}
        self._failures: dict[str, int] = {}
        self._ready = asyncio.Event()
        self._shutting_down = False

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._pending)

    @property
    def shutting_down(self) -> bool:
        return self._shutting_down

    @property
    def idle(self) -> bool:
        """Nothing pending and nothing in flight (scheduled timers are
        future work and deliberately excluded)."""
        return not self._pending and not self._processing

    def pending_keys(self) -> list[str]:
        return list(self._pending)

    def processing_priority(self, key: str) -> Optional[int]:
        """The priority class an in-flight key was popped at (None when the
        key is not processing) — lets a re-routing caller (shard handoff)
        preserve the class instead of demoting to NORMAL."""
        meta = self._processing.get(key)
        return meta[0] if meta is not None else None

    def _report_depth(self) -> None:
        if self.metrics is None:
            return
        self.metrics.controller_queue_depth.labels(controller=self.name).set(
            len(self._pending)
        )
        for priority, n in self._pri_counts.items():
            self.metrics.workqueue_depth.labels(
                queue=self.name, priority=PRIORITY_NAMES[priority]
            ).set(n)

    # ------------------------------------------------------------------
    def add(
        self,
        key: str,
        priority: int = PRIORITY_NORMAL,
        lane: str = DEFAULT_LANE,
    ) -> None:
        """Queue ``key``; collapses onto an existing pending entry (keeping
        the earlier enqueue timestamp, upgrading priority when the new add
        outranks it) and defers onto the dirty set while the key's reconcile
        is in flight."""
        if self._shutting_down:
            return
        if key in self._processing:
            prev = self._dirty.get(key)
            if prev is None or priority < prev[0]:
                self._dirty[key] = (priority, lane)
            self._count_coalesced()
            return
        existing = self._pending.get(key)
        if existing is not None:
            if priority < existing[0]:
                # preemption: pull the key out of its old slot and re-queue
                # it at the stronger class (front-of-lane: it has waited)
                self._remove_pending(key)
                self._pending[key] = (priority, lane)
                self._pri_counts[priority] += 1
                self._lane_for(priority, lane).appendleft(key)
                self._report_depth()
                self._ready.set()
            self._count_coalesced()
            return
        # an immediate add beats any scheduled timer for the same key
        timer = self._timers.pop(key, None)
        if timer is not None:
            timer.cancel()
        self._pending[key] = (priority, lane)
        self._pri_counts[priority] += 1
        self._enqueued_ts.setdefault(key, time.monotonic())
        self._lane_for(priority, lane).append(key)
        self._report_depth()
        self._ready.set()

    def _lane_for(self, priority: int, lane: str) -> deque[str]:
        lanes = self._lanes[priority]
        d = lanes.get(lane)
        if d is None:
            d = lanes[lane] = deque()
        if not d:
            # (re)joining the rotation — only empty lanes are absent from it
            self._lane_rr[priority].append(lane)
        return d

    def _remove_pending(self, key: str) -> None:
        priority, lane = self._pending.pop(key)
        self._pri_counts[priority] -= 1
        d = self._lanes[priority].get(lane)
        if d is not None:
            try:
                d.remove(key)
            except ValueError:
                pass
            if not d:
                try:
                    self._lane_rr[priority].remove(lane)
                except ValueError:
                    pass

    def add_after(
        self,
        key: str,
        delay: float,
        priority: int = PRIORITY_NORMAL,
        lane: str = DEFAULT_LANE,
    ) -> None:
        """Delayed add; an existing timer for the key is replaced only when
        the new one fires sooner (AddAfter semantics), and a key already
        pending needs no timer at all."""
        if self._shutting_down:
            return
        if delay <= 0:
            self.add(key, priority, lane)
            return
        if key in self._pending:
            return
        loop = asyncio.get_running_loop()
        existing = self._timers.get(key)
        if existing is not None:
            if existing.when() - loop.time() <= delay:
                return
            existing.cancel()
        self._timers[key] = loop.call_later(
            delay, self._fire, key, priority, lane
        )

    def _fire(self, key: str, priority: int, lane: str) -> None:
        self._timers.pop(key, None)
        self.add(key, priority, lane)

    # ------------------------------------------------------------------
    async def get(self) -> str:
        """Next key, highest priority class first, round-robin across that
        class's fairness lanes.  The key enters the processing set; the
        caller MUST finish with ``done(key)`` (or ``fail``+``done``).
        Raises :class:`ShutDown` once the queue is shut down and empty."""
        while True:
            if self._pending:
                return self._pop()
            if self._shutting_down:
                raise ShutDown(self.name)
            self._ready.clear()
            await self._ready.wait()

    def _pop(self) -> str:
        for priority in _PRIORITIES:
            rr = self._lane_rr[priority]
            if not rr:
                continue
            lane = rr.popleft()
            d = self._lanes[priority][lane]
            key = d.popleft()
            if d:
                rr.append(lane)  # rotate: next get serves the next lane
            meta = self._pending.pop(key)
            self._pri_counts[meta[0]] -= 1
            self._processing[key] = meta
            enqueued_at = self._enqueued_ts.pop(key, None)
            if self.metrics is not None and enqueued_at is not None:
                self.metrics.controller_queue_latency.labels(
                    controller=self.name
                ).observe(max(0.0, time.monotonic() - enqueued_at))
            self._report_depth()
            return key
        raise RuntimeError("pending map and lanes disagree")  # unreachable

    def done(self, key: str) -> None:
        """Processing finished; a dirty re-add (event arrived mid-reconcile)
        flushes back onto the queue immediately."""
        meta = self._processing.pop(key, None)
        dirty = self._dirty.pop(key, None)
        if dirty is not None and not self._shutting_down:
            self.add(key, *dirty)
        elif meta is None and dirty is None:
            pass  # done() on an unknown key is a no-op by design

    def fail(self, key: str) -> float:
        """Reconcile failed: schedule the key back with per-item exponential
        backoff (capped); returns the delay chosen.  Call before ``done`` so
        a dirty immediate re-add (fresh evidence) wins over the backoff."""
        n = self._failures.get(key, 0)
        self._failures[key] = n + 1
        delay = min(self.base * (2**n), self.cap)
        meta = self._processing.get(key) or (PRIORITY_NORMAL, DEFAULT_LANE)
        if self.metrics is not None:
            self.metrics.workqueue_retries_total.labels(queue=self.name).inc()
        # release the processing slot first or add_after's add path would
        # divert into the dirty set
        self._processing.pop(key, None)
        self.add_after(key, delay, *meta)
        return delay

    def forget(self, key: str) -> None:
        self._failures.pop(key, None)

    def abort(self, key: str) -> None:
        """The worker died mid-reconcile (cancelled): put the key straight
        back so a resumed worker finishes the job."""
        meta = self._processing.pop(key, (PRIORITY_NORMAL, DEFAULT_LANE))
        dirty = self._dirty.pop(key, None)
        if dirty is not None and dirty[0] < meta[0]:
            meta = dirty
        if not self._shutting_down:
            self.add(key, *meta)

    def _count_coalesced(self) -> None:
        if self.metrics is not None:
            self.metrics.workqueue_coalesced_total.labels(queue=self.name).inc()

    # ------------------------------------------------------------------
    def shut_down(self) -> None:
        """Stop accepting work and cancel scheduled timers; queued keys keep
        draining through ``get()`` until empty, then ``get()`` raises
        :class:`ShutDown` (clean-drain semantics)."""
        self._shutting_down = True
        for t in self._timers.values():
            t.cancel()
        self._timers.clear()
        self._dirty.clear()
        self._ready.set()  # wake waiters so they observe the shutdown

    async def drain(self, timeout: float = 5.0) -> bool:
        """Wait until nothing is pending or processing; True on success."""
        deadline = time.monotonic() + timeout
        while self._pending or self._processing:
            if time.monotonic() >= deadline:
                return False
            await asyncio.sleep(0.005)
        return True
