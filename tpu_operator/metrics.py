"""Operator Prometheus metrics.

Reference analogue: controllers/operator_metrics.go:29-201 — reconciliation
status/total/failed/last-success gauges+counters, node-count gauge, label
presence gauge, and the upgrade-state gauge family fed by the upgrade
controller (gpu_operator_nodes_upgrades_*) — plus the duration Histograms
controller-runtime emits for free in the reference
(controller_runtime_reconcile_time_seconds and the rest_client families),
fed here by the span layer in ``tpu_operator/obs/trace.py``.
"""

from __future__ import annotations

from typing import Optional

from prometheus_client import CollectorRegistry, Counter, Gauge, Histogram

# reconciliation_status encodings (operator_metrics.go:52-64)
RECONCILE_SUCCESS = 1
RECONCILE_NOT_READY = 0
RECONCILE_FAILED = -1

# controller-runtime-ish latency buckets: sub-10ms fake-cluster calls up to
# the 45s no-TPU poll / multi-minute operand rollouts
DURATION_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

# API-requests-per-reconcile buckets: a cached steady-state pass lands in the
# 0 bucket; convergence passes over large clusters run to the hundreds
REQUEST_COUNT_BUCKETS = (0, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000)


class OperatorMetrics:
    """Instance-scoped registry so tests can run many operators per process."""

    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry or CollectorRegistry()
        g = lambda name, doc: Gauge(name, doc, registry=self.registry)  # noqa: E731
        c = lambda name, doc: Counter(name, doc, registry=self.registry)  # noqa: E731
        self.tpu_nodes_total = g(
            "tpu_operator_tpu_nodes_total", "Number of nodes with TPU accelerators"
        )
        self.reconciliation_status = g(
            "tpu_operator_reconciliation_status",
            "1=success, 0=notReady, -1=failed (last reconcile)",
        )
        self.reconciliation_total = c(
            "tpu_operator_reconciliation_total", "Total reconciliations"
        )
        self.reconciliation_failed_total = c(
            "tpu_operator_reconciliation_failed_total", "Failed reconciliations"
        )
        self.reconciliation_last_success_ts = g(
            "tpu_operator_reconciliation_last_success_ts_seconds",
            "Unix timestamp of the last successful reconcile",
        )
        self.has_gke_tpu_labels = g(
            "tpu_operator_has_gke_tpu_labels",
            "1 when at least one node carries GKE TPU labels (has_nfd_labels analogue)",
        )
        self.operand_state = Gauge(
            "tpu_operator_operand_state",
            "Per-state sync result: 1=ready/disabled, 0=notReady, -1=error",
            ["state"],
            registry=self.registry,
        )
        # upgrade-state gauge family (operator_metrics.go upgrade gauges)
        self.upgrades_in_progress = g(
            "tpu_operator_nodes_upgrades_in_progress", "Nodes currently upgrading"
        )
        self.upgrades_done = g("tpu_operator_nodes_upgrades_done", "Nodes upgraded")
        self.upgrades_failed = g("tpu_operator_nodes_upgrades_failed", "Nodes failed upgrade")
        self.upgrades_available = g(
            "tpu_operator_nodes_upgrades_available", "Nodes available for upgrade"
        )
        self.upgrades_pending = g("tpu_operator_nodes_upgrades_pending", "Nodes pending upgrade")
        self.remediation_in_progress = g(
            "tpu_operator_nodes_remediation_in_progress",
            "Nodes currently re-validating (remediation controller)",
        )
        self.remediation_failed = g(
            "tpu_operator_nodes_remediation_failed",
            "Nodes whose requested re-validation failed (sticky until re-requested)",
        )
        self.auto_upgrade_enabled = g(
            "tpu_operator_runtime_auto_upgrade_enabled", "1 when auto-upgrade is on"
        )
        # node health engine (controllers/health.py; docs/ROBUSTNESS.md)
        self.health_unhealthy_nodes = g(
            "tpu_operator_nodes_health_unhealthy",
            "Nodes currently tripped by the health engine's hysteresis",
        )
        self.health_degraded_nodes = g(
            "tpu_operator_nodes_health_degraded",
            "Healthy nodes marked slice-degraded because a slice peer is unhealthy",
        )
        self.health_observe_only = g(
            "tpu_operator_health_observe_only",
            "1 while the disruption budget is exhausted and the engine "
            "observes without actuating (alert: a fleet-wide signal source "
            "is probably lying)",
        )
        self.health_trips_total = c(
            "tpu_operator_health_trips_total",
            "Nodes tripped unhealthy by the hysteresis detector",
        )
        self.health_actuations_total = Counter(
            "tpu_operator_health_actuations_total",
            "Escalation-ladder actions taken on tripped nodes",
            ["action"],  # remediate | restart-runtime | quarantine
            registry=self.registry,
        )
        self.health_actuations_denied_total = c(
            "tpu_operator_health_actuations_denied_total",
            "Actuations withheld because the disruption budget was exhausted",
        )
        # live workload migration (controllers/migration.py;
        # docs/ROBUSTNESS.md "Live migration")
        self.migrations_total = Counter(
            "tpu_operator_migrations_total",
            "Workload-pod migration outcomes along the drain paths: "
            "requested (migrate annotation stamped), migrated (checkpoint "
            "complete, restore pod rescheduled), timeout (checkpoint never "
            "completed inside migration.timeoutSeconds), failed (workload "
            "crashed mid-checkpoint)",
            ["outcome"],
            registry=self.registry,
        )
        self.drain_evictions_total = Counter(
            "tpu_operator_drain_evictions_total",
            "Workload-pod deletions along the operator's drain paths, by "
            "owning controller (upgrade | remediation | health) and reason: "
            "migrated (deleted after a completed checkpoint+reschedule), "
            "timeout (migration fell back to evict), failed (checkpoint "
            "crashed), forced (drain.force), no-handler (pod never opted "
            "into migration), completed (pod finished on its own before "
            "any migrate request — cleanup, nothing lost)",
            ["controller", "reason"],
            registry=self.registry,
        )
        # duration Histograms, fed by the obs.trace span layer
        h = lambda name, doc, label: Histogram(  # noqa: E731
            name, doc, [label], registry=self.registry, buckets=DURATION_BUCKETS
        )
        self.reconcile_duration = h(
            "tpu_operator_reconcile_duration_seconds",
            "Reconcile pass duration per controller "
            "(controller_runtime_reconcile_time_seconds analogue)",
            "controller",
        )
        self.state_sync_duration = h(
            "tpu_operator_state_sync_duration_seconds",
            "Per-operand-state sync duration within a reconcile pass",
            "state",
        )
        self.k8s_request_duration = h(
            "tpu_operator_k8s_request_duration_seconds",
            "Kubernetes API request latency by verb "
            "(rest_client_request_duration_seconds analogue)",
            "verb",
        )
        self.apply_duration = h(
            "tpu_operator_apply_duration_seconds",
            "create_or_update latency per object kind",
            "kind",
        )
        self.workload_phase_duration = h(
            "tpu_operator_workload_phase_duration_seconds",
            "Validator component / workload check phase duration",
            "phase",
        )
        # cached + concurrent reconcile pipeline (docs/PERFORMANCE.md)
        self.cache_hits_total = Counter(
            "tpu_operator_informer_cache_hits_total",
            "Reads served from the informer-backed CachedReader, by kind",
            ["kind"],
            registry=self.registry,
        )
        self.cache_misses_total = Counter(
            "tpu_operator_informer_cache_misses_total",
            "Cached reads that fell back to a live API request, by kind",
            ["kind"],
            registry=self.registry,
        )
        self.inflight_applies = g(
            "tpu_operator_inflight_applies",
            "create_or_update calls currently in flight (bounded fan-out)",
        )
        self.api_requests_per_reconcile = Histogram(
            "tpu_operator_k8s_requests_per_reconcile",
            "Kubernetes API requests issued within one reconcile pass "
            "(0 = fully cache-served steady state)",
            registry=self.registry,
            buckets=REQUEST_COUNT_BUCKETS,
        )
        # API resilience surface (k8s/retry.py; docs/ROBUSTNESS.md)
        self.api_breaker_state = g(
            "tpu_operator_api_breaker_state",
            "Apiserver circuit breaker: 0=closed, 1=half-open, 2=open "
            "(open == manager in degraded mode; alert on > 0)",
        )
        self.k8s_request_retries_total = Counter(
            "tpu_operator_k8s_request_retries_total",
            "API request retries issued by the client retry policy, by verb",
            ["verb"],
            registry=self.registry,
        )
        self.degraded_mode_total = c(
            "tpu_operator_degraded_mode_entered_total",
            "Times the manager entered degraded mode (breaker opened)",
        )
        # controller saturation surface (controllers/runtime.py; the signals
        # reconcile-plane sharding will shed load on — docs/OBSERVABILITY.md
        # "Fleet telemetry & SLOs")
        self.controller_queue_depth = Gauge(
            "tpu_operator_controller_queue_depth",
            "Keys queued (not yet popped) per controller workqueue",
            ["controller"],
            registry=self.registry,
        )
        self.controller_queue_latency = Histogram(
            "tpu_operator_controller_queue_latency_seconds",
            "Time a key waited in the workqueue between enqueue and pop "
            "(workqueue_queue_duration_seconds analogue)",
            ["controller"],
            registry=self.registry,
            buckets=DURATION_BUCKETS,
        )
        self.controller_requeues_total = Counter(
            "tpu_operator_controller_requeues_total",
            "Keys re-enqueued per controller: reason=failure (reconcile "
            "raised, backoff applied) or scheduled (reconcile asked for a "
            "delayed revisit)",
            ["controller", "reason"],
            registry=self.registry,
        )
        self.controller_busy_fraction = Gauge(
            "tpu_operator_controller_busy_fraction",
            "EWMA fraction of wall time the controller worker spent "
            "reconciling vs waiting for work (1.0 = saturated worker)",
            ["controller"],
            registry=self.registry,
        )
        # shared priority/fairness workqueue framework (k8s/workqueue.py)
        # + hash-ring sharded delta plane (k8s/sharding.py,
        # controllers/plane.py) — docs/PERFORMANCE.md "Delta reconcile &
        # sharding".  Label spaces are bounded: queue = controller/shard
        # names, priority = high|normal|low, shard = node-shard-<i>.
        self.workqueue_depth = Gauge(
            "tpu_operator_workqueue_depth",
            "Keys pending per workqueue per priority class "
            "(high = health/remediation actuation, normal = event-driven "
            "deltas, low = periodic resync sweeps)",
            ["queue", "priority"],
            registry=self.registry,
        )
        self.workqueue_retries_total = Counter(
            "tpu_operator_workqueue_retries_total",
            "Keys re-queued with per-item exponential backoff after a "
            "failed reconcile, per workqueue",
            ["queue"],
            registry=self.registry,
        )
        self.workqueue_coalesced_total = Counter(
            "tpu_operator_workqueue_coalesced_total",
            "Adds collapsed onto an already-pending or in-flight key "
            "(dedup/coalescing hits), per workqueue",
            ["queue"],
            registry=self.registry,
        )
        self.shard_reconciles_total = Counter(
            "tpu_operator_shard_reconciles_total",
            "Per-node delta reconciles executed per hash-ring worker shard",
            ["shard"],
            registry=self.registry,
        )
        self.shard_handoffs_total = c(
            "tpu_operator_shard_handoffs_total",
            "Hash-ring rebalances (shards added/removed); every handoff "
            "re-routes the moved keys and fences the old owner's writes",
        )
        self.shard_fence_rejections_total = c(
            "tpu_operator_shard_fence_rejections_total",
            "Mutating requests refused by a shard write fence because the "
            "hash ring reassigned the key mid-reconcile (each one is a "
            "double-actuation that did NOT happen)",
        )
        # multi-replica sharded plane (controllers/plane.py
        # LeasedNodePlane; docs/PERFORMANCE.md "Multi-replica sharding"):
        # cross-pod shard ownership via one Lease per shard.  Label space
        # bounded by consts.NODE_SHARDS.
        self.shard_lease_held = Gauge(
            "tpu_operator_shard_lease_held",
            "1 while this replica holds the shard's Lease (and therefore "
            "runs its Controller and caches its arc), else 0",
            ["shard"],
            registry=self.registry,
        )
        self.shard_lease_transitions_total = Counter(
            "tpu_operator_shard_lease_transitions_total",
            "Shard-Lease acquisitions and losses on this replica, per "
            "direction (every loss fences the shard's in-flight writes)",
            ["shard", "direction"],
            registry=self.registry,
        )
        # fleet telemetry plane (obs/fleet.py): windowed fleet rollups +
        # aggregator health.  Only ROLLUPS are exported — per-node series
        # stay inside the ring so operator-registry cardinality is bounded
        # by the metric catalogue, not the fleet size.
        self.fleet_quantile = Gauge(
            "tpu_operator_fleet_quantile",
            "Windowed fleet rollup per metric (default window): "
            "quantile is p50/p90/p99/min/max/mean/count",
            ["metric", "quantile"],
            registry=self.registry,
        )
        self.join_phase_seconds = Gauge(
            "tpu_operator_join_phase_seconds",
            "Windowed fleet rollup of the join->validated critical path, "
            "per propagated phase segment (runtime-ready / "
            "validator-scheduled / plugin-advertised / compile / "
            "collective); quantile is p50/p90/p99/min/max/mean/count",
            ["phase", "quantile"],
            registry=self.registry,
        )
        # continuous profiling & straggler attribution plane
        # (obs/profile.py; docs/OBSERVABILITY.md "Continuous profiling &
        # straggler attribution").  Only bounded rollups export: the phase
        # label is closed over obs.profile.STEP_PHASES (4 values) and the
        # quantile set is fixed, so the family is 4x7 series regardless of
        # fleet size; per-host and per-slice detail lives in
        # GET /debug/profile only.
        self.step_phase_seconds = Gauge(
            "tpu_operator_step_phase_seconds",
            "Windowed fleet rollup of per-step workload phase spans "
            "(compile / host-input / compute / collective-wait); "
            "quantile is p50/p90/p99/min/max/mean/count",
            ["phase", "quantile"],
            registry=self.registry,
        )
        self.step_skew_ratio = g(
            "tpu_operator_step_skew_ratio",
            "Worst per-slice straggler skew ratio at the newest evaluated "
            "barrier: (max-min per-host work) / mean step wall",
        )
        self.step_idle_fraction = g(
            "tpu_operator_step_idle_fraction",
            "Fraction of windowed step wall time spent in collective-wait "
            "fleet-wide (the learner-idle signal actor fleets scale off)",
        )
        self.stragglers_detected_total = c(
            "tpu_operator_stragglers_detected_total",
            "StragglerDetected verdicts fired by the per-slice skew "
            "detector (sustained over the configured step threshold)",
        )
        self.fleet_series = g(
            "tpu_operator_fleet_series",
            "Distinct (metric, labels) series currently held in the "
            "aggregator's ring buffers",
        )
        self.fleet_nodes_reporting = g(
            "tpu_operator_fleet_nodes_reporting",
            "Distinct node label values seen across fleet series in the "
            "default window",
        )
        self.fleet_samples_ingested_total = Counter(
            "tpu_operator_fleet_samples_ingested_total",
            "Samples ingested into the fleet aggregator, by source "
            "(span | push | node)",
            ["source"],
            registry=self.registry,
        )
        self.fleet_push_rejected_total = Counter(
            "tpu_operator_fleet_push_rejected_total",
            "Fleet ingest pushes rejected, by reason "
            "(too-large | bad-json | bad-shape | unknown-metric | series-cap)",
            ["reason"],
            registry=self.registry,
        )
        # declarative SLO engine (obs/fleet.py SLOEngine)
        self.slo_burn_rate = Gauge(
            "tpu_operator_slo_burn_rate",
            "Error-budget burn rate per SLO per evaluation window "
            "(1.0 = spending exactly the budget; alert thresholds are "
            "per-SLO burnRateThreshold)",
            ["slo", "window"],
            registry=self.registry,
        )
        self.slo_breached = Gauge(
            "tpu_operator_slo_breached",
            "1 while the SLO's multi-window burn-rate condition holds "
            "(SLOBurnRate fired, SLORecovered pending)",
            ["slo"],
            registry=self.registry,
        )
        self.slo_transitions_total = Counter(
            "tpu_operator_slo_transitions_total",
            "SLO breach/recovery transitions, by kind (fired | recovered)",
            ["slo", "kind"],
            registry=self.registry,
        )
        # fleet compile-artifact cache (workloads/compile_cache.py served
        # by the Manager's /compile-cache/* routes — docs/PERFORMANCE.md
        # "Compile cache & warm-pool validation")
        self.compile_cache_artifacts = g(
            "tpu_operator_compile_cache_artifacts",
            "Serialized-executable artifacts held by the fleet compile cache",
        )
        self.compile_cache_bytes = g(
            "tpu_operator_compile_cache_bytes",
            "Total bytes held by the fleet compile cache's artifact store",
        )
        self.compile_cache_requests_total = Counter(
            "tpu_operator_compile_cache_requests_total",
            "Fleet compile-cache operations, by outcome: stored (new "
            "artifact ingested), duplicate (idempotent re-publish), "
            "served (artifact download), rejected (corrupt/mis-keyed/"
            "over-cap upload)",
            ["outcome"],
            registry=self.registry,
        )
        # elastic multi-slice scheduler (controllers/slicescheduler.py +
        # tpu_operator/scheduling/; docs/SCHEDULING.md).  Label spaces are
        # bounded enums (phase, outcome), never request names.
        self.slice_requests = Gauge(
            "tpu_operator_slice_requests",
            "TPUSliceRequest count by status.phase "
            "(Pending | Bound | Unschedulable)",
            ["phase"],
            registry=self.registry,
        )
        self.slice_placements_total = Counter(
            "tpu_operator_slice_placements_total",
            "Slice-scheduler decisions, by outcome: placed (request bound "
            "to capacity), unschedulable (no eligible capacity can satisfy "
            "it), preempted (grant lost its arc to failure/quarantine and "
            "was re-placed or re-queued), compacted (defrag moved a grant "
            "onto a smaller free arc through migration), grown (elastic "
            "grant re-placed onto bigger capacity), released (request "
            "deleted or labels garbage-collected)",
            ["outcome"],
            registry=self.registry,
        )
        self.slice_placement_latency = Histogram(
            "tpu_operator_slice_placement_latency_seconds",
            "Pending->Bound latency per TPUSliceRequest (first observed "
            "pending to the bind patch landing)",
            registry=self.registry,
            buckets=DURATION_BUCKETS,
        )
        # preemption economy (docs/SCHEDULING.md "Preemption economy"):
        # reclaim-by-demotion of reclaimable grants for guaranteed claimants
        self.slice_preemptions_total = Counter(
            "tpu_operator_slice_preemptions_total",
            "Preemption-economy transitions, by outcome: demoted "
            "(reclaimable victim checkpoint-resharded onto smaller "
            "capacity), parked (no capacity satisfied the victim's "
            "minTopology; snapshot published, arc released), resumed "
            "(parked request re-placed and restored), reclaim-failed "
            "(reclaim aborted: non-migratable pod or degraded target), "
            "park-timeout (parkTimeoutSeconds expired; degraded to "
            "Unschedulable)",
            ["outcome"],
            registry=self.registry,
        )
        self.parked_slices = g(
            "tpu_operator_parked_slices",
            "TPUSliceRequests currently Parked: reclaimed with their final "
            "snapshot published, waiting for capacity to auto-resume",
        )
        self.slice_reclaim_latency = Histogram(
            "tpu_operator_slice_reclaim_latency_seconds",
            "Reclaim-to-bound latency per guaranteed claimant: reclaim "
            "move armed (victim selected) to the claimant's bind landing",
            registry=self.registry,
            buckets=DURATION_BUCKETS,
        )
        self.slice_fragmentation_ratio = g(
            "tpu_operator_slice_fragmentation_ratio",
            "Free-capacity fragmentation: 1 - largest_free_arc_chips / "
            "total_free_chips over eligible free arcs (0 = one contiguous "
            "box holds all free capacity; defrag arms above "
            "scheduling.defragThreshold)",
        )
        # chip-time accounting ledger (obs/accounting.py; docs/
        # OBSERVABILITY.md "Chip-time accounting").  {state} is the fixed
        # six-value taxonomy, {request} is a live-grant label removed on
        # release (bounded by concurrent TPUSliceRequests, the slo_breached
        # precedent).
        self.chip_seconds_total = Counter(
            "tpu_operator_chip_seconds_total",
            "Attributed chip-seconds by ledger state: busy_useful (steps "
            "past the last durable checkpoint, decoded tokens), busy_wasted "
            "(replayed-step recompute, checkpoint/restore overhead), "
            "idle_granted (bound but not stepping), idle_free, draining, "
            "quarantined.  Summed across states this equals tracked chips "
            "x wall-clock (conservation invariant, 1% tolerance)",
            ["state"],
            registry=self.registry,
        )
        self.goodput_ratio = g(
            "tpu_operator_goodput_ratio",
            "busy_useful / (busy_useful + busy_wasted) over the ledger's "
            "lifetime: the fraction of busy chip-time that advanced work "
            "(1.0 when no busy evidence yet)",
        )
        self.chip_utilization = g(
            "tpu_operator_chip_utilization",
            "(busy_useful + busy_wasted) / granted chip-seconds: how much "
            "of what the scheduler granted actually stepped (ROADMAP item "
            "3's packing signal)",
        )
        self.grant_utilization = Gauge(
            "tpu_operator_grant_utilization",
            "Per-live-grant busy/granted chip-second ratio (label removed "
            "when the grant is released)",
            ["request"],
            registry=self.registry,
        )
        # batched revalidation coordinator (controllers/revalidation.py):
        # warm-pool scheduling of fleet-wide re-validation waves
        self.revalidation_pending = g(
            "tpu_operator_nodes_revalidation_pending",
            "Nodes queued (validate=pending) behind the revalidation "
            "coordinator's seeder-first, budget-bounded promotion order",
        )
        self.revalidation_in_flight = g(
            "tpu_operator_nodes_revalidation_in_flight",
            "Nodes currently admitted to re-validation by the coordinator "
            "(validate=requested or remediation revalidating)",
        )
        self.revalidation_promotions_total = Counter(
            "tpu_operator_revalidation_promotions_total",
            "Coordinator promotions of pending nodes into re-validation, "
            "by role: seeder (first of its kind — compiles and publishes "
            "artifacts) or warm (fans out against the seeded fleet cache)",
            ["role"],
            registry=self.registry,
        )
        self.revalidation_demotions_total = c(
            "tpu_operator_revalidation_demotions_total",
            "Thundering-herd validate=requested nodes demoted to pending "
            "by the coordinator (wave intake beyond the disruption budget)",
        )
        # serving front door (tpu_operator/serving/frontdoor.py;
        # docs/SERVING.md "Front door").  Label spaces are bounded enums
        # (outcome, state) — NEVER session ids or request ids: per-session
        # series on a millions-of-users endpoint is the canonical
        # cardinality explosion, and the metric-labels analysis rule pins
        # the frontdoor family to this exact allowlist.
        self.frontdoor_routed_total = Counter(
            "tpu_operator_frontdoor_routed_total",
            "Requests placed onto a replica, by routing outcome: sticky "
            "(session's bound replica), spillover (new session or rebind "
            "onto the least-loaded fresh replica), retry (re-placed after "
            "replica loss, spending session retry budget), replay "
            "(resubmitted on the restored replica after a drain handoff)",
            ["outcome"],
            registry=self.registry,
        )
        self.frontdoor_shed_total = c(
            "tpu_operator_frontdoor_shed_total",
            "Requests shed with an honest 429 + Retry-After because no "
            "fresh replica had admission headroom (counted separately "
            "from failures: a shed client was told to come back, never "
            "silently dropped)",
        )
        self.frontdoor_hedges_total = Counter(
            "tpu_operator_frontdoor_hedges_total",
            "Single-hedge policy outcomes: fired (first token overdue, a "
            "second prefill placed — idempotent work only), won (hedge "
            "delivered first and the primary was cancelled), wasted "
            "(primary delivered first and the hedge was cancelled "
            "pre-decode — no double billing either way)",
            ["outcome"],
            registry=self.registry,
        )
        self.frontdoor_handoffs_total = Counter(
            "tpu_operator_frontdoor_handoffs_total",
            "Draining-replica handoff transitions: parked (drain "
            "checkpointed the replica; its sessions hold at the router), "
            "restored (the restore pod re-attached and parked sessions "
            "rebound), replayed (in-flight requests absent from the "
            "snapshot resubmitted at the snapshot's schedule position)",
            ["outcome"],
            registry=self.registry,
        )
        self.frontdoor_failed_total = c(
            "tpu_operator_frontdoor_failed_total",
            "Requests failed back to the client after the session retry "
            "budget was exhausted (the serve-fleet soak gates this at 0: "
            "every loss path must end in retry, replay, or an honest shed)",
        )
        self.frontdoor_sessions = g(
            "tpu_operator_frontdoor_sessions",
            "Live sessions bound to a replica at the front door",
        )
        self.frontdoor_replicas = Gauge(
            "tpu_operator_frontdoor_replicas",
            "Replica fleet as the router sees it, by state: ready, "
            "draining (checkpoint requested, sessions parking), parked "
            "(checkpoint taken, restore pending), unknown (capacity "
            "evidence stale past the freshness bound), dead (declared "
            "lost; in-flight work retried away)",
            ["state"],
            registry=self.registry,
        )
        self.frontdoor_ttft_seconds = Histogram(
            "tpu_operator_frontdoor_ttft_seconds",
            "Endpoint-level time-to-first-token: submit at the front door "
            "to first delivered token, across retries/hedges/handoffs "
            "(the client-visible number, not the per-replica one)",
            registry=self.registry,
            buckets=DURATION_BUCKETS,
        )
        self.frontdoor_tpot_seconds = Histogram(
            "tpu_operator_frontdoor_tpot_seconds",
            "Endpoint-level time-per-output-token between consecutively "
            "delivered tokens of one request (dedup'd across sources: a "
            "handoff or hedge never double-counts a position)",
            registry=self.registry,
            buckets=DURATION_BUCKETS,
        )
        self.frontdoor_tokens_billed_total = c(
            "tpu_operator_frontdoor_decode_tokens_billed_total",
            "Decode tokens delivered to clients, billed exactly once per "
            "(request, position) — the no-double-billing invariant the "
            "chaos suite pins across hedges and replica-loss retries",
        )
        self.frontdoor_dup_tokens_total = c(
            "tpu_operator_frontdoor_duplicate_tokens_discarded_total",
            "Tokens that arrived for an already-delivered position (late "
            "hedge loser, post-restore overlap) and were discarded "
            "unbilled — nonzero here with billed == delivered is the "
            "dedup layer doing its job",
        )
