"""Shared observability layer: spans/traces, Kubernetes Events, JSON logs.

The reference operator gets most of this for free from controller-runtime
(reconcile duration histograms, workqueue metrics) and client-go
(``record.EventRecorder`` with its dedup correlator); this package is the
in-tree equivalent every controller and the apply layer report through:

- ``obs.trace``   — context-manager spans with a contextvar-propagated
  reconcile id, feeding the Prometheus Histograms on ``OperatorMetrics``
  and an in-memory ring buffer served at ``/debug/traces``; the
  serializable ``TraceContext`` (``TPU_TRACEPARENT``) + ``Tracer.adopt``
  carry one trace id across process boundaries (operator → rendered pod
  env → validator phases → flight samples → fleet exemplars).
- ``obs.events``  — a ``v1/Event`` recorder with client-go-style
  dedup + count bumping.
- ``obs.logging`` — structured JSON logging (opt-in via
  ``--log-format=json``) whose records carry the active reconcile id,
  controller, and operand state from the span context.
- ``obs.flight``  — per-step workload flight recorder: JSONL samples
  tagged with the active span id, persisted next to the workload's
  result drop-box and pushed to the node metrics agent's ``/push``
  endpoint for live ``source="workload"`` Prometheus series.
- ``obs.fleet``   — the fleet telemetry plane: ring-buffer time series
  aggregating spans, the agents' push hop, and informer-cached node
  evidence into windowed rollups (``/debug/fleet``,
  ``tpu_operator_fleet_*``) plus the declarative SLO burn-rate engine
  (``SLOBurnRate``/``SLORecovered`` Events, health-engine signal) and
  the join→validated critical-path breakdown
  (``join_phase_seconds{node,phase}`` →
  ``tpu_operator_join_phase_seconds``).
- ``obs.explain`` — the per-node causal timeline + blocking-on verdict
  behind ``GET /debug/explain?node=``: node state transitions, deduped
  Events, SLO episodes, and propagated trace links in one document.
"""
