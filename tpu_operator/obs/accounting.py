"""Chip-time accounting: an event-sourced ledger over the fleet's chip-seconds.

ROADMAP items 1 (preemption economy) and 3 (sub-arc packing) need numbers
the latency/SLO plane (PRs 2/6/7) cannot produce: where did the
chip-seconds actually go?  This module attributes every chip-second of
every tracked TPU node to exactly one **state** and one **owner**:

``busy_useful``
    training steps past the last durable checkpoint, and serving decode
    intervals that produced tokens;
``busy_wasted``
    recompute of steps replayed after a restore, compile time, and
    checkpoint/restore overhead;
``idle_granted``
    bound to a ``TPUSliceRequest`` (the node carries
    ``consts.SLICE_REQUEST_LABEL``) but no workload evidence of stepping;
``idle_free``
    schedulable capacity nobody owns;
``draining``
    a migration in flight (migrate annotation stamped / node cordoned);
``quarantined``
    the health engine's verdict labels exclude the node from capacity.

Two layers keep the books honest:

* **Occupancy** is sampled from the same node stamps the slice scheduler
  already reads each pass (``scheduling.arcs_from_nodes``): assignment
  labels, health labels, ``spec.unschedulable``.  Every tracked node is in
  exactly one occupancy state at all times, so the **conservation
  invariant** — summed attributed chip-seconds == tracked chips x
  wall-clock — holds by construction; :meth:`ChipTimeLedger.conservation`
  computes both sides independently and reports the drift (gated at 1% by
  the ``make goodput`` soak and the property tests).
* **Evidence** arrives through the agent push hop
  (``obs/fleet.FleetAggregator.ingest_push`` forwards workload counters
  here): cumulative useful/wasted busy seconds recorded by
  ``workloads/checkpoint.py``, replayed/lost step deltas, serving decoded
  tokens.  Evidence never creates chip-seconds — it *carves* the owner's
  granted bucket into busy_useful / busy_wasted / idle_granted, clamped so
  the carve can never exceed what occupancy granted.  A multi-host pusher
  or a replayed flight record can therefore skew the split but never break
  conservation.

Because occupancy is re-derived from node stamps every pass and evidence
counters are cumulative-with-reset-detection, the ledger is
**reconstructible after an operator restart**: a fresh instance fed one
``observe_arcs`` pass rebuilds every owner and state; the first push from
each workload re-seeds the evidence baselines without double counting.

Surfaced as bounded ``tpu_operator_chip_seconds_total{state}`` counters,
``tpu_operator_goodput_ratio`` / ``tpu_operator_chip_utilization`` gauges,
per-grant ``tpu_operator_grant_utilization{request}`` (removed on
release), and the ``GET /debug/accounting`` document (fleet rollup +
per-grant drill-down, joinable to /debug/explain and /debug/traces via
reconcile ids).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Iterable, Optional

from tpu_operator import consts
from tpu_operator.obs import trace
from tpu_operator.utils import deep_get

# Public state taxonomy (the bounded {state} label set — never grows per
# entity; see docs/OBSERVABILITY.md "Chip-time accounting").
STATE_BUSY_USEFUL = "busy_useful"
STATE_BUSY_WASTED = "busy_wasted"
STATE_IDLE_GRANTED = "idle_granted"
STATE_IDLE_FREE = "idle_free"
STATE_DRAINING = "draining"
STATE_QUARANTINED = "quarantined"

STATES = (
    STATE_BUSY_USEFUL,
    STATE_BUSY_WASTED,
    STATE_IDLE_GRANTED,
    STATE_IDLE_FREE,
    STATE_DRAINING,
    STATE_QUARANTINED,
)

# Internal occupancy states (busy/idle split inside a grant is carved from
# evidence at read time, so occupancy tracks the grant as one bucket).
_OCC_GRANTED = "granted"
_OCC_FREE = STATE_IDLE_FREE
_OCC_DRAINING = STATE_DRAINING
_OCC_QUARANTINED = STATE_QUARANTINED

# Evidence counters the ledger consumes from the push hop (names are the
# obs/flight COUNTER_KEYS catalogue names carried in agent pushes).
COUNTER_USEFUL_SECONDS = "tpu_workload_useful_seconds_total"
COUNTER_WASTED_SECONDS = "tpu_workload_wasted_seconds_total"
COUNTER_REPLAYED_STEPS = "tpu_workload_replayed_steps_total"
COUNTER_LOST_STEPS = "tpu_workload_lost_steps_total"
COUNTER_DECODED_TOKENS = "tpu_workload_serving_decoded_tokens_total"

# A serving push whose decoded-token counter advanced marks the replica
# busy_useful for the inter-push gap, capped so a stalled-then-revived
# pusher cannot claim an unbounded interval retroactively.
_SERVING_CREDIT_CAP_S = 120.0

# Draining marks set by the migration coordinator expire if neither an
# eviction nor a reschedule ever lands (handler crashed mid-drain and the
# annotation was wiped out-of-band) so a node cannot leak in ``draining``.
_DRAIN_TTL_S = 900.0

_TRANSITION_LOG_LIMIT = 256
_RELEASED_GRANTS_LIMIT = 64

# controllers/migration.MIGRATED, inlined to keep obs/ import-free of the
# controller layer (pinned equal by the accounting tests).
_REASON_MIGRATED = "migrated"


@dataclass
class _NodeTrack:
    """One tracked TPU node's current occupancy interval."""

    chips: int
    occ: str
    owner: str
    since: float
    tracked_s: float = 0.0  # closed chip-seconds, state-blind (wall side)


@dataclass
class _GrantMeta:
    """Per-owner drill-down row state (survives node churn within the
    grant; pruned ``_RELEASED_GRANTS_LIMIT`` deep once released)."""

    bound_ts: float
    reconcile_id: str = ""
    outcome: str = ""
    nodes: tuple = ()
    released_ts: float = 0.0
    release_reason: str = ""
    migrations: int = 0
    evictions: int = 0
    kills: int = 0
    lost_steps: float = 0.0
    replayed_steps: float = 0.0
    decoded_tokens: float = 0.0


@dataclass
class _Evidence:
    """Cumulative carve evidence for one owner (chip-seconds)."""

    useful: float = 0.0
    wasted: float = 0.0


class ChipTimeLedger:
    """Event-sourced chip-second attribution with a conservation invariant.

    Thread-hostile by design (single asyncio loop, like every controller
    object here); all methods are synchronous and cheap.
    """

    def __init__(self, metrics=None, fleet=None, clock=time.monotonic):
        self.metrics = metrics
        self.fleet = fleet
        self.clock = clock
        self._nodes: dict[str, _NodeTrack] = {}
        self._grants: dict[str, _GrantMeta] = {}
        self._released: deque[tuple[str, _GrantMeta]] = deque(
            maxlen=_RELEASED_GRANTS_LIMIT
        )
        # (occupancy state, owner) -> closed chip-seconds
        self._buckets: dict[tuple[str, str], float] = {}
        self._evidence: dict[str, _Evidence] = {}
        # (node, check, counter) -> last cumulative value seen (the
        # double-count guard: re-pushed windows delta to zero, process
        # restarts reset-detect back to the new value).
        self._baselines: dict[tuple[str, str, str], float] = {}
        # (node, check) -> ts of last serving credit
        self._serving_seen: dict[tuple[str, str], float] = {}
        self._draining: dict[str, float] = {}  # node -> mark ts
        self._retired_wall_s = 0.0
        self._transitions: deque[dict] = deque(maxlen=_TRANSITION_LOG_LIMIT)
        self._exported: dict[str, float] = {}

    # -- occupancy ------------------------------------------------------

    def observe_arcs(self, arcs, nodes: Iterable[dict], now: Optional[float] = None):
        """Fold one scheduler pass: re-derive every tracked node's
        occupancy from the same arcs + node objects the pass already
        holds (zero extra API verbs).  This is also the restart path — a
        fresh ledger is fully repopulated by its first call."""
        now = self.clock() if now is None else now
        by_name = {}
        for n in nodes:
            name = deep_get(n, "metadata", "name", default="")
            if name:
                by_name[name] = n
        seen: set[str] = set()
        for arc in arcs:
            chips_per_node = max(1, arc.chips // max(1, len(arc.nodes)))
            for node_name in arc.nodes:
                seen.add(node_name)
                node = by_name.get(node_name, {})
                occ, owner = self._classify(node_name, node, arc, now)
                self._upsert(node_name, chips_per_node, occ, owner, now)
                if occ == _OCC_GRANTED and owner and owner not in self._grants:
                    # restart reconstruction: the stamp is the ledger of
                    # record, so an owner first seen via labels gets a
                    # grant row even though note_grant never ran.
                    self._grants[owner] = _GrantMeta(
                        bound_ts=now, outcome="reconstructed",
                        nodes=tuple(arc.nodes),
                    )
        for gone in [n for n in self._nodes if n not in seen]:
            self._retire(gone, now)
        for name, track in self._nodes.items():
            if track.occ == _OCC_GRANTED and track.owner in self._grants:
                meta = self._grants[track.owner]
                if name not in meta.nodes:
                    meta.nodes = tuple(sorted(set(meta.nodes) | {name}))

    def _classify(self, name: str, node: dict, arc, now: float) -> tuple[str, str]:
        labels = deep_get(node, "metadata", "labels", default={}) or {}
        owner = labels.get(consts.SLICE_REQUEST_LABEL, "") or arc.assigned
        unhealthy = labels.get(consts.TPU_HEALTH_LABEL) == consts.HEALTH_UNHEALTHY
        state_label = labels.get(consts.HEALTH_STATE_LABEL, "")
        if unhealthy or state_label not in ("", consts.HEALTH_OK):
            self._draining.pop(name, None)
            return _OCC_QUARANTINED, owner
        mark = self._draining.get(name)
        if mark is not None and now - mark > _DRAIN_TTL_S:
            self._draining.pop(name, None)
            mark = None
        if mark is not None or deep_get(node, "spec", "unschedulable"):
            return _OCC_DRAINING, owner
        if owner:
            return _OCC_GRANTED, owner
        return _OCC_FREE, ""

    def _upsert(self, name: str, chips: int, occ: str, owner: str, now: float):
        track = self._nodes.get(name)
        if track is None:
            self._nodes[name] = _NodeTrack(chips, occ, owner, now)
            return
        if track.occ != occ or track.owner != owner or track.chips != chips:
            self._accrue(track, now)
            track.chips = chips
            track.occ = occ
            track.owner = owner
        else:
            self._accrue(track, now)

    def _accrue(self, track: _NodeTrack, now: float):
        dt = max(0.0, now - track.since)
        if dt:
            chip_s = track.chips * dt
            key = (track.occ, track.owner)
            self._buckets[key] = self._buckets.get(key, 0.0) + chip_s
            track.tracked_s += chip_s
        track.since = now

    def _retire(self, name: str, now: float):
        track = self._nodes.pop(name)
        self._accrue(track, now)
        self._retired_wall_s += track.tracked_s
        self._draining.pop(name, None)

    def advance(self, now: Optional[float] = None):
        """Close every open interval into its bucket (no state change)."""
        now = self.clock() if now is None else now
        for track in self._nodes.values():
            self._accrue(track, now)

    # -- transitions (the calls the ledger-transitions rule asserts) ----

    def note_grant(self, request: str, nodes=(), outcome: str = "placed",
                   now: Optional[float] = None):
        """A scheduler grant decision landed (bind / compaction / grow)."""
        now = self.clock() if now is None else now
        meta = self._grants.get(request)
        if meta is None:
            meta = _GrantMeta(bound_ts=now)
            self._grants[request] = meta
        meta.outcome = outcome
        meta.reconcile_id = trace.reconcile_id() or meta.reconcile_id
        if nodes:
            meta.nodes = tuple(sorted(nodes))
        self._event(now, "grant", owner=request, outcome=outcome)

    def note_release(self, request: str, reason: str = "released",
                     now: Optional[float] = None):
        """A scheduler release landed (GC / preemption / compaction src)."""
        now = self.clock() if now is None else now
        meta = self._grants.pop(request, None)
        if meta is not None:
            meta.released_ts = now
            meta.release_reason = reason
            meta.reconcile_id = trace.reconcile_id() or meta.reconcile_id
            self._released.append((request, meta))
        for name, track in self._nodes.items():
            if track.owner == request:
                self._draining.pop(name, None)
        self._event(now, "release", owner=request, outcome=reason)

    def note_draining(self, node: str, owner: str = "", reason: str = "",
                      now: Optional[float] = None):
        """The migration coordinator stamped a drain request."""
        now = self.clock() if now is None else now
        self._draining[node] = now
        track = self._nodes.get(node)
        if track is not None:
            self._accrue(track, now)
            track.occ = _OCC_DRAINING
            owner = owner or track.owner
        self._event(now, "draining", node=node, owner=owner, outcome=reason)

    def note_eviction(self, node: str, owner: str = "", controller: str = "",
                      reason: str = "", now: Optional[float] = None):
        """The drain path deleted a pod (the single kill funnel)."""
        now = self.clock() if now is None else now
        self._draining.pop(node, None)
        track = self._nodes.get(node)
        if track is not None and not owner:
            owner = track.owner
        meta = self._grants.get(owner)
        if meta is not None:
            meta.evictions += 1
            if reason != _REASON_MIGRATED:
                meta.kills += 1
        self._event(now, "eviction", node=node, owner=owner,
                    outcome=reason or controller)

    def note_migrated(self, node: str, owner: str = "", controller: str = "",
                      now: Optional[float] = None):
        """A checkpointed pod was rescheduled (drain completed cleanly)."""
        now = self.clock() if now is None else now
        self._draining.pop(node, None)
        track = self._nodes.get(node)
        if track is not None and not owner:
            owner = track.owner
        meta = self._grants.get(owner)
        if meta is not None:
            meta.migrations += 1
        self._event(now, "migrated", node=node, owner=owner,
                    outcome=controller)

    def _event(self, now: float, kind: str, node: str = "", owner: str = "",
               outcome: str = ""):
        self._transitions.append({
            "ts": round(now, 3),
            "event": kind,
            "node": node,
            "owner": owner,
            "outcome": outcome,
            "reconcile_id": trace.reconcile_id(),
        })

    # -- evidence (the agent push hop) ----------------------------------

    def observe_push(self, node: str, workloads: dict,
                     now: Optional[float] = None):
        """Fold one agent push's workload counters into carve evidence.

        Counters are cumulative per workload process; deltas are taken
        against per-(node, check, counter) baselines with reset
        detection, so a re-pushed window credits zero (the double-count
        guard) and a restore's fresh process re-seeds from its own zero."""
        now = self.clock() if now is None else now
        track = self._nodes.get(node)
        owner = track.owner if track is not None else ""
        owner_chips = self._owner_chips(owner) if owner else 0
        ev = self._evidence.setdefault(owner, _Evidence()) if owner else None
        meta = self._grants.get(owner)
        for check, payload in (workloads or {}).items():
            counters = (payload or {}).get("counters") or {}
            useful_s = self._delta(node, check, COUNTER_USEFUL_SECONDS, counters)
            wasted_s = self._delta(node, check, COUNTER_WASTED_SECONDS, counters)
            replayed = self._delta(node, check, COUNTER_REPLAYED_STEPS, counters)
            lost = self._delta(node, check, COUNTER_LOST_STEPS, counters)
            tokens = self._delta(node, check, COUNTER_DECODED_TOKENS, counters)
            if ev is not None:
                # A step occupies the whole grant, not just the pushing
                # host — evidence scales by owner chips and the carve
                # clamp absorbs multi-host double pushes.
                ev.useful += useful_s * owner_chips
                ev.wasted += wasted_s * owner_chips
                if tokens > 0:
                    last = self._serving_seen.get((node, check))
                    gap = min(_SERVING_CREDIT_CAP_S,
                              now - last if last is not None else 0.0)
                    ev.useful += max(0.0, gap) * owner_chips
            if COUNTER_DECODED_TOKENS in counters:
                self._serving_seen[(node, check)] = now
            if meta is not None:
                meta.replayed_steps += replayed
                meta.lost_steps += lost
                meta.decoded_tokens += tokens

    def _delta(self, node: str, check: str, counter: str, counters: dict) -> float:
        if counter not in counters:
            return 0.0
        try:
            value = float(counters[counter])
        except (TypeError, ValueError):
            return 0.0
        key = (node, check, counter)
        last = self._baselines.get(key)
        self._baselines[key] = value
        if last is None or value < last:  # first sight or counter reset
            return max(0.0, value)
        return value - last

    def _owner_chips(self, owner: str) -> int:
        return sum(t.chips for t in self._nodes.values() if t.owner == owner)

    # -- read side ------------------------------------------------------

    def _carve(self) -> tuple[dict[str, float], dict[str, dict]]:
        """Split each owner's granted bucket by evidence, clamped so the
        six public states always sum to exactly the occupancy total."""
        states = {s: 0.0 for s in STATES}
        owners: dict[str, dict] = {}
        for (occ, owner), chip_s in self._buckets.items():
            if occ == _OCC_GRANTED:
                row = owners.setdefault(owner, {"granted": 0.0,
                                                "draining": 0.0,
                                                "quarantined": 0.0})
                row["granted"] += chip_s
            elif occ in (_OCC_DRAINING, _OCC_QUARANTINED):
                states[occ] += chip_s
                if owner:
                    row = owners.setdefault(owner, {"granted": 0.0,
                                                    "draining": 0.0,
                                                    "quarantined": 0.0})
                    row[occ] += chip_s
            else:
                states[STATE_IDLE_FREE] += chip_s
        for owner, row in owners.items():
            ev = self._evidence.get(owner, _Evidence())
            granted = row["granted"]
            useful = min(ev.useful, granted)
            wasted = min(ev.wasted, granted - useful)
            row[STATE_BUSY_USEFUL] = useful
            row[STATE_BUSY_WASTED] = wasted
            row[STATE_IDLE_GRANTED] = granted - useful - wasted
            states[STATE_BUSY_USEFUL] += useful
            states[STATE_BUSY_WASTED] += wasted
            states[STATE_IDLE_GRANTED] += granted - useful - wasted
        return states, owners

    def useful_chip_seconds(self, now: Optional[float] = None) -> dict:
        """Per live grant, the busy-useful chip-seconds accrued so far —
        the "useful work at risk" input the preemption economy's victim
        scoring ranks on (scheduling.victim_score): among equal-priority
        reclaimable grants, the one that has banked the least useful
        work is demoted first."""
        self.advance(now)
        _, owners = self._carve()
        return {
            owner: round(row.get(STATE_BUSY_USEFUL, 0.0), 6)
            for owner, row in owners.items()
            if owner in self._grants
        }

    def conservation(self, now: Optional[float] = None) -> dict:
        """Both sides of the invariant, computed independently: the wall
        side from state-blind per-node tracking, the attributed side from
        the state buckets."""
        now = self.clock() if now is None else now
        self.advance(now)
        wall = self._retired_wall_s + sum(
            t.tracked_s for t in self._nodes.values()
        )
        attributed = sum(self._buckets.values())
        drift = abs(attributed - wall) / wall if wall > 0 else 0.0
        return {
            "wall_chip_seconds": round(wall, 6),
            "attributed_chip_seconds": round(attributed, 6),
            "drift": round(drift, 6),
        }

    def rollup(self, now: Optional[float] = None) -> dict:
        """The headline ratios (also what lands in the fleet rings)."""
        self.advance(self.clock() if now is None else now)
        states, _ = self._carve()
        busy = states[STATE_BUSY_USEFUL] + states[STATE_BUSY_WASTED]
        granted = busy + states[STATE_IDLE_GRANTED]
        goodput = states[STATE_BUSY_USEFUL] / busy if busy > 0 else 1.0
        utilization = busy / granted if granted > 0 else 0.0
        return {
            "goodput_ratio": round(goodput, 6),
            "chip_utilization": round(utilization, 6),
        }

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The ``GET /debug/accounting`` document."""
        now = self.clock() if now is None else now
        self.advance(now)
        states, owners = self._carve()
        cons = self.conservation(now)
        busy = states[STATE_BUSY_USEFUL] + states[STATE_BUSY_WASTED]
        granted = busy + states[STATE_IDLE_GRANTED]
        grants = {}
        # released ring first: a name that was released and re-granted
        # (preempt → re-place) must surface its LIVE row, not the husk
        for name, meta in list(self._released) + list(self._grants.items()):
            row = owners.get(name, {})
            g = row.get("granted", 0.0)
            b = row.get(STATE_BUSY_USEFUL, 0.0) + row.get(STATE_BUSY_WASTED, 0.0)
            grants[name] = {
                "nodes": list(meta.nodes),
                "chips": self._owner_chips(name),
                "bound_ts": round(meta.bound_ts, 3),
                "outcome": meta.outcome,
                "reconcile_id": meta.reconcile_id,
                "released_ts": round(meta.released_ts, 3) or 0,
                "release_reason": meta.release_reason,
                "granted_chip_seconds": round(g, 6),
                "busy_useful": round(row.get(STATE_BUSY_USEFUL, 0.0), 6),
                "busy_wasted": round(row.get(STATE_BUSY_WASTED, 0.0), 6),
                "idle_granted": round(row.get(STATE_IDLE_GRANTED, 0.0), 6),
                "draining": round(row.get(STATE_DRAINING, 0.0), 6),
                "quarantined": round(row.get(STATE_QUARANTINED, 0.0), 6),
                "utilization": round(b / g, 6) if g > 0 else 0.0,
                "goodput_ratio": (
                    round(row.get(STATE_BUSY_USEFUL, 0.0) / b, 6)
                    if b > 0 else 1.0
                ),
                "migrations": meta.migrations,
                "evictions": meta.evictions,
                "kills": meta.kills,
                "lost_steps": round(meta.lost_steps, 3),
                "replayed_steps": round(meta.replayed_steps, 3),
                "decoded_tokens": round(meta.decoded_tokens, 3),
            }
        return {
            "ts": round(now, 3),
            "wall_chip_seconds": cons["wall_chip_seconds"],
            "attributed_chip_seconds": cons["attributed_chip_seconds"],
            "conservation_drift": cons["drift"],
            "goodput_ratio": (
                round(states[STATE_BUSY_USEFUL] / busy, 6) if busy > 0 else 1.0
            ),
            "chip_utilization": round(busy / granted, 6) if granted > 0 else 0.0,
            "states": {s: round(v, 6) for s, v in states.items()},
            "nodes": {
                name: {
                    "chips": t.chips,
                    "occupancy": t.occ,
                    "owner": t.owner,
                    "since": round(t.since, 3),
                }
                for name, t in sorted(self._nodes.items())
            },
            "grants": grants,
            "transitions": list(self._transitions),
        }

    # -- export ---------------------------------------------------------

    def export(self, now: Optional[float] = None):
        """Refresh the Prometheus families and (when wired) the fleet
        rings.  Counter families export monotonic deltas against the last
        export; a carve that momentarily re-splits busy time clamps at
        zero instead of decrementing (within the 1% tolerance)."""
        now = self.clock() if now is None else now
        self.advance(now)
        states, _ = self._carve()
        busy = states[STATE_BUSY_USEFUL] + states[STATE_BUSY_WASTED]
        granted = busy + states[STATE_IDLE_GRANTED]
        goodput = states[STATE_BUSY_USEFUL] / busy if busy > 0 else 1.0
        utilization = busy / granted if granted > 0 else 0.0
        if self.metrics is not None:
            for state, total in states.items():
                delta = total - self._exported.get(state, 0.0)
                if delta > 0:
                    self.metrics.chip_seconds_total.labels(state=state).inc(delta)
                self._exported[state] = max(total, self._exported.get(state, 0.0))
            self.metrics.goodput_ratio.set(goodput)
            self.metrics.chip_utilization.set(utilization)
            _, owners = self._carve()
            live = set(self._grants)
            for name in live:
                row = owners.get(name, {})
                g = row.get("granted", 0.0)
                b = (row.get(STATE_BUSY_USEFUL, 0.0)
                     + row.get(STATE_BUSY_WASTED, 0.0))
                self.metrics.grant_utilization.labels(request=name).set(
                    b / g if g > 0 else 0.0
                )
            for name, _meta in list(self._released):
                if name not in live:
                    try:
                        self.metrics.grant_utilization.remove(name)
                    except KeyError:
                        pass
        if self.fleet is not None:
            from tpu_operator.obs import fleet as obs_fleet

            self.fleet.ingest(
                obs_fleet.METRIC_GOODPUT_RATIO, goodput, ts=time.time(),
                source=obs_fleet.SOURCE_NODE,
            )
            self.fleet.ingest(
                obs_fleet.METRIC_CHIP_UTILIZATION, utilization, ts=time.time(),
                source=obs_fleet.SOURCE_NODE,
            )
