"""Kubernetes EventRecorder: real ``v1/Event`` objects with dedup.

Reference analogue: client-go's ``record.EventRecorder`` + EventCorrelator —
the reference emits Events on every operand transition and upgrade action;
repeated identical events bump ``count``/``lastTimestamp`` on the existing
object instead of flooding etcd.  Here the correlation cache is in-process
and keyed on (involvedObject, type, reason, message); posting is always
best-effort — an Event that cannot be written must never fail a reconcile.
"""

from __future__ import annotations

import copy
import datetime
import logging
import uuid
from collections import OrderedDict
from typing import Optional

from tpu_operator import consts
from tpu_operator.k8s.client import ApiClient, ApiError
from tpu_operator.obs import trace as obs_trace

log = logging.getLogger("tpu_operator.obs.events")

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"

# Event reasons (CamelCase like kubelet/client-go conventions).
REASON_OPERAND_READY = "OperandReady"
REASON_OPERAND_NOT_READY = "OperandNotReady"
REASON_OPERAND_ERROR = "OperandError"
REASON_OPERAND_DISABLED = "OperandDisabled"
REASON_RECONCILE_FAILED = "ReconcileFailed"
REASON_POLICY_READY = "Ready"
REASON_UPGRADE_STARTED = "UpgradeStarted"
REASON_UPGRADE_DONE = "UpgradeDone"
REASON_UPGRADE_FAILED = "UpgradeFailed"
REASON_REMEDIATION_STARTED = "RemediationStarted"
REASON_REVALIDATION_BATCHED = "RevalidationBatched"
REASON_REVALIDATION_SEEDED = "RevalidationSeeded"
REASON_REMEDIATION_HEALTHY = "RemediationHealthy"
REASON_REMEDIATION_FAILED = "RemediationFailed"
REASON_VALIDATION_FAILED = "ValidationFailed"
REASON_SELECTOR_CONFLICT = "SelectorConflict"
REASON_PERF_REGRESSED = "WorkloadPerfRegressed"
# node health engine (controllers/health.py; docs/ROBUSTNESS.md)
REASON_NODE_UNHEALTHY = "NodeUnhealthy"
# live workload migration (controllers/migration.py; docs/ROBUSTNESS.md
# "Live migration"): the checkpoint→reschedule→restore drain phase
REASON_MIGRATION_REQUESTED = "MigrationRequested"
REASON_MIGRATION_COMPLETED = "MigrationCompleted"
REASON_MIGRATION_TIMEOUT = "MigrationTimedOut"
REASON_MIGRATION_FAILED = "MigrationFailed"
REASON_WORKLOAD_EVICTED = "WorkloadEvicted"
REASON_NODE_RECOVERED = "NodeRecovered"
REASON_NODE_QUARANTINED = "NodeQuarantined"
REASON_HEALTH_BUDGET_EXHAUSTED = "HealthBudgetExhausted"
REASON_HEALTH_BUDGET_RESTORED = "HealthBudgetRestored"
# elastic multi-slice scheduler (controllers/slicescheduler.py;
# docs/SCHEDULING.md): request lifecycle + defrag-by-migration evidence
REASON_SLICE_PLACED = "SlicePlaced"
REASON_SLICE_PREEMPTED = "SlicePreempted"
REASON_SLICE_COMPACTED = "SliceCompacted"
REASON_SLICE_UNSCHEDULABLE = "SliceUnschedulable"
# preemption economy (docs/SCHEDULING.md "Preemption economy"): reclaim
# transitions of reclaimable grants demoted/parked for guaranteed claimants
REASON_SLICE_DEMOTED = "SliceDemoted"
REASON_SLICE_PARKED = "SliceParked"
REASON_SLICE_RESUMED = "SliceResumed"
REASON_SLICE_RECLAIM_FAILED = "SliceReclaimFailed"
# fleet SLO engine (obs/fleet.py; docs/OBSERVABILITY.md "Fleet telemetry
# & SLOs"): multi-window burn-rate breach / recovery
REASON_SLO_BURN_RATE = "SLOBurnRate"
REASON_SLO_RECOVERED = "SLORecovered"
# continuous profiling plane (obs/profile.py; docs/OBSERVABILITY.md
# "Continuous profiling & straggler attribution"): a slice member host
# sustained the worst per-barrier work skew / the slice went clean again
REASON_STRAGGLER_DETECTED = "StragglerDetected"
REASON_STRAGGLER_RECOVERED = "StragglerRecovered"
# resilience surface (docs/ROBUSTNESS.md): degraded mode + leadership
REASON_DEGRADED = "DegradedMode"
REASON_DEGRADED_RECOVERED = "DegradedModeRecovered"
REASON_LEADER_ELECTED = "LeaderElected"
REASON_LEADERSHIP_LOST = "LeadershipLost"


def namespace_ref(name: str) -> dict:
    """involvedObject for manager-scoped events (degraded mode has no
    narrower object to hang evidence on than the operator namespace)."""
    return {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": name}}


def lease_ref(namespace: str, name: str) -> dict:
    """involvedObject for leadership-transition events (client-go's leader
    elector reports on the lock object itself)."""
    return {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": name, "namespace": namespace},
    }


def pod_ref(name: str, namespace: str) -> dict:
    """involvedObject for per-pod drain/migration events (the evidence an
    operator of a lost training job greps for first)."""
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": namespace},
    }


def node_ref(name: str) -> dict:
    """Minimal involvedObject for a Node event when only the name is at
    hand (upgrade/remediation state transitions patch by name)."""
    return {"apiVersion": "v1", "kind": "Node", "metadata": {"name": name}}


def slicerequest_ref(name: str) -> dict:
    """involvedObject for slice-scheduler decisions on a TPUSliceRequest
    (the scheduler also mirrors each decision onto the member nodes via
    node_ref so /debug/explain timelines carry it)."""
    from tpu_operator.api import types as api_types

    return {
        "apiVersion": f"{api_types.GROUP}/{api_types.SLICE_REQUEST_VERSION}",
        "kind": api_types.SLICE_REQUEST_KIND,
        "metadata": {"name": name},
    }


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


class EventRecorder:
    def __init__(
        self,
        client: ApiClient,
        namespace: str,
        component: str = "tpu-operator",
        cache_size: int = 256,
    ):
        self.client = client
        self.namespace = namespace
        self.component = component
        self.cache_size = cache_size
        # correlation key -> last posted Event object (live copy)
        self._cache: OrderedDict[tuple, dict] = OrderedDict()
        # optional observer (obs.explain.ExplainEngine.observe_event):
        # called for every emitted Event — including ones whose API post
        # fails, because the timeline is evidence precisely when the
        # apiserver is wobbling.  Never allowed to raise into a post.
        self.sink = None

    # ------------------------------------------------------------------
    async def normal(
        self, involved: dict, reason: str, message: str,
        trace: Optional[dict] = None,
    ) -> Optional[dict]:
        return await self.event(involved, TYPE_NORMAL, reason, message, trace=trace)

    async def warning(
        self, involved: dict, reason: str, message: str,
        trace: Optional[dict] = None,
    ) -> Optional[dict]:
        return await self.event(involved, TYPE_WARNING, reason, message, trace=trace)

    async def event(
        self, involved: dict, type_: str, reason: str, message: str,
        trace: Optional[dict] = None,
    ) -> Optional[dict]:
        """Post (or count-bump) an Event.  Never raises: Events are
        evidence for humans/alerting, not reconcile control flow.

        ``trace`` carries explicit ``{"reconcile_id", "trace_id"}``
        correlation ids for posts that happen OUTSIDE the span that
        observed the transition (deferred queues, retry loops); it
        overrides the ambient context read."""
        if self.sink is not None:
            try:
                self.sink(involved, type_, reason, message)
            except Exception as e:  # noqa: BLE001
                log.debug("event sink failed: %s", e)
        try:
            return await self._post(involved, type_, reason, message, trace=trace)
        except Exception as e:  # noqa: BLE001
            log.warning("dropped event %s/%s: %s", type_, reason, e)
            return None

    # ------------------------------------------------------------------
    @staticmethod
    def _key(involved: dict, type_: str, reason: str, message: str) -> tuple:
        meta = involved.get("metadata", {}) or {}
        return (
            involved.get("kind", ""),
            meta.get("namespace", ""),
            meta.get("name", ""),
            meta.get("uid", ""),
            type_,
            reason,
            message,
        )

    async def _post(
        self, involved: dict, type_: str, reason: str, message: str,
        trace: Optional[dict] = None,
    ) -> Optional[dict]:
        key = self._key(involved, type_, reason, message)
        # the posting pass's correlation ids: kubectl get events -o yaml
        # joins to /debug/traces and /debug/explain through these
        trace_anns = {}
        rid = (trace or {}).get("reconcile_id") or obs_trace.reconcile_id()
        tid = (trace or {}).get("trace_id") or obs_trace.trace_id()
        if rid:
            trace_anns[consts.EVENT_RECONCILE_ID_ANNOTATION] = rid
        if tid:
            trace_anns[consts.EVENT_TRACE_ID_ANNOTATION] = tid
        cached = self._cache.get(key)
        if cached is not None:
            # correlator hit: bump count/lastTimestamp on the live object
            ev = copy.deepcopy(cached)
            ev["count"] = int(ev.get("count", 1)) + 1
            ev["lastTimestamp"] = _now()
            if trace_anns:
                # a repeat names the LATEST pass that observed it — the
                # join should lead to current evidence, not the first
                # occurrence hours ago
                ev["metadata"].setdefault("annotations", {}).update(trace_anns)
            try:
                live = await self.client.update(ev)
                self._cache[key] = live
                self._cache.move_to_end(key)
                return live
            except ApiError as e:
                if not (e.conflict or e.not_found):
                    raise
                # stale cache (Event GC'd or raced); fall through to create
                self._cache.pop(key, None)

        meta = involved.get("metadata", {}) or {}
        uid = meta.get("uid", "")
        if not uid and involved.get("kind") and meta.get("name"):
            # name-only refs (node_ref from a patch-by-name transition):
            # fill the uid so kubectl describe's involvedObject.uid field
            # selector matches (client-go's recorder always carries it);
            # best-effort — an unresolvable ref still posts by name
            try:
                av = involved.get("apiVersion", "")
                group = av.split("/", 1)[0] if "/" in av else ""
                live = await self.client.get(
                    group, involved["kind"], meta["name"], meta.get("namespace")
                )
                uid = (live.get("metadata") or {}).get("uid", "")
            except Exception as e:  # noqa: BLE001
                log.debug("could not resolve uid for event ref %s: %s", key, e)
        now = _now()
        ev = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": f"{meta.get('name', 'unknown')}.{uuid.uuid4().hex[:10]}",
                "namespace": self.namespace,
                **({"annotations": trace_anns} if trace_anns else {}),
            },
            "involvedObject": {
                "apiVersion": involved.get("apiVersion", ""),
                "kind": involved.get("kind", ""),
                "name": meta.get("name", ""),
                "namespace": meta.get("namespace", ""),
                "uid": uid,
            },
            "type": type_,
            "reason": reason,
            "message": message[:1024],
            "source": {"component": self.component},
            "reportingComponent": self.component,
            "firstTimestamp": now,
            "lastTimestamp": now,
            "count": 1,
        }
        live = await self.client.create(ev)
        self._cache[key] = live
        self._cache.move_to_end(key)
        while len(self._cache) > self.cache_size:
            self._cache.popitem(last=False)
        return live
