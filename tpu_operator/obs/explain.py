"""Per-node causal explain engine: ``GET /debug/explain?node=``.

PRs 1/2/6 left "why is node X not validated" spread over four surfaces —
``/debug/traces``, Events, node labels, and the fleet rollups — each
correlated by hand.  This module stitches them into ONE time-ordered
narrative per node:

- **Node state transitions** observed from the informer-cached node list a
  reconcile pass already holds (``observe_nodes`` — zero API verbs, the
  ``collect_nodes`` discipline): join, validated, Ready flaps, cordons,
  agent health verdicts, health-engine hysteresis/escalation states,
  upgrade and remediation machine states, slice readiness.
- **Kubernetes Events** involving the node, fed by the EventRecorder's
  sink hook at post time (already deduped by its correlator).
- **SLO breach episodes** naming the node among their offenders, fed by
  the Manager's fleet loop on every fired/recovered transition.
- **Propagated traces**: the join-phase pushes carry the
  ``TPU_TRACEPARENT`` trace id minted by the operator
  (state/render_data.py), so the snapshot links the node straight to the
  reconcile span trees in ``/debug/traces?trace_id=``.

The headline field is the machine-readable ``blocking_on`` verdict: what
this node is waiting on RIGHT NOW ("waiting: validator compile, 9.2s so
far"), derived from the ownership hierarchy (health engine > upgrade >
remediation > join critical path) and the join-phase segments the
validator pushed (obs/fleet.py ``JOIN_PHASES``).

Everything here is bounded evidence: per-node timelines are rings,
departed nodes are pruned with the fleet aggregator's node map, and a
snapshot never performs I/O.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Iterable, Optional

from tpu_operator import consts
from tpu_operator.obs import fleet as fleet_api
from tpu_operator.utils import deep_get

# timeline entries kept per node: enough to tell the node's story across a
# few join/upgrade/remediation episodes, small enough that 10k nodes hold
# ~10k rings of dicts, not a database
TIMELINE_MAX = 128

# entry kinds, for readers filtering the narrative
KIND_NODE = "node"            # join/validated/Ready/cordon transitions
KIND_HEALTH = "health"        # agent verdicts + health-engine states
KIND_UPGRADE = "upgrade"      # upgrade machine state label
KIND_REMEDIATION = "remediation"
KIND_EVENT = "event"          # deduped Kubernetes Events on the node
KIND_SLO = "slo"              # fleet SLO episodes naming this node

# label/annotation fields whose transitions the timeline narrates,
# (field key, entry kind, human name)
_WATCHED_LABELS = (
    (consts.TPU_HEALTH_LABEL, KIND_HEALTH, "agent health verdict"),
    (consts.HEALTH_STATE_LABEL, KIND_HEALTH, "health engine state"),
    (consts.UPGRADE_STATE_LABEL, KIND_UPGRADE, "upgrade state"),
    (consts.REMEDIATION_STATE_LABEL, KIND_REMEDIATION, "remediation state"),
    (consts.VALIDATE_REQUEST_LABEL, KIND_REMEDIATION, "re-validation request"),
    (consts.SLICE_READY_LABEL, KIND_NODE, "slice readiness"),
)
_WATCHED_ANNOTATIONS = (
    (consts.HEALTH_ESCALATION_ANNOTATION, KIND_HEALTH, "health escalation rung"),
    (consts.TPU_HEALTH_REASON_ANNOTATION, KIND_HEALTH, "agent health reason"),
    (consts.HEALTH_DEGRADED_BY_ANNOTATION, KIND_HEALTH, "slice-degraded by"),
)

def _upgrade_active_states() -> tuple:
    """The states in which the upgrade machine owns the node — the ONE
    source of truth in controllers/upgrade.py, imported lazily so the obs
    layer carries no controller import at module load (an inlined copy
    here drifted once already: it missed drain-required)."""
    from tpu_operator.controllers.upgrade import NON_TERMINAL_STATES

    return NON_TERMINAL_STATES


class ExplainEngine:
    """Stitches node evidence into ``/debug/explain`` documents."""

    def __init__(self, fleet=None, tracer=None, max_entries: int = TIMELINE_MAX):
        # obs.fleet.FleetAggregator: per-node join evidence + SLO state
        self.fleet = fleet
        # obs.trace.Tracer: the /debug/traces ring the snapshot links into
        self.tracer = tracer
        self.max_entries = max_entries
        self._timelines: dict[str, deque] = {}
        # last observed field snapshot per node, for transition detection
        self._last: dict[str, dict] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    # Ingest: informer-cached node evidence (zero API verbs).

    def observe_nodes(self, nodes: list[dict], now: Optional[float] = None) -> None:
        """One pass over the cached node list: append a timeline entry per
        observed transition.  Called from the clusterpolicy reconcile pass
        that already holds the list — same zero-API discipline as
        ``FleetAggregator.collect_nodes``."""
        now = time.time() if now is None else now
        live: set[str] = set()
        for node in nodes:
            name = deep_get(node, "metadata", "name", default="")
            if not name:
                continue
            live.add(name)
            self._observe_node(name, node, now)
        with self._lock:
            for gone in set(self._last) - live:
                del self._last[gone]
                self._timelines.pop(gone, None)

    def _observe_node(self, name: str, node: dict, now: float) -> None:
        labels = deep_get(node, "metadata", "labels", default={}) or {}
        anns = deep_get(node, "metadata", "annotations", default={}) or {}
        fields: dict = {
            "validated": consts.TPU_RESOURCE
            in (deep_get(node, "status", "allocatable") or {}),
            "ready": self._ready(node),
            "unschedulable": bool(deep_get(node, "spec", "unschedulable")),
        }
        for key, _, _ in _WATCHED_LABELS:
            fields[key] = labels.get(key, "")
        for key, _, _ in _WATCHED_ANNOTATIONS:
            fields[key] = anns.get(key, "")
        with self._lock:
            prev = self._last.get(name)
            self._last[name] = fields
            if prev is None:
                # first sight: anchor the timeline at the node's join; the
                # current non-default states are recorded once so a
                # restarted operator still explains a mid-episode node
                created = fleet_api._parse_k8s_ts(
                    deep_get(node, "metadata", "creationTimestamp", default="")
                )
                self._append(name, created or now, KIND_NODE, "node joined the cluster")
                prev = {
                    "validated": False, "ready": True, "unschedulable": False,
                    **{k: "" for k, _, _ in _WATCHED_LABELS},
                    **{k: "" for k, _, _ in _WATCHED_ANNOTATIONS},
                }
            if fields["validated"] != prev["validated"]:
                self._append(
                    name, now, KIND_NODE,
                    "node validated (google.com/tpu advertised)"
                    if fields["validated"]
                    else "node lost validation (google.com/tpu withdrawn)",
                )
            if fields["ready"] != prev["ready"] and fields["ready"] is not None:
                self._append(
                    name, now, KIND_NODE,
                    "Ready condition True" if fields["ready"]
                    else "Ready condition False",
                )
            if fields["unschedulable"] != prev["unschedulable"]:
                self._append(
                    name, now, KIND_NODE,
                    "node cordoned" if fields["unschedulable"] else "node uncordoned",
                )
            for key, kind, title in (*_WATCHED_LABELS, *_WATCHED_ANNOTATIONS):
                if fields[key] != prev.get(key, ""):
                    frm, to = prev.get(key, ""), fields[key]
                    self._append(
                        name, now, kind,
                        f"{title}: {frm or '(none)'} -> {to or '(cleared)'}",
                        field=key,
                    )

    @staticmethod
    def _ready(node: dict) -> Optional[bool]:
        for cond in deep_get(node, "status", "conditions", default=[]) or []:
            if cond.get("type") == "Ready":
                return cond.get("status") == "True"
        return None

    # ------------------------------------------------------------------
    # Ingest: Events + SLO episodes (push hooks).

    def observe_event(
        self, involved: dict, type_: str, reason: str, message: str
    ) -> None:
        """EventRecorder sink: node-involved Events join the timeline at
        post time, already deduped by the recorder's correlator."""
        if involved.get("kind") != "Node":
            return
        name = deep_get(involved, "metadata", "name", default="")
        if not name:
            return
        with self._lock:
            if name not in self._last:
                # unknown (or already-departed) node: a trailing Event
                # racing node deletion must not resurrect a timeline the
                # prune loop (keyed on observed nodes) would never reap
                return
            self._append(
                name, time.time(), KIND_EVENT,
                f"{type_}/{reason}: {message}"[:512],
                reason=reason,
            )

    def observe_slo(
        self, kind: str, slo: str, message: str, offenders: Iterable[str] = ()
    ) -> None:
        """Manager fleet-loop hook: a fired/recovered SLO transition lands
        on every offender node's timeline."""
        now = time.time()
        with self._lock:
            for node in offenders:
                if node not in self._last:
                    continue  # same no-resurrection rule as observe_event
                self._append(
                    node, now, KIND_SLO,
                    f"SLO {slo} {kind}: {message}"[:512],
                    slo=slo,
                )

    def _append(self, node: str, ts: float, kind: str, detail: str, **extra) -> None:
        ring = self._timelines.get(node)
        if ring is None:
            ring = self._timelines[node] = deque(maxlen=self.max_entries)
        ring.append({"ts": round(ts, 3), "kind": kind, "detail": detail, **extra})

    # ------------------------------------------------------------------
    # The /debug/explain document.

    def nodes(self) -> list[str]:
        with self._lock:
            return sorted(self._last)

    def snapshot(self, node: str, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        with self._lock:
            timeline = sorted(
                self._timelines.get(node, ()), key=lambda e: e["ts"]
            )
            fields = dict(self._last.get(node) or {})
        join = self.fleet.node_join(node) if self.fleet is not None else {
            "validated": False, "phases": {},
        }
        # the engine's OWN observation of allocatable is authoritative too:
        # a fleet fed by a different process (or none) must not make a
        # validated node read as mid-join
        join["validated"] = bool(join.get("validated") or fields.get("validated"))
        slos = self._node_slos(node)
        trace_ids = sorted({
            entry.get("trace_id", "")
            for entry in join.get("phases", {}).values()
            if entry.get("trace_id")
        })
        doc = {
            "node": node,
            "ts": round(now, 3),
            "known": bool(fields),
            "blocking_on": self._blocking_on(node, fields, join, slos, now),
            "join": join,
            "slos_breached": slos,
            "timeline": timeline,
            "trace_ids": trace_ids,
            "traces": self._linked_traces(trace_ids),
        }
        return doc

    def _node_slos(self, node: str) -> list[str]:
        if self.fleet is None:
            return []
        return sorted(
            name
            for name, offenders in self.fleet.slo_engine.breached_offenders().items()
            if node in offenders
        )

    def _linked_traces(self, trace_ids: list[str]) -> list[dict]:
        """Summaries of ring traces this node's propagated ids point at —
        enough to jump to ``/debug/traces?trace_id=`` without guessing."""
        if self.tracer is None or not trace_ids:
            return []
        wanted = set(trace_ids)
        out = []
        for trace in self.tracer.snapshot():
            if trace.get("trace_id") in wanted:
                out.append({
                    k: trace[k]
                    for k in ("name", "trace_id", "reconcile_id",
                              "start_ts", "duration_s", "evicted")
                    if k in trace
                })
        return out

    def _blocking_on(
        self, node: str, fields: dict, join: dict, slos: list, now: float
    ) -> dict:
        """The machine-readable verdict: what owns this node's progress
        right now, in ownership-hierarchy order (health actuation >
        upgrade machine > remediation machine > join critical path)."""
        if not fields:
            return {"state": "unknown", "detail": f"node {node} never observed"}
        health_state = fields.get(consts.HEALTH_STATE_LABEL, "")
        escalation = fields.get(consts.HEALTH_ESCALATION_ANNOTATION, "")
        if health_state in (consts.HEALTH_QUARANTINED, consts.HEALTH_TRIPPED,
                            consts.HEALTH_OBSERVE) or escalation:
            reason = fields.get(consts.TPU_HEALTH_REASON_ANNOTATION, "")
            return {
                "state": "health",
                "phase": escalation or health_state,
                "detail": (
                    f"health engine owns the node "
                    f"(state={health_state or 'tripped'}"
                    + (f", rung={escalation}" if escalation else "")
                    + (f", reason={reason}" if reason else "")
                    + ")"
                ),
            }
        upgrade = fields.get(consts.UPGRADE_STATE_LABEL, "")
        if upgrade in _upgrade_active_states():
            return {
                "state": "upgrade",
                "phase": upgrade,
                "detail": f"runtime upgrade machine owns the node ({upgrade})",
            }
        remediation = fields.get(consts.REMEDIATION_STATE_LABEL, "")
        request = fields.get(consts.VALIDATE_REQUEST_LABEL, "")
        if remediation == "revalidating" or request == "requested":
            return {
                "state": "remediation",
                "phase": remediation or "requested",
                "detail": "re-validation in progress",
            }
        if not join.get("validated"):
            return self._joining_verdict(join, now)
        verdict: dict = {"state": "validated", "phase": "", "detail": "node validated"}
        if slos:
            verdict["detail"] += (
                "; breaching SLO " + ", ".join(slos) + " (see slos_breached)"
            )
        return verdict

    def _joining_verdict(self, join: dict, now: float) -> dict:
        """Mid-join: the first missing phase of the propagated critical
        path is what the node is waiting on; elapsed counts from the
        newest received segment (or the join itself)."""
        phases = join.get("phases") or {}
        waiting = next(
            (p for p in fleet_api.JOIN_PHASES if p not in phases),
            fleet_api.JOIN_PHASES[-1],
        )
        newest = max((e.get("ts", 0.0) for e in phases.values()), default=0.0)
        elapsed = max(0.0, now - newest) if newest else None
        detail = f"waiting: {self._phase_label(waiting)}"
        if elapsed is not None:
            detail += f", {elapsed:.1f}s so far"
        out = {"state": "joining", "phase": waiting, "detail": detail}
        if elapsed is not None:
            out["waiting_s"] = round(elapsed, 3)
        return out

    @staticmethod
    def _phase_label(phase: str) -> str:
        return {
            "runtime-ready": "tpu runtime container",
            "validator-scheduled": "validator scheduling + PJRT probe",
            "plugin-advertised": "device plugin advertising google.com/tpu",
            "compile": "validator compile",
            "collective": "validation collective",
        }.get(phase, phase)
