"""Fleet telemetry plane: in-operator aggregation + declarative SLOs.

PRs 1-2 gave every *process* eyes — spans, Events, flight records, per-node
agent pushes — but nothing saw the *fleet*: join→validated latency,
workload-regression rates, and controller-queue saturation existed only as
scattered per-node samples.  This module is the aggregation layer the
scale roadmap (sharding, scored placement, elastic pools) gates on:

- :class:`FleetAggregator` — a TSDB-lite: fixed-size ring-buffer time
  series keyed on ``(metric, labels)``, ingesting

  * the operator's own spans (reconcile durations, tagged with exemplar
    span ids so an SLO breach jumps straight to ``/debug/traces``),
  * the node agents' push hop (``metrics_agent`` forwards its ``/push``
    traffic to the operator's fleet ingest route when
    ``TPU_FLEET_PUSH_URL`` is set),
  * informer-cached node evidence (join→validated transitions, health
    verdict counts) — collected during reconcile passes that already hold
    the node list, so aggregation adds ZERO steady-state API verbs.

  Windowed rollups (count/min/max/mean/p50/p90/p99) are served as JSON at
  ``/debug/fleet`` and exported as bounded ``tpu_operator_fleet_*`` gauges
  — per-node series stay inside the ring; only rollups reach Prometheus,
  so registry cardinality is bounded by the metric catalogue, not the
  fleet size (hack/check_metric_labels.py enforces the same discipline
  tree-wide).

- :class:`SLOEngine` — multi-window burn-rate evaluation of the
  ``observability.slos`` ClusterPolicy spec: the burn rate per window is
  ``bad_fraction / (1 - objective)``; a breach requires EVERY window to
  burn past the threshold (the long window proves the budget spend is
  real, the short window proves it is still happening — the Google-SRE
  multi-window discipline), recovery requires the shortest window to go
  quiet.  Transitions emit ``SLOBurnRate`` / ``SLORecovered`` Events via
  the Manager and feed the health engine as an additional central signal
  (per-node offender sets).

Everything here is best-effort telemetry: ingest never raises into a
reconcile pass, and a full ring simply forgets the oldest samples.
"""

from __future__ import annotations

import calendar
import json
import logging
import math
import threading
import time
from collections import deque
from typing import Any, Iterable, Optional

from tpu_operator import consts
from tpu_operator.api.types import SLOSpec
from tpu_operator.utils import deep_get

log = logging.getLogger("tpu_operator.obs.fleet")

# ---------------------------------------------------------------------------
# Fleet metric catalogue.  Push ingest accepts exactly these names (plus the
# tpu_workload_* family pattern, which mirrors the metrics agent's
# WORKLOAD_COUNTERS without importing the agents package) — an unknown name
# is rejected and counted, never silently stored: the exported rollup
# surface must stay the documented catalogue.
METRIC_RECONCILE_DURATION = "reconcile_duration_seconds"
METRIC_JOIN_TO_VALIDATED = "join_to_validated_seconds"
METRIC_JOIN_PHASE = "join_phase_seconds"
METRIC_HEALTH_UNHEALTHY = "health_verdict_unhealthy_nodes"
METRIC_CHIP_SCRAPE_ERRORS = "chip_scrape_errors_total"
# elastic multi-slice scheduler (controllers/slicescheduler.py): per-bind
# placement latency and the free-capacity fragmentation ratio, ingested
# operator-side (zero extra API verbs — the scheduler pass already holds
# the evidence) so /debug/fleet serves windowed rollups of both
METRIC_SLICE_PLACEMENT = "slice_placement_seconds"
METRIC_SLICE_FRAGMENTATION = "slice_fragmentation_ratio"
# chip-time accounting (obs/accounting.py): the ledger's headline ratios,
# ingested operator-side each export so /debug/fleet and the quantile
# gauges carry windowed goodput/utilization next to the latency rollups
METRIC_GOODPUT_RATIO = "goodput_ratio"
METRIC_CHIP_UTILIZATION = "chip_utilization"

_WORKLOAD_METRIC_PREFIX = "tpu_workload_"
_METRIC_NAME_MAX = 128

OPERATOR_METRICS_CATALOGUE = (
    METRIC_RECONCILE_DURATION,
    METRIC_JOIN_TO_VALIDATED,
    METRIC_JOIN_PHASE,
    METRIC_HEALTH_UNHEALTHY,
    METRIC_CHIP_SCRAPE_ERRORS,
    METRIC_SLICE_PLACEMENT,
    METRIC_SLICE_FRAGMENTATION,
    METRIC_GOODPUT_RATIO,
    METRIC_CHIP_UTILIZATION,
)

# join→validated critical-path phases, in pipeline order (the validator
# derives the segments from its status-file timestamps + flight record —
# validator/status.join_phase_segments — and pushes them through the agent
# hop).  Ingest accepts ONLY these names: the phase label must stay a
# bounded vocabulary or the exported rollup family grows with attacker
# input (both push ports are unauthenticated).
JOIN_PHASES = (
    "runtime-ready",
    "validator-scheduled",
    "plugin-advertised",
    "compile",
    "collective",
)

# ingest sources (fleet_samples_ingested_total label values)
SOURCE_SPAN = "span"
SOURCE_PUSH = "push"
SOURCE_NODE = "node"

# the pushed serving-counter family (obs/flight COUNTER_KEYS serve_* names):
# per-replica capacity evidence the serving front door routes on.  The
# ``workload`` label on these series is the replica name (TPU_SERVE_NAME).
SERVING_METRIC_PREFIX = "tpu_workload_serving_"

_QUANTILES = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))

# exemplars kept per metric: enough to jump from a breach to a handful of
# recent traces, small enough to never matter
_EXEMPLARS_PER_METRIC = 8

# distinct node names the per-node join-phase map may hold: pushes arrive
# from an unauthenticated port, and an invented node name must not grow
# operator memory without bound (live nodes are pruned by collect_nodes)
_JOIN_PHASE_MAX_NODES = 4096


def _valid_metric_name(name: str) -> bool:
    if not isinstance(name, str) or not name or len(name) > _METRIC_NAME_MAX:
        return False
    if name in OPERATOR_METRICS_CATALOGUE:
        return True
    return name.startswith(_WORKLOAD_METRIC_PREFIX) and name.replace(
        "_", ""
    ).isalnum() and name == name.lower()


def _roll(sorted_values: list) -> dict:
    return {
        "count": len(sorted_values),
        "min": sorted_values[0],
        "max": sorted_values[-1],
        "mean": sum(sorted_values) / len(sorted_values),
        **{q: quantile(sorted_values, frac) for q, frac in _QUANTILES},
    }


def quantile(sorted_values: list, q: float) -> float:
    """Linear-interpolated quantile over an ascending list (the numpy
    'linear' method, so tests can pin rollups against hand-computed ground
    truth)."""
    if not sorted_values:
        raise ValueError("quantile of empty list")
    if len(sorted_values) == 1:
        return float(sorted_values[0])
    pos = q * (len(sorted_values) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(sorted_values) - 1)
    frac = pos - lo
    return float(sorted_values[lo] * (1 - frac) + sorted_values[hi] * frac)


def _parse_k8s_ts(value: str) -> Optional[float]:
    """``2026-08-04T12:00:00Z`` → unix seconds (the only shape the fake and
    real apiservers emit for creationTimestamp); None when unparsable.
    timegm, not mktime: the timestamp is UTC, and a local-zone conversion
    would skew every join sample by the DST offset."""
    try:
        return float(calendar.timegm(time.strptime(value, "%Y-%m-%dT%H:%M:%SZ")))
    except (TypeError, ValueError):
        return None


async def read_bytes_capped(request, limit: int):
    """Size-guarded raw body read shared by every unauthenticated POST
    surface (fleet /push ingest, compile-cache artifact publication, the
    agent's relay hop).  Returns ``(body, None)`` or ``(None,
    error_response)`` — 413 past the cap (declared Content-Length or
    actual bytes)."""
    from aiohttp import web

    if request.content_length is not None and request.content_length > limit:
        return None, web.json_response(
            {"error": f"payload exceeds {limit} bytes"}, status=413
        )
    # read() must LOOP: StreamReader.read(n) returns whatever is buffered
    # once any bytes arrive, and a body spanning several TCP segments would
    # otherwise be truncated
    chunks: list[bytes] = []
    remaining = limit + 1
    while remaining > 0:
        chunk = await request.content.read(remaining)
        if not chunk:
            break
        chunks.append(chunk)
        remaining -= len(chunk)
    body = b"".join(chunks)
    if len(body) > limit:
        return None, web.json_response(
            {"error": f"payload exceeds {limit} bytes"}, status=413
        )
    return body, None


async def read_json_capped(request, limit: int = consts.PUSH_MAX_BYTES):
    """Size-guarded JSON body read (:func:`read_bytes_capped` + parse);
    400 on bad JSON."""
    from aiohttp import web

    body, error = await read_bytes_capped(request, limit)
    if error is not None:
        return None, error
    try:
        return json.loads(body), None
    except (UnicodeDecodeError, ValueError):
        return None, web.json_response({"error": "invalid JSON"}, status=400)


def _window_labels(windows: Iterable) -> set:
    out = set()
    for w in windows or []:
        try:
            out.add(f"{float(w):g}s")
        except (TypeError, ValueError):
            continue
    return out


class _Series:
    __slots__ = ("samples", "ordered")

    def __init__(self, maxlen: int):
        # (ts, value) tuples, append-only, oldest evicted by the ring bound
        self.samples: deque = deque(maxlen=maxlen)
        # True while appends arrive in non-decreasing ts order (the live
        # path always does; tests ingest synthetic timestamps) — lets
        # window scans walk newest-first and stop at the cutoff instead of
        # touching every sample of every ring each evaluation
        self.ordered = True

    def append(self, ts: float, value: float) -> None:
        if self.samples and ts < self.samples[-1][0]:
            self.ordered = False
        self.samples.append((ts, value))

    def window(self, cutoff: float) -> Iterable[tuple[float, float]]:
        if not self.ordered:
            return [s for s in self.samples if s[0] >= cutoff]
        out = []
        for s in reversed(self.samples):
            if s[0] < cutoff:
                break
            out.append(s)
        return out


class SLOEngine:
    """Multi-window burn-rate evaluation over a :class:`FleetAggregator`."""

    def __init__(self, aggregator: "FleetAggregator", metrics=None):
        self.aggregator = aggregator
        self.metrics = metrics
        self.slos: dict[str, SLOSpec] = {}
        self.breached: dict[str, bool] = {}
        # slo name -> {node -> bad sample count} in the shortest window,
        # refreshed each evaluation while breached (health-engine signal)
        self._offenders: dict[str, dict[str, int]] = {}
        # slo name -> trace/reconcile ids of the metric's exemplars at the
        # moment the breach fired, held until recovery: the traces a human
        # follows from the SLOBurnRate Event must survive ring eviction
        # (obs.trace.Tracer pins on referenced_trace_ids) for as long as
        # the breach is unresolved
        self._breach_trace_ids: dict[str, set] = {}

    def configure(self, slo_dicts: Iterable[dict]) -> None:
        """(Re)parse the declarative spec; breach state survives for SLOs
        that keep their name, removed SLOs drop their state and gauges."""
        parsed: dict[str, SLOSpec] = {}
        for entry in slo_dicts or []:
            if not isinstance(entry, dict):
                continue
            slo = SLOSpec.from_dict(entry)
            if not slo.name or not slo.metric or not slo.windows:
                continue
            parsed[slo.name] = slo
        for gone in set(self.slos) - set(parsed):
            self._drop_gauges(gone, self.slos[gone].windows)
            self.breached.pop(gone, None)
            self._offenders.pop(gone, None)
            self._breach_trace_ids.pop(gone, None)
        for kept, slo in parsed.items():
            old = self.slos.get(kept)
            if old is None:
                continue
            # a retained SLO whose window set changed must drop the
            # no-longer-evaluated window label sets, or their burn gauges
            # freeze at the last value forever
            self._drop_burn_windows(
                kept, _window_labels(old.windows) - _window_labels(slo.windows)
            )
        self.slos = parsed

    def _drop_burn_windows(self, name: str, window_labels: Iterable[str]) -> None:
        if self.metrics is None:
            return
        for label in window_labels:
            try:
                self.metrics.slo_burn_rate.remove(name, label)
            except KeyError:
                pass

    def _drop_gauges(self, name: str, windows: Iterable[float]) -> None:
        """A deleted SLO must not leave slo_breached latched at its last
        value — Prometheus would page on a ghost forever."""
        if self.metrics is None:
            return
        try:
            self.metrics.slo_breached.remove(name)
        except KeyError:
            pass
        self._drop_burn_windows(name, _window_labels(windows))

    # ------------------------------------------------------------------
    def _good(self, slo: SLOSpec, value: float) -> bool:
        if slo.threshold is None:
            return True
        if slo.comparison == "ge":
            return value >= slo.threshold
        return value <= slo.threshold

    def _window_burn(
        self, slo: SLOSpec, window_s: float, now: float
    ) -> tuple[Optional[float], dict[str, int]]:
        """(burn rate or None when the window lacks evidence, per-node bad
        counts).  Burn 0.0 means samples exist and all are good."""
        rows = self.aggregator.window_samples(slo.metric, window_s, now)
        if len(rows) < max(1, slo.min_samples):
            return None, {}
        bad = 0
        bad_nodes: dict[str, int] = {}
        for value, labels in rows:
            if not self._good(slo, value):
                bad += 1
                node = labels.get("node", "")
                if node:
                    bad_nodes[node] = bad_nodes.get(node, 0) + 1
        budget = max(1e-9, 1.0 - slo.objective)
        return (bad / len(rows)) / budget, bad_nodes

    def evaluate(self, now: Optional[float] = None) -> list[tuple[str, str, str]]:
        """One evaluation pass over every configured SLO.  Returns breach
        transitions as ``(kind, slo_name, message)`` with kind ``fired`` or
        ``recovered`` — the caller (Manager) turns them into Events."""
        now = time.time() if now is None else now
        transitions: list[tuple[str, str, str]] = []
        for name, slo in self.slos.items():
            windows = sorted(float(w) for w in slo.windows if float(w) > 0)
            if not windows:
                continue
            burns: dict[float, Optional[float]] = {}
            offenders: dict[str, int] = {}
            for w in windows:
                burn, bad_nodes = self._window_burn(slo, w, now)
                burns[w] = burn
                if w == windows[0]:
                    offenders = bad_nodes
                if self.metrics is not None:
                    self.metrics.slo_burn_rate.labels(
                        slo=name, window=f"{w:g}s"
                    ).set(burn or 0.0)
            was = self.breached.get(name, False)
            all_burning = all(
                b is not None and b >= slo.burn_rate_threshold
                for b in burns.values()
            )
            # recovery needs EVIDENCE of recovery: the shortest window must
            # hold samples and burn under the threshold.  Telemetry going
            # dark right after a breach (agents crashed, push hop down)
            # must NOT clear the alert it caused — the breach holds until
            # good samples arrive or the whole episode ages out of even
            # the longest window (nothing left to judge).
            short_quiet = (
                burns[windows[0]] is not None
                and burns[windows[0]] < slo.burn_rate_threshold
            )
            all_dark = all(b is None for b in burns.values())
            if not was and all_burning:
                self.breached[name] = True
                self._offenders[name] = offenders
                self._breach_trace_ids[name] = (
                    self.aggregator.exemplar_trace_ids(slo.metric)
                )
                detail = ", ".join(
                    f"{w:g}s={burns[w]:.2f}x" for w in windows
                )
                transitions.append((
                    "fired", name,
                    f"SLO {name} ({slo.metric}) burning past "
                    f"{slo.burn_rate_threshold:g}x on every window: {detail}",
                ))
            elif was and (short_quiet or all_dark):
                self.breached[name] = False
                self._offenders.pop(name, None)
                self._breach_trace_ids.pop(name, None)
                transitions.append((
                    "recovered", name,
                    f"SLO {name} ({slo.metric}) "
                    + (
                        "burn rate back under "
                        f"{slo.burn_rate_threshold:g}x in the "
                        f"{windows[0]:g}s window"
                        if short_quiet
                        else "episode aged out of every window (no samples "
                             "left to judge)"
                    ),
                ))
            elif was:
                # still breached: keep the offender set current so the
                # health engine tracks the nodes that are bad NOW
                self._offenders[name] = offenders
            if self.metrics is not None:
                self.metrics.slo_breached.labels(slo=name).set(
                    1 if self.breached.get(name) else 0
                )
                for kind, tname, _ in transitions:
                    if tname == name:
                        self.metrics.slo_transitions_total.labels(
                            slo=name, kind=kind
                        ).inc()
        return transitions

    # ------------------------------------------------------------------
    def breached_slos(self) -> dict[str, SLOSpec]:
        return {n: self.slos[n] for n, b in self.breached.items() if b and n in self.slos}

    def breached_offenders(self) -> dict[str, list]:
        """{breached slo name: sorted offender nodes} — the explain engine
        and the Manager's SLO hooks read through this instead of the raw
        offender bookkeeping."""
        return {
            name: sorted(bad_nodes)
            for name, bad_nodes in self._offenders.items()
            if self.breached.get(name)
        }

    def breach_trace_ids(self) -> set:
        """Trace/reconcile ids referenced by UNRESOLVED breaches (pinned
        against /debug/traces ring eviction until recovery)."""
        out: set = set()
        for name, ids in self._breach_trace_ids.items():
            if self.breached.get(name):
                out |= ids
        return out

    def node_offenders(self, node: str) -> list[str]:
        """SLO names currently breached with this node among the bad
        samples of the shortest window — the health engine observes these
        as sustained ``slo:<name>`` signals.  Only SLOs that opted in via
        ``feedHealthEngine`` participate: fleet ingest is unauthenticated,
        and a spoofed push must not be able to march nodes onto the
        remediation ladder unless the operator explicitly coupled that
        SLO to actuation."""
        return sorted(
            name
            for name, bad_nodes in self._offenders.items()
            if self.breached.get(name)
            and node in bad_nodes
            and name in self.slos
            and self.slos[name].feed_health_engine
        )

    def snapshot(self) -> dict:
        return {
            name: {
                "metric": slo.metric,
                "objective": slo.objective,
                "threshold": slo.threshold,
                "comparison": slo.comparison,
                "windows": [float(w) for w in slo.windows],
                "burn_rate_threshold": slo.burn_rate_threshold,
                "breached": bool(self.breached.get(name)),
                "offenders": sorted((self._offenders.get(name) or {})),
            }
            for name, slo in self.slos.items()
        }


class FleetAggregator:
    """Ring-buffer fleet time series + rollups + the SLO engine.

    Thread-safe on a plain lock: ingest arrives from the event loop (push
    route, reconcile passes) and from span completion, which validator-side
    tracers may drive off-loop."""

    def __init__(
        self,
        metrics=None,
        ring_samples: int = consts.FLEET_RING_SAMPLES,
        max_series: int = consts.FLEET_MAX_SERIES,
        ledger=None,
        profile=None,
    ):
        self.metrics = metrics
        self.ring_samples = ring_samples
        self.max_series = max_series
        # obs.accounting.ChipTimeLedger (optional): ingest_push forwards
        # each node's workload counters so busy evidence reaches the
        # chip-time carve without a second push endpoint
        self.ledger = ledger
        # obs.profile.ProfileEngine (optional): ingest_push forwards each
        # node's step-profile windows the same way, so straggler
        # attribution rides the existing hop too
        self.profile = profile
        # metric → labels-key → series: window scans touch only the
        # queried metric's bucket, not every series in the aggregator
        self._series: dict[str, dict[tuple, _Series]] = {}
        self._n_series = 0
        self._exemplars: dict[str, deque] = {}
        # metrics whose rollup gauges are currently exported; emptied
        # windows remove their label sets instead of freezing stale values
        self._exported: set[str] = set()
        self._exported_phases: set[str] = set()
        self._lock = threading.Lock()
        self.slo_engine = SLOEngine(self, metrics)
        # join→validated transition tracking: node -> last seen validated?
        self._node_validated: dict[str, bool] = {}
        # nodes whose join has been ingested: once per node LIFETIME — a
        # lagging watch briefly showing a node unvalidated again must not
        # re-fire the transition and double-count the join
        self._node_joined: set[str] = set()
        # per-node join evidence behind /debug/explain: the measured
        # join→validated seconds (node -> value) and the pushed phase
        # segments (node -> {phase: {"seconds", "ts", "trace_id"}}) — both
        # pruned with the node-validated map when nodes leave the cluster
        self._node_join_seconds: dict[str, float] = {}
        self._node_join_phases: dict[str, dict[str, dict]] = {}
        # throttle for the gauge-style health verdict series
        self._last_unhealthy: Optional[tuple[float, float]] = None  # (ts, count)

    # ------------------------------------------------------------------
    # Ingest.

    def ingest(
        self,
        metric: str,
        value: float,
        labels: Optional[dict] = None,
        ts: Optional[float] = None,
        exemplar: Optional[dict] = None,
        source: str = SOURCE_PUSH,
    ) -> bool:
        """One sample; False when rejected (bad name/value, series cap)."""
        if not _valid_metric_name(metric):
            self._reject("unknown-metric")
            return False
        try:
            value = float(value)
        except (TypeError, ValueError):
            self._reject("bad-shape")
            return False
        if not math.isfinite(value):
            self._reject("bad-shape")
            return False
        labels_key = tuple(sorted((labels or {}).items()))
        ts = time.time() if ts is None else ts
        with self._lock:
            bucket = self._series.setdefault(metric, {})
            series = bucket.get(labels_key)
            if series is None:
                if self._n_series >= self.max_series:
                    if not bucket:
                        del self._series[metric]
                    self._reject("series-cap")
                    return False
                series = bucket[labels_key] = _Series(self.ring_samples)
                self._n_series += 1
            series.append(ts, value)
            if exemplar:
                self._exemplars.setdefault(
                    metric, deque(maxlen=_EXEMPLARS_PER_METRIC)
                ).append({"ts": round(ts, 3), "value": value, **exemplar})
        if self.metrics is not None:
            self.metrics.fleet_samples_ingested_total.labels(source=source).inc()
        return True

    def _reject(self, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.fleet_push_rejected_total.labels(reason=reason).inc()

    def observe_span(self, span) -> None:
        """Span-completion hook (obs.trace.Tracer.fleet): reconcile root
        spans become fleet duration samples carrying exemplar span ids, so
        a breach on the reconcile-latency SLO points at concrete traces
        (``/debug/traces?reconcile_id=``)."""
        try:
            from tpu_operator.obs import trace as obs_trace

            if span.kind != obs_trace.KIND_RECONCILE or span.duration_s is None:
                return
            self.ingest(
                METRIC_RECONCILE_DURATION,
                span.duration_s,
                {"controller": span.attrs.get("controller", "")},
                exemplar={
                    "span_id": span.span_id,
                    "reconcile_id": span.reconcile_id,
                    "trace_id": span.trace_id,
                },
                source=SOURCE_SPAN,
            )
        except Exception as e:  # noqa: BLE001 — telemetry must never fail a span
            log.debug("fleet span observation failed: %s", e)

    def ingest_push(self, body: Any) -> int:
        """One forwarded agent push::

            {"node": "tpu-0-0", "trace_id": "9c1d05e3f2aa",
             "workloads": {"train": {"counters": {"tpu_workload_mfu": 0.95}}},
             "join_phases": {"compile": 9.2, "collective": 0.8},
             "chips": {"scrape_errors_total": 3}}

        ``trace_id`` is the propagated TPU_TRACEPARENT trace (the flight
        recorder stamps it, the agent hop forwards it): it rides every
        ingested sample as an exemplar, joining the push back to the
        operator reconcile that minted the context.  ``join_phases``
        (validator-pushed critical-path segments) become bounded
        ``join_phase_seconds{node,phase}`` samples and feed the per-node
        evidence behind ``/debug/explain``.

        Returns accepted sample count; malformed shapes are counted and
        skipped, never raised (the route answers 400/413 for body-level
        problems before this runs)."""
        if not isinstance(body, dict):
            self._reject("bad-shape")
            return 0
        node = str(body.get("node") or "")
        raw_trace = body.get("trace_id")
        trace_id = raw_trace if isinstance(raw_trace, str) and len(raw_trace) <= 32 else ""
        exemplar = {"node": node, "trace_id": trace_id} if trace_id else None
        accepted = 0
        workloads = body.get("workloads")
        if isinstance(workloads, dict):
            for check, entry in workloads.items():
                counters = (entry or {}).get("counters") if isinstance(entry, dict) else None
                if not isinstance(counters, dict):
                    # step-profile-only windows carry no counters; they are
                    # consumed by the profile hop below, not a shape error
                    steps = (entry or {}).get("steps") if isinstance(entry, dict) else None
                    if not isinstance(steps, list):
                        self._reject("bad-shape")
                    continue
                for counter, value in counters.items():
                    labels = {"workload": str(check)}
                    if node:
                        labels["node"] = node
                    if self.ingest(
                        counter, value, labels, exemplar=exemplar,
                        source=SOURCE_PUSH,
                    ):
                        accepted += 1
            if self.ledger is not None and node:
                try:
                    self.ledger.observe_push(node, workloads)
                except Exception as e:  # noqa: BLE001 — accounting must never fail a push
                    log.debug("chip-time ledger push observation failed: %s", e)
            if self.profile is not None and node:
                try:
                    self.profile.observe_push(node, workloads)
                except Exception as e:  # noqa: BLE001 — profiling must never fail a push
                    log.debug("profile push observation failed: %s", e)
        accepted += self._ingest_join_phases(
            node, body.get("join_phases"), trace_id
        )
        chips = body.get("chips")
        if isinstance(chips, dict):
            value = chips.get("scrape_errors_total")
            if value is not None and self.ingest(
                METRIC_CHIP_SCRAPE_ERRORS, value,
                {"node": node} if node else {}, source=SOURCE_PUSH,
            ):
                accepted += 1
        return accepted

    def _ingest_join_phases(
        self, node: str, phases: Any, trace_id: str
    ) -> int:
        """Validator-pushed critical-path segments.  Phase names outside
        :data:`JOIN_PHASES` are rejected (bounded label vocabulary on an
        unauthenticated port); node names beyond the per-node map cap are
        dropped for the explain evidence but still sampled into the ring
        (the ring has its own series cap)."""
        if not isinstance(phases, dict) or not node:
            if phases is not None:
                self._reject("bad-shape")
            return 0
        now = time.time()
        accepted = 0
        for phase, seconds in phases.items():
            if phase not in JOIN_PHASES:
                self._reject("unknown-metric")
                continue
            if not self.ingest(
                METRIC_JOIN_PHASE,
                seconds,
                {"node": node, "phase": phase},
                ts=now,
                exemplar={"node": node, "phase": phase, "trace_id": trace_id}
                if trace_id
                else None,
                source=SOURCE_PUSH,
            ):
                continue
            accepted += 1
            with self._lock:
                if (
                    node in self._node_join_phases
                    or len(self._node_join_phases) < _JOIN_PHASE_MAX_NODES
                ):
                    self._node_join_phases.setdefault(node, {})[phase] = {
                        "seconds": float(seconds),
                        "ts": round(now, 3),
                        "trace_id": trace_id,
                    }
        return accepted

    def collect_nodes(self, nodes: list[dict], now: Optional[float] = None) -> None:
        """Derive fleet samples from informer-cached Node objects during a
        reconcile pass — the pass already holds the list, so this costs
        zero API verbs.  join→validated is TRANSITION-only: a node first
        seen already validated contributes nothing (a restarted operator
        must not re-ingest stale joins with inflated values)."""
        now = time.time() if now is None else now
        live: set[str] = set()
        unhealthy = 0
        for node in nodes:
            name = deep_get(node, "metadata", "name", default="")
            if not name:
                continue
            live.add(name)
            labels = deep_get(node, "metadata", "labels", default={}) or {}
            if labels.get(consts.TPU_HEALTH_LABEL) == consts.HEALTH_UNHEALTHY:
                unhealthy += 1
            validated = consts.TPU_RESOURCE in (
                deep_get(node, "status", "allocatable") or {}
            )
            prev = self._node_validated.get(name)
            self._node_validated[name] = validated
            if validated and prev is False and name not in self._node_joined:
                self._node_joined.add(name)
                created = _parse_k8s_ts(
                    deep_get(node, "metadata", "creationTimestamp", default="")
                )
                if created is not None:
                    join_s = max(0.0, now - created)
                    self._node_join_seconds[name] = join_s
                    self.ingest(
                        METRIC_JOIN_TO_VALIDATED,
                        join_s,
                        {"node": name},
                        ts=now,
                        source=SOURCE_NODE,
                    )
        for gone in set(self._node_validated) - live:
            del self._node_validated[gone]
            self._node_joined.discard(gone)
            self._node_join_seconds.pop(gone, None)
        with self._lock:
            # prune against the LIVE set, not just departed known nodes:
            # the push port is unauthenticated, and phase entries for
            # invented node names (never in the informer list) would
            # otherwise hold the per-node cap forever, starving real
            # joins of their explain evidence
            for gone in set(self._node_join_phases) - live:
                del self._node_join_phases[gone]
        # gauge-style series, throttled: ingest on change or every 5s
        last = self._last_unhealthy
        if last is None or last[1] != unhealthy or now - last[0] >= 5.0:
            self._last_unhealthy = (now, float(unhealthy))
            self.ingest(
                METRIC_HEALTH_UNHEALTHY, float(unhealthy), ts=now,
                source=SOURCE_NODE,
            )

    # ------------------------------------------------------------------
    # Per-node join evidence (the /debug/explain data plane).

    def node_join(self, node: str) -> dict:
        """This node's join→validated evidence: ``validated`` (bool),
        ``join_to_validated_seconds`` (present once the transition was
        observed), and the pushed ``phases`` segments in pipeline order."""
        with self._lock:
            phases = {
                phase: dict(entry)
                for phase, entry in (self._node_join_phases.get(node) or {}).items()
            }
        out: dict = {
            "validated": bool(self._node_validated.get(node)),
            "phases": {p: phases[p] for p in JOIN_PHASES if p in phases},
        }
        join_s = self._node_join_seconds.get(node)
        if join_s is not None:
            out["join_to_validated_seconds"] = round(join_s, 3)
        return out

    def known_nodes(self) -> list[str]:
        return sorted(self._node_validated)

    def exemplar_trace_ids(self, metric: str) -> set:
        """Non-empty trace/reconcile ids referenced by a metric's current
        exemplars."""
        with self._lock:
            exemplars = list(self._exemplars.get(metric) or ())
        return {
            ex[key]
            for ex in exemplars
            for key in ("trace_id", "reconcile_id")
            if ex.get(key)
        }

    def referenced_trace_ids(self) -> set:
        """Every trace/reconcile id a /debug/fleet reader could currently
        be holding: live exemplars across all metrics plus the exemplar
        sets snapshotted by unresolved SLO breaches.  The Tracer pins these
        against ring eviction (obs/trace.py) so the join never dangles."""
        with self._lock:
            metrics = list(self._exemplars)
        out: set = set()
        for metric in metrics:
            out |= self.exemplar_trace_ids(metric)
        out |= self.slo_engine.breach_trace_ids()
        out.discard("")
        return out

    # ------------------------------------------------------------------
    # SLO plumbing.

    def configure_slos(self, slo_dicts: Iterable[dict]) -> None:
        self.slo_engine.configure(slo_dicts)

    def evaluate_slos(self, now: Optional[float] = None) -> list[tuple[str, str, str]]:
        return self.slo_engine.evaluate(now)

    def node_slo_offenders(self, node: str) -> list[str]:
        return self.slo_engine.node_offenders(node)

    # ------------------------------------------------------------------
    # Rollups.

    def window_samples(
        self, metric: str, window_s: float, now: Optional[float] = None
    ) -> list[tuple[float, dict]]:
        """``(value, labels)`` for every sample of ``metric`` within the
        window, across all series."""
        now = time.time() if now is None else now
        cutoff = now - window_s
        out: list[tuple[float, dict]] = []
        with self._lock:
            for labels_key, series in (self._series.get(metric) or {}).items():
                labels = dict(labels_key)
                for _ts, value in series.window(cutoff):
                    out.append((value, labels))
        return out

    def rollup(
        self, metric: str, window_s: float, now: Optional[float] = None
    ) -> Optional[dict]:
        values = sorted(v for v, _ in self.window_samples(metric, window_s, now))
        if not values:
            return None
        return _roll(values)

    def join_phase_rollup(
        self, window_s: float, now: Optional[float] = None
    ) -> dict[str, dict]:
        """Per-phase rollups of ``join_phase_seconds`` within the window —
        the critical-path breakdown behind ``tpu_operator_join_phase_seconds``
        and the bench's compile-dominance gate."""
        by_phase: dict[str, list] = {}
        for value, labels in self.window_samples(METRIC_JOIN_PHASE, window_s, now):
            phase = labels.get("phase", "")
            if phase:
                by_phase.setdefault(phase, []).append(value)
        return {
            phase: _roll(sorted(values))
            for phase, values in by_phase.items()
        }

    def metrics_held(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def series_count(self) -> int:
        with self._lock:
            return self._n_series

    def nodes_reporting(self, window_s: float, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        cutoff = now - window_s
        nodes: set[str] = set()
        with self._lock:
            for bucket in self._series.values():
                for labels_key, series in bucket.items():
                    node = dict(labels_key).get("node")
                    if not node or node in nodes or not series.samples:
                        continue
                    newest = (
                        series.samples[-1][0]
                        if series.ordered
                        else max(ts for ts, _ in series.samples)
                    )
                    if newest >= cutoff:
                        nodes.add(node)
        return len(nodes)

    def serving_view(
        self,
        now: Optional[float] = None,
        stale_after_s: Optional[float] = None,
    ) -> dict[str, dict]:
        """Per-replica serving rollups, freshness-stamped.

        Groups the ``tpu_workload_serving_*`` push series by their
        ``workload`` label (the replica name) and reports each replica's
        NEWEST value per counter together with the newest push timestamp::

            {"serve-fd-0": {"ts": 171.2, "age_s": 0.4, "fresh": True,
                            "node": "tpu-3-1",
                            "metrics": {"queue_depth": 2.0,
                                        "kv_blocks_free": 61.0, ...}}}

        ``fresh`` is the router's admission-evidence contract: evidence
        older than ``stale_after_s`` (default ``FRONTDOOR_STALE_PUSHES``
        push intervals) means the replica is UNKNOWN — a blackholed or
        dead engine looks exactly like a quiet one from here, so the
        router must route AWAY from it, never onto it.  The stamp is the
        ingest-side receive time of the newest sample, not anything the
        replica claims about itself: a wedged replica cannot forge
        freshness."""
        now = time.time() if now is None else now
        if stale_after_s is None:
            stale_after_s = (
                consts.FRONTDOOR_STALE_PUSHES * consts.SERVE_PUSH_INTERVAL_SECONDS
            )
        view: dict[str, dict] = {}
        with self._lock:
            for metric, bucket in self._series.items():
                if not metric.startswith(SERVING_METRIC_PREFIX):
                    continue
                short = metric[len(SERVING_METRIC_PREFIX):]
                for labels_key, series in bucket.items():
                    if not series.samples:
                        continue
                    labels = dict(labels_key)
                    replica = labels.get("workload")
                    if not replica:
                        continue
                    ts, value = (
                        series.samples[-1]
                        if series.ordered
                        else max(series.samples)
                    )
                    entry = view.setdefault(
                        replica, {"ts": 0.0, "node": "", "metrics": {}}
                    )
                    entry["metrics"][short] = value
                    if ts > entry["ts"]:
                        entry["ts"] = ts
                        entry["node"] = labels.get("node", "")
        for entry in view.values():
            age = max(0.0, now - entry["ts"])
            entry["ts"] = round(entry["ts"], 3)
            entry["age_s"] = round(age, 3)
            entry["fresh"] = age <= stale_after_s
        return view

    def snapshot(
        self,
        windows: Iterable[float] = consts.FLEET_WINDOWS,
        now: Optional[float] = None,
    ) -> dict:
        """The ``/debug/fleet`` document: per-metric windowed rollups,
        recent exemplars (joinable against ``/debug/traces``), SLO state,
        and aggregator health."""
        now = time.time() if now is None else now
        windows = [float(w) for w in windows]
        metrics: dict[str, dict] = {}
        for metric in self.metrics_held():
            per_window = {
                f"{w:g}s": self.rollup(metric, w, now) for w in windows
            }
            metrics[metric] = {k: v for k, v in per_window.items() if v}
        with self._lock:
            exemplars = {m: list(d) for m, d in self._exemplars.items() if d}
            n_series = self._n_series
        join_phases = {
            f"{w:g}s": roll
            for w in windows
            if (roll := self.join_phase_rollup(w, now))
        }
        return {
            "ts": round(now, 3),
            "windows_s": windows,
            "series": n_series,
            "nodes_reporting": self.nodes_reporting(max(windows), now),
            "metrics": metrics,
            "join_phases": join_phases,
            "exemplars": exemplars,
            "slos": self.slo_engine.snapshot(),
            # freshness-stamped per-replica serving capacity (the front
            # door's routing evidence; docs/SERVING.md "Front door")
            "serving": self.serving_view(now),
        }

    def export(
        self, window_s: float = 300.0, now: Optional[float] = None
    ) -> None:
        """Refresh the bounded ``tpu_operator_fleet_*`` gauges from the
        default window's rollups (called by the Manager's fleet loop)."""
        if self.metrics is None:
            return
        now = time.time() if now is None else now
        _QUANTILE_KEYS = ("p50", "p90", "p99", "min", "max", "mean", "count")
        for metric in self.metrics_held():
            roll = self.rollup(metric, window_s, now)
            if roll is None:
                # a metric whose samples aged out of the window must drop
                # its label sets, not freeze hours-stale rollups on the
                # registry with no staleness marker
                if metric in self._exported:
                    self._exported.discard(metric)
                    for q in _QUANTILE_KEYS:
                        try:
                            self.metrics.fleet_quantile.remove(metric, q)
                        except KeyError:
                            pass
                continue
            self._exported.add(metric)
            for q in _QUANTILE_KEYS:
                self.metrics.fleet_quantile.labels(
                    metric=metric, quantile=q
                ).set(roll[q])
        # the join critical path gets its own bounded family
        # (tpu_operator_join_phase_seconds{phase,quantile}): phase is the
        # fixed JOIN_PHASES vocabulary, so cardinality is 5 × 7 regardless
        # of fleet size
        per_phase = self.join_phase_rollup(window_s, now)
        for phase in self._exported_phases - set(per_phase):
            self._exported_phases.discard(phase)
            for q in _QUANTILE_KEYS:
                try:
                    self.metrics.join_phase_seconds.remove(phase, q)
                except KeyError:
                    pass
        for phase, roll in per_phase.items():
            self._exported_phases.add(phase)
            for q in _QUANTILE_KEYS:
                self.metrics.join_phase_seconds.labels(
                    phase=phase, quantile=q
                ).set(roll[q])
        self.metrics.fleet_series.set(self.series_count())
        self.metrics.fleet_nodes_reporting.set(
            self.nodes_reporting(window_s, now)
        )
