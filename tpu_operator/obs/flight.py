"""Per-step workload flight recorder + node telemetry push client.

The validation/bench workloads used to print ONE JSON line at exit — a
verdict with no history.  This module is the black-box recorder between a
JAX step and a scrape-able time series:

- ``record(check, phase, step=..., **metrics)`` appends one sample to an
  in-memory ring; samples carry a wall-clock ``ts``, the workload ``check``
  name, a ``phase`` (``compile`` / ``run`` / ``step`` / ``result``), an
  optional step index, the metric map, and — when an ``obs.trace`` span is
  active — the span id and reconcile id, so a flight record is joinable
  against ``/debug/traces``.
- Samples persist as a JSONL **flight record** next to the workload's
  result drop-box (``validator.status.flight_record_path``), append-only —
  local workers sharing one validation root accumulate samples instead of
  overwriting each other; the per-node coordinator (the validator, or
  bench.py's sequential launcher) clears the record before a fresh run.
- Each sample also feeds the node's **metrics agent** over its ``/push``
  endpoint (``TPU_METRICS_PUSH_URL``) from a background thread, throttled
  to one POST per ``push_interval`` seconds with backoff on failures —
  ``record()`` never touches the network, so a dead agent costs the
  timed loops nothing — giving ``/metrics`` live ``source="workload"``
  series while a bench is still running.

Like ``obs.trace.span``, the module-level ``record()`` is a no-op unless a
recorder is active — workload code instruments unconditionally and pays
nothing in untracked processes.  Activation is either explicit
(``activate(recorder)``, used by the validator's in-process checks) or
ambient via ``TPU_FLIGHT_RECORD=<path>`` in the environment (used by
run_validation subprocesses and bench.py), resolved lazily on the first
``record()``.  Persistence is best-effort everywhere: telemetry must never
fail a workload.
"""

from __future__ import annotations

import contextlib
import json
import logging
import math
import os
import socket
import threading
import time
import urllib.error
import urllib.request
from contextvars import ContextVar
from typing import Iterator, Optional

from tpu_operator.obs import trace

# environment contract (bench.py / run_validation / workload pods)
RECORD_ENV = "TPU_FLIGHT_RECORD"
PUSH_ENV = "TPU_METRICS_PUSH_URL"

MAX_SAMPLES = 4096  # ring bound: telemetry, not a database
_FLUSH_EVERY = 32   # samples between best-effort JSONL rewrites
# step-profile windows pending push, per check (obs/profile.py plane): a
# dead agent drops the oldest windows, never blocks the step loop
MAX_STEP_WINDOW = 64

# sample metric key → canonical workload counter (agents.metrics_agent
# WORKLOAD_COUNTERS); only mapped keys are pushed — the JSONL record keeps
# every metric, the Prometheus surface keeps the stable catalogue
COUNTER_KEYS = {
    "step_s": "tpu_workload_step_duration_seconds",
    "compile_s": "tpu_workload_compile_seconds",
    "gbps": "tpu_workload_achieved_gbps",
    "tflops": "tpu_workload_achieved_tflops",
    "mfu": "tpu_workload_mfu",
    "tokens_per_sec": "tpu_workload_tokens_per_sec",
    "overhead_dominated": "tpu_workload_overhead_dominated",
    # compile-artifact cache counters (workloads/compile_cache.py
    # ArtifactStore.record_flight_sample) — the warm-pool evidence
    "cache_hits": "tpu_workload_compile_cache_hits_total",
    "cache_misses": "tpu_workload_compile_cache_misses_total",
    "cache_bytes": "tpu_workload_compile_cache_bytes_total",
    # sustained-serving telemetry (workloads/serving.py
    # ServingEngine.telemetry): per-step rolling rollups only — request
    # ids stay inside flight samples, never in the pushed counter surface.
    # Every serving sample key carries the serve_ prefix: this map is
    # GLOBAL across workloads, and a generic name here (queue_depth,
    # requests_completed) would silently publish any other workload's
    # like-named flight metric into the serving SLO feed.
    "serve_tokens_per_sec": "tpu_workload_serving_tokens_per_sec",
    "serve_ttft_p99_s": "tpu_workload_serving_ttft_p99_seconds",
    "serve_tpot_p99_s": "tpu_workload_serving_tpot_p99_seconds",
    "serve_queue_depth": "tpu_workload_serving_queue_depth",
    "serve_batch_size": "tpu_workload_serving_batch_size",
    "serve_kv_blocks_free": "tpu_workload_serving_kv_blocks_free",
    "serve_requests_completed": "tpu_workload_serving_requests_completed_total",
    "serve_requests_rejected": "tpu_workload_serving_requests_rejected_total",
    "serve_decoded_tokens": "tpu_workload_serving_decoded_tokens_total",
    # chip-time accounting evidence (workloads/checkpoint.py training loop
    # + restore path; obs/accounting.py carves busy time from these).
    # acct_* are cumulative-per-process seconds — the ledger deltas them
    # with reset detection, so re-pushed windows credit zero.
    "checkpoint_s": "tpu_workload_checkpoint_seconds",
    "restore_s": "tpu_workload_restore_seconds",
    "acct_useful_s": "tpu_workload_useful_seconds_total",
    "acct_wasted_s": "tpu_workload_wasted_seconds_total",
    "replayed_steps": "tpu_workload_replayed_steps_total",
    "lost_steps": "tpu_workload_lost_steps_total",
}

# result keys worth a flight sample when a check only reports a summary
# dict (record_result): the union of the workloads' headline figures,
# normalized onto the sample metric vocabulary above
_RESULT_KEYS = {
    "gbps": "gbps",
    "algbw_gbps": "gbps",
    "busbw_gbps": "busbw_gbps",
    "link_gbps": "gbps",
    "cache_gbps": "gbps",
    "tflops": "tflops",
    "attn_tflops": "tflops",
    "model_tflops": "tflops",
    "mfu": "mfu",
    "train_mfu": "mfu",
    "tokens_per_sec": "tokens_per_sec",
    "step_time_ms": "step_time_ms",
    "decode_us": "decode_us",
    "time_s": "time_s",
    "duration_s": "duration_s",
    "max_error": "max_error",
    "overhead_dominated": "overhead_dominated",
}


class FlightRecorder:
    """Bounded sample ring with JSONL persistence and throttled push."""

    def __init__(
        self,
        path: str = "",
        push_url: str = "",
        run_id: str = "",
        push_interval: float = 1.0,
        max_samples: int = MAX_SAMPLES,
    ):
        self.path = path
        self.push_url = push_url
        # cross-process trace context (TPU_TRACEPARENT, stamped into the
        # pod env by the operator): samples without an enclosing span still
        # carry the propagated trace id, and every push window names it so
        # the agent hop and fleet ingest can exemplar-link the trace
        env_ctx = trace.TraceContext.from_env()
        self.trace_id = env_ctx.trace_id if env_ctx is not None else ""
        self.run_id = run_id or f"{os.getpid()}-{int(time.time())}"
        self.push_interval = push_interval
        self.max_samples = max_samples
        # host identity stamped onto step-profile windows so merged or
        # re-forwarded push bodies can never misattribute cross-host skew
        # (NODE_NAME is the downward-API contract every workload pod gets)
        self.host = os.environ.get("NODE_NAME", "") or socket.gethostname()
        self.samples: list[dict] = []
        self.dropped = 0
        self._unflushed = 0
        self._persisted = 0  # samples already written to the JSONL record
        # latest counter values per check, merged across samples so one
        # POST carries every workload's current figures; drained by the
        # push thread (record() must NEVER block on the network — a
        # blackholed agent inside a timed benchmark loop would inflate
        # every step_s by the socket timeout)
        self._pending: dict[str, dict] = {}
        # step-profile windows pending push, per check (bounded); and the
        # per-check monotonic step_seq high-water mark — a replayed or
        # out-of-order record_step is dropped HERE, at the source, so no
        # downstream hop ever has to disambiguate duplicate barriers
        self._pending_steps: dict[str, list] = {}
        self._step_seq_hwm: dict[str, int] = {}
        # cumulative samples per check for tpu_workload_steps_total: the
        # exposed series must be monotonic (a per-window count would read
        # as endless Prometheus counter resets)
        self._step_counts: dict[str, int] = {}
        self._push_lock = threading.Lock()
        self._push_wake = threading.Event()
        self._push_thread: Optional[threading.Thread] = None
        self._closed = False

    # ------------------------------------------------------------------
    def record(
        self,
        check: str,
        phase: str = "step",
        step: Optional[int] = None,
        **metrics,
    ) -> dict:
        sample: dict = {
            "ts": round(time.time(), 6),
            "run_id": self.run_id,
            "check": check,
            "phase": phase,
        }
        if step is not None:
            sample["step"] = step
        sp = trace.current_span()
        if sp is not None:
            sample["span_id"] = sp.span_id
            if sp.reconcile_id:
                sample["reconcile_id"] = sp.reconcile_id
        # the propagated trace id: from the enclosing span when one is
        # active (an adopted tracer already joined the remote trace),
        # else straight from the TPU_TRACEPARENT contract
        tid = (sp.trace_id if sp is not None else "") or self.trace_id
        if tid:
            sample["trace_id"] = tid
        # non-finite floats (a NaN loss) would corrupt the JSONL record
        # and the push payload; record their absence, not their poison
        sample["metrics"] = {
            k: v
            for k, v in metrics.items()
            if v is not None
            and not (isinstance(v, float) and not math.isfinite(v))
        }
        self._append(sample)
        self._queue_push(check, sample["metrics"])
        return sample

    def _append(self, sample: dict) -> None:
        if len(self.samples) >= self.max_samples:
            # keep the newest: the tail of a long run is the evidence a
            # regression hunt needs; count what fell off the front
            self.samples.pop(0)
            self.dropped += 1
            if self._persisted > 0:
                self._persisted -= 1
        self.samples.append(sample)
        self._unflushed += 1
        if self.path and self._unflushed >= _FLUSH_EVERY:
            self.flush()

    def record_step(
        self,
        check: str,
        step_seq: int,
        wall_s: float,
        phases: Optional[dict] = None,
    ) -> Optional[dict]:
        """One step-profile window: per-step wall time plus the bounded
        phase breakdown (obs/profile.STEP_PHASES), stamped with this
        host's identity and a per-check MONOTONIC ``step_seq`` — a replay
        or out-of-order call is dropped at the source.  The window rides
        the next push's ``workloads[check]["steps"]`` list and lands in
        the operator's ProfileEngine; the JSONL record keeps it too (the
        soaks' evidence hop reads it back from there)."""
        from tpu_operator.obs import profile as obs_profile

        try:
            seq = int(step_seq)
        except (TypeError, ValueError):
            return None
        if not isinstance(wall_s, (int, float)) or isinstance(wall_s, bool) \
                or not math.isfinite(float(wall_s)) or float(wall_s) < 0:
            return None
        hwm = self._step_seq_hwm.get(check)
        if hwm is not None and seq <= hwm:
            return None
        self._step_seq_hwm[check] = seq
        entry = {
            "step_seq": seq,
            "host": self.host,
            "wall_s": round(float(wall_s), 6),
            "phases": {
                name: round(float(v), 6)
                for name, v in (phases or {}).items()
                if name in obs_profile.STEP_PHASES
                and isinstance(v, (int, float)) and not isinstance(v, bool)
                and math.isfinite(float(v)) and float(v) >= 0.0
            },
        }
        sample: dict = {
            "ts": round(time.time(), 6),
            "run_id": self.run_id,
            "check": check,
            "phase": "step-window",
            "step": seq,
            **entry,
        }
        sp = trace.current_span()
        if sp is not None:
            sample["span_id"] = sp.span_id
            if sp.reconcile_id:
                sample["reconcile_id"] = sp.reconcile_id
        tid = (sp.trace_id if sp is not None else "") or self.trace_id
        if tid:
            sample["trace_id"] = tid
        self._append(sample)
        if self.push_url and not self._closed:
            with self._push_lock:
                queue = self._pending_steps.setdefault(check, [])
                queue.append(entry)
                del queue[:-MAX_STEP_WINDOW]
            if self._push_thread is None:
                self._push_thread = threading.Thread(
                    target=self._push_loop, name="flight-push", daemon=True
                )
                self._push_thread.start()
            self._push_wake.set()
        return sample

    def record_result(self, check: str, result: dict) -> Optional[dict]:
        """One summary sample from a check's result dict (the generic hook
        run_validation applies to EVERY check, so even workloads without
        per-step instrumentation leave a flight trail)."""
        if not isinstance(result, dict):
            return None
        metrics = {}
        for key, name in _RESULT_KEYS.items():
            value = result.get(key)
            if isinstance(value, bool):
                metrics[name] = float(value)
            elif isinstance(value, (int, float)):
                metrics[name] = value
        if not result.get("ok", True):
            metrics["failed"] = 1.0
        return self.record(check, phase="result", **metrics)

    # ------------------------------------------------------------------
    def flush(self) -> None:
        """Append the not-yet-persisted samples to the JSONL record.
        Append-ONLY — never truncate: several local workers sharing one
        validation root (spawn_local_workers, the concurrent partition
        acceptance, single-host multislice dryrun) accumulate samples
        instead of racing to erase each other's.  Staleness is the
        coordinator's job: the validator (one per node) and bench.py
        clear the record before a fresh run, when no writer is live;
        a torn interleaved line is skipped by read_flight_record."""
        self._unflushed = 0
        if not self.path:
            return
        try:
            new = self.samples[self._persisted:]
            if not new:
                return
            lines = []
            for sample in new:
                # per-sample serialization: one non-JSON metric value (a
                # stray numpy scalar) loses its own line, never the whole
                # record from that point on
                try:
                    lines.append(json.dumps(sample) + "\n")
                except (TypeError, ValueError):
                    continue
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "a") as f:
                f.write("".join(lines))
            self._persisted = len(self.samples)
        except Exception as e:  # noqa: BLE001 — telemetry must never fail the workload
            logging.getLogger("tpu_operator.obs.flight").debug(
                "flight flush failed: %s", e
            )

    def close(self) -> None:
        self.flush()
        self._closed = True
        thread = self._push_thread
        if thread is not None:
            self._push_wake.set()
            # bounded: a blackholed agent must not hold the workload's exit
            thread.join(timeout=3.0)

    # ------------------------------------------------------------------
    def _queue_push(self, check: str, metrics: dict) -> None:
        if not self.push_url or self._closed:
            return
        with self._push_lock:
            counters = self._pending.setdefault(check, {})
            for key, counter in COUNTER_KEYS.items():
                value = metrics.get(key)
                if isinstance(value, (bool, int, float)):
                    counters[counter] = float(value)
            self._step_counts[check] = self._step_counts.get(check, 0) + 1
            counters["tpu_workload_steps_total"] = float(self._step_counts[check])
        if self._push_thread is None:
            self._push_thread = threading.Thread(
                target=self._push_loop, name="flight-push", daemon=True
            )
            self._push_thread.start()
        self._push_wake.set()

    def _take_pending(self) -> Optional[dict]:
        with self._push_lock:
            if not self._pending and not any(self._pending_steps.values()):
                return None
            workloads = {
                check: {"counters": dict(counters)}
                for check, counters in self._pending.items()
            }
            for check, steps in self._pending_steps.items():
                if steps:
                    entry = workloads.setdefault(check, {"counters": {}})
                    entry["steps"] = list(steps)
            self._pending.clear()
            self._pending_steps.clear()
        return workloads

    def _requeue(self, workloads: dict) -> None:
        """Put a failed push window back so once-recorded counters (a
        compile_s) survive a transient agent outage; values recorded
        since the take win over the failed window's.  Step-profile
        windows merge back by step_seq (live entries win), so a retried
        POST can never deliver the same barrier twice."""
        with self._push_lock:
            for check, entry in workloads.items():
                live = self._pending.setdefault(check, {})
                merged = {**entry.get("counters", {}), **live}
                live.clear()
                live.update(merged)
                steps = entry.get("steps")
                if steps:
                    queue = self._pending_steps.setdefault(check, [])
                    seen = {s["step_seq"] for s in queue}
                    queue[:0] = [
                        s for s in steps if s["step_seq"] not in seen
                    ]
                    queue.sort(key=lambda s: s["step_seq"])
                    del queue[:-MAX_STEP_WINDOW]

    def _push_loop(self) -> None:
        """Background push thread: drains the pending counters at most once
        per ``push_interval``, with exponential backoff on failures —
        record() itself never touches the network, so a dead or blackholed
        agent costs the measurements nothing."""
        failures = 0
        while True:
            self._push_wake.wait(timeout=self.push_interval)
            self._push_wake.clear()
            if failures:
                # backoff sleep bounded so close() isn't held long
                time.sleep(min(30.0, 2.0 ** failures) if not self._closed else 0)
            workloads = self._take_pending()
            if workloads is None:
                if self._closed:
                    return
                continue
            payload = {
                "source": "workload",
                "run_id": self.run_id,
                "workloads": workloads,
            }
            if self.trace_id:
                payload["trace_id"] = self.trace_id
            body = json.dumps(payload).encode()
            req = urllib.request.Request(
                self.push_url,
                data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=1.0):
                    pass
                failures = 0
            except (urllib.error.URLError, OSError, ValueError):
                failures += 1
                self._requeue(workloads)
            if self._closed and (
                failures
                or not (self._pending or any(self._pending_steps.values()))
            ):
                return
            # throttle between successful pushes
            if not self._closed:
                time.sleep(self.push_interval)


# ---------------------------------------------------------------------------
# ambient recorder (the obs.trace ambient-tracer pattern)

_current: ContextVar[Optional[FlightRecorder]] = ContextVar(
    "tpu_operator_flight", default=None
)
# lazily-resolved env recorder: subprocesses (bench modules, workload pods)
# record without any in-module activation when TPU_FLIGHT_RECORD is set;
# keyed on the env values so a changed environment (tests, re-exec'd
# harnesses) rotates to a fresh recorder instead of serving a stale one
_env_recorder: Optional[FlightRecorder] = None
_env_key: Optional[tuple] = None


def from_env() -> Optional[FlightRecorder]:
    """A recorder configured from the environment, or None when untracked
    (no TPU_FLIGHT_RECORD and no TPU_METRICS_PUSH_URL)."""
    path = os.environ.get(RECORD_ENV, "")
    push = os.environ.get(PUSH_ENV, "")
    if not path and not push:
        return None
    return FlightRecorder(path=path, push_url=push)


def recorder_for(path: str) -> FlightRecorder:
    """Recorder persisting at ``path``, pushing to TPU_METRICS_PUSH_URL
    when set — the construction rule every validation entry point
    (run_validation, distributed, the validator's in-process checks)
    shares.  Deliberately does NOT honor TPU_FLIGHT_RECORD: the drop-box
    path is where the validator reads its flight evidence from
    (status.flight_evidence); an env override would silently divorce the
    samples from the evidence.  The env override is for standalone bench
    modules, which resolve it through ``active()``."""
    return FlightRecorder(path=path, push_url=os.environ.get(PUSH_ENV, ""))


def active() -> Optional[FlightRecorder]:
    recorder = _current.get()
    if recorder is not None:
        return recorder
    global _env_recorder, _env_key
    key = (
        os.environ.get(RECORD_ENV, ""),
        os.environ.get(PUSH_ENV, ""),
        # a changed trace context rotates the recorder too: samples must
        # carry the CURRENT propagated trace id, not the one at first use
        os.environ.get(trace.TRACEPARENT_ENV, ""),
    )
    if key[:2] == ("", ""):
        return None
    if _env_key != key:
        if _env_recorder is not None:
            _env_recorder.close()
        _env_recorder = FlightRecorder(path=key[0], push_url=key[1])
        _env_key = key
    return _env_recorder


@contextlib.contextmanager
def activate(recorder: FlightRecorder) -> Iterator[FlightRecorder]:
    """Make ``recorder`` ambient for the current context; closes (final
    flush + push) on exit."""
    token = _current.set(recorder)
    try:
        yield recorder
    finally:
        _current.reset(token)
        recorder.close()


def record(
    check: str, phase: str = "step", step: Optional[int] = None, **metrics
) -> None:
    """Sample on the AMBIENT recorder; no-op (near-zero cost) when no
    recorder is active — workloads instrument unconditionally."""
    recorder = active()
    if recorder is not None:
        recorder.record(check, phase=phase, step=step, **metrics)


def record_result(check: str, result: dict) -> None:
    recorder = active()
    if recorder is not None:
        recorder.record_result(check, result)


def record_step(
    check: str, step_seq: int, wall_s: float, phases: Optional[dict] = None
) -> None:
    """Step-profile window on the AMBIENT recorder (no-op untracked) —
    the per-step phase-breakdown companion to ``record()``; see
    ``FlightRecorder.record_step``."""
    recorder = active()
    if recorder is not None:
        recorder.record_step(check, step_seq, wall_s, phases=phases)


def close_active() -> None:
    """Final flush+push for the ambient/env recorder (subprocess mains call
    this before exit; the activate() context manager does it for scoped
    recorders)."""
    recorder = active()
    if recorder is not None:
        recorder.close()


def push_join_phases(
    node: str,
    phases: dict,
    trace_id: str = "",
    url: str = "",
    timeout: float = 2.0,
) -> bool:
    """One-shot POST of a node's join→validated phase segments to the
    metrics agent (``TPU_METRICS_PUSH_URL``), which forwards them to the
    operator's fleet ingest where they become
    ``join_phase_seconds{node,phase}`` samples — the critical-path
    decomposition behind ``/debug/explain`` and the
    ``tpu_operator_join_phase_seconds`` rollups.  Blocking by design: the
    validator calls it through ``run_in_executor`` AFTER jax-ready is
    written, off the readiness critical path.  Best-effort like every
    telemetry hop — returns False instead of raising."""
    url = url or os.environ.get(PUSH_ENV, "")
    clean = {
        str(k): float(v)
        for k, v in (phases or {}).items()
        if isinstance(v, (int, float))
        and not isinstance(v, bool)
        and math.isfinite(float(v))
        and float(v) >= 0.0
    }
    if not url or not node or not clean:
        return False
    body: dict = {"source": "workload", "node": node, "join_phases": clean}
    if trace_id:
        body["trace_id"] = trace_id
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout):
            return True
    except (urllib.error.URLError, OSError, ValueError):
        return False
