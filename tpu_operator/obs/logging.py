"""Structured JSON logging correlated with the active span context.

Reference analogue: the zap JSON logs controller-runtime managers emit.
Opt-in via ``--log-format=json`` on the operator/validator binaries and the
agent entrypoints (or ``TPU_OPERATOR_LOG_FORMAT=json`` for entrypoints
without a flag surface).  Every JSON record carries the active reconcile
id, controller, and operand state pulled from ``obs.trace.log_context()``,
so one reconcile pass is greppable across the whole process's log stream.
"""

from __future__ import annotations

import json
import logging
import sys

TEXT_FORMAT = "%(asctime)s %(levelname)s %(name)s %(message)s"

FORMAT_TEXT = "text"
FORMAT_JSON = "json"


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        from tpu_operator.obs import trace

        out: dict = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        out.update(trace.log_context())
        if record.exc_info:
            out["exc_info"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class _StderrHandler(logging.StreamHandler):
    """StreamHandler resolving ``sys.stderr`` at EMIT time (the pattern of
    logging's lastResort handler): a handler pinned to the stderr of setup
    time breaks when the stream is swapped and closed underneath it —
    pytest's capture does exactly that per test."""

    def __init__(self):
        logging.Handler.__init__(self)

    @property
    def stream(self):
        return sys.stderr


def setup(fmt: str = FORMAT_TEXT, level: int = logging.INFO) -> None:
    """Configure root logging in the requested format.  Replaces existing
    root handlers (unlike ``basicConfig``) so re-invocation — tests, agent
    oneshots — deterministically lands on the requested format."""
    handler = _StderrHandler()
    handler.setFormatter(
        JsonFormatter() if fmt == FORMAT_JSON else logging.Formatter(TEXT_FORMAT)
    )
    root = logging.getLogger()
    root.setLevel(level)
    root.handlers[:] = [handler]
