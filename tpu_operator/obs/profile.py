"""Continuous profiling & straggler attribution plane.

The chip-time ledger (obs/accounting.py) says how much chip-time was
useful; this module says where inside a step the time went and which host
of a multi-host slice is dragging the collective.  On an ICI mesh every
step ends at an implicit barrier: one slow host stalls every peer, the
peers book the stall as collective-wait, and the loss is invisible to
per-process metrics because everyone's *wall* time converges on the
slowest host.  Attribution therefore needs per-host, per-step phase
evidence — exactly what this plane moves:

**Workload side.**  :class:`StepTimer` accumulates bounded per-step phase
spans (``STEP_PHASES``: compile / host-input / compute / collective-wait)
inside the existing step loops; ``flight.record_step`` stamps each step
window with a monotonic ``step_seq`` and the host identity and ships it
through the same agent push hop the workload counters ride (bounded
vocabulary, like ``join_phase_seconds``).  :class:`FileStepBarrier` is the
env-gated step barrier multi-host training loops synchronize on when the
runtime provides no collective (CPU soaks, tests) — the wait it returns
IS the collective-wait phase.

**Operator side.**  :class:`ProfileEngine` hangs off the FleetAggregator's
push ingest: it groups step windows per (slice, step_seq) barrier using
the ``consts.SLICE_REQUEST_LABEL`` node stamps the scheduler already
maintains, computes per-host **work** (wall − collective-wait), and calls
the straggler: ``skew = max(work) − min(work)`` per barrier, slow host =
argmax(work), ``skew_ratio = skew / mean(wall)``.  A ratio past the
configured threshold for ``sustained_steps`` consecutive barriers fires a
``StragglerDetected`` verdict (the Manager posts the Event); behind the
opt-in ``feedHealthEngine`` gate the named host feeds the health engine a
sustained ``straggler:<slice>`` signal so detection can drive the
existing quarantine→migrate ladder.

Exports stay bounded: ``tpu_operator_step_phase_seconds{phase,quantile}``
(4×7 series), ``step_skew_ratio`` / ``step_idle_fraction`` headline
gauges, and a stragglers counter.  Per-host and per-slice detail lives
only in the ``GET /debug/profile`` document, which also splits the
ledger's ``busy_useful`` into compute vs collective-wait —
``step_idle_fraction`` is the signal ROADMAP item 4 (Podracer-style RL
fleets) scales actor counts off.
"""

from __future__ import annotations

import contextlib
import math
import os
import time
from collections import OrderedDict, deque
from typing import Iterable, Iterator, Optional

from tpu_operator import consts
from tpu_operator.utils import deep_get

# The bounded per-step phase vocabulary (the ONLY phase label values that
# may reach Prometheus; the metric-labels lint and the agent hop both pin
# membership here).
PHASE_COMPILE = "compile"
PHASE_HOST_INPUT = "host-input"
PHASE_COMPUTE = "compute"
PHASE_COLLECTIVE_WAIT = "collective-wait"

STEP_PHASES = (
    PHASE_COMPILE,
    PHASE_HOST_INPUT,
    PHASE_COMPUTE,
    PHASE_COLLECTIVE_WAIT,
)

# environment contract for the file step barrier (bench.py straggler soak,
# multi-host CPU training pods sharing a filesystem)
BARRIER_DIR_ENV = "TPU_STEP_BARRIER_DIR"
BARRIER_WORLD_ENV = "TPU_STEP_BARRIER_WORLD"
BARRIER_RANK_ENV = "TPU_STEP_BARRIER_RANK"
BARRIER_TIMEOUT_ENV = "TPU_STEP_BARRIER_TIMEOUT_S"

# step windows per check per push (agent-side cap mirrors this)
MAX_STEPS_PER_PUSH = 128

# barrier markers each rank keeps behind its own head: the catch-up
# budget for a member restored from a checkpoint while its peers
# free-ran (markers are one tiny file each, GC'd as the rank advances)
REPLAY_WINDOW_STEPS = 8192

_QUANTILE_KEYS = ("p50", "p90", "p99", "min", "max", "mean", "count")

_PHASE_RING = 2048          # per-phase sample ring (fleet-wide)
_BARRIERS_PER_SLICE = 128   # retained step_seqs per slice
_HOSTS_PER_BARRIER = 64     # hosts tracked per (slice, step_seq)
_SEEN_PER_SOURCE = 512      # dedup ring per (node, check)
_INCOMPLETE_GRACE_S = 30.0  # how long a barrier may wait for late hosts


def _quantile(ordered: list, q: float) -> float:
    """Linear-interpolation quantile over an ASCENDING list (the
    obs/fleet.quantile contract, duplicated here so obs/profile stays
    import-free of the aggregator)."""
    if not ordered:
        return 0.0
    if len(ordered) == 1:
        return float(ordered[0])
    pos = q * (len(ordered) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(ordered) - 1)
    frac = pos - lo
    return float(ordered[lo]) * (1 - frac) + float(ordered[hi]) * frac


def _roll(values: Iterable[float]) -> dict:
    ordered = sorted(float(v) for v in values)
    if not ordered:
        return {k: 0.0 for k in _QUANTILE_KEYS}
    return {
        "p50": round(_quantile(ordered, 0.50), 6),
        "p90": round(_quantile(ordered, 0.90), 6),
        "p99": round(_quantile(ordered, 0.99), 6),
        "min": round(ordered[0], 6),
        "max": round(ordered[-1], 6),
        "mean": round(sum(ordered) / len(ordered), 6),
        "count": float(len(ordered)),
    }


def _finite(value) -> Optional[float]:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    v = float(value)
    if not math.isfinite(v) or v < 0.0:
        return None
    return v


def clean_steps(steps, limit: int = MAX_STEPS_PER_PUSH) -> list[dict]:
    """Validate/normalize a pushed step-window list onto the canonical
    entry shape ``{step_seq, host, wall_s, phases}`` — the shared gate the
    agent hop and the fleet ingest both apply, so a malformed or
    vocabulary-busting entry is dropped at the first hop it touches."""
    out: list[dict] = []
    if not isinstance(steps, (list, tuple)):
        return out
    for entry in steps:
        if len(out) >= limit:
            break
        if not isinstance(entry, dict):
            continue
        try:
            seq = int(entry.get("step_seq"))
        except (TypeError, ValueError):
            continue
        wall = _finite(entry.get("wall_s"))
        if seq < 0 or wall is None:
            continue
        host = str(entry.get("host") or "")[:64]
        phases = entry.get("phases") or {}
        clean_phases: dict[str, float] = {}
        if isinstance(phases, dict):
            for name in STEP_PHASES:
                v = _finite(phases.get(name))
                if v is not None:
                    clean_phases[name] = round(v, 6)
        out.append({
            "step_seq": seq,
            "host": host,
            "wall_s": round(wall, 6),
            "phases": clean_phases,
        })
    return out


# ---------------------------------------------------------------------------
# workload side


class StepTimer:
    """Per-step phase accumulator for workload step loops.

    ``with timer.phase(PHASE_COMPUTE): ...`` adds the block's wall time to
    the phase's span; ``spans()`` yields the bounded phase→seconds map a
    ``flight.record_step`` window carries.  Phase names are closed over
    ``STEP_PHASES`` — an unknown name raises immediately (at development
    time, in the loop author's face) rather than minting unbounded label
    values three hops downstream."""

    def __init__(self):
        self._spans: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        if name not in STEP_PHASES:
            raise ValueError(
                f"unknown step phase {name!r} (bounded vocabulary: {STEP_PHASES})"
            )
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._spans[name] = (
                self._spans.get(name, 0.0) + (time.perf_counter() - t0)
            )

    def add(self, name: str, seconds: float) -> None:
        """Credit already-measured seconds to a phase (loops that time a
        region themselves, e.g. a barrier wait returning its duration)."""
        if name not in STEP_PHASES:
            raise ValueError(
                f"unknown step phase {name!r} (bounded vocabulary: {STEP_PHASES})"
            )
        v = _finite(seconds)
        if v is not None:
            self._spans[name] = self._spans.get(name, 0.0) + v

    def spans(self) -> dict:
        return dict(self._spans)

    def reset(self) -> None:
        self._spans.clear()


class FileStepBarrier:
    """Filesystem step barrier for multi-host training loops.

    Emulates the per-step ICI collective sync on hosts that share a
    filesystem (the straggler soak, CPU tests): each member writes a
    ``step-<n>.<rank>`` marker then polls until every live rank's marker
    exists; :meth:`wait` returns the seconds spent blocked — which IS the
    step's collective-wait phase.  A member that exits cleanly mid-run (a
    migrating checkpoint handler) calls :meth:`leave` so peers stop
    waiting on it; a restored process re-joins by constructing a fresh
    barrier (the ctor clears its own leave marker).  A dead peer that
    never said goodbye costs at most ``timeout_s`` per step — the barrier
    degrades to free-running, it never wedges the loop."""

    def __init__(
        self,
        root: str,
        world: int,
        rank: int,
        poll_s: float = 0.002,
        timeout_s: float = 20.0,
    ):
        self.root = root
        self.world = world
        self.rank = rank
        self.poll_s = poll_s
        self.timeout_s = timeout_s
        os.makedirs(self.root, exist_ok=True)
        # re-join: a restored member withdraws its goodbye
        with contextlib.suppress(OSError):
            os.remove(self._leave_path(rank))

    @classmethod
    def from_env(cls, env=None) -> Optional["FileStepBarrier"]:
        env = os.environ if env is None else env
        root = env.get(BARRIER_DIR_ENV, "")
        if not root:
            return None
        try:
            world = int(env.get(BARRIER_WORLD_ENV, "0"))
            rank = int(env.get(BARRIER_RANK_ENV, "-1"))
            timeout_s = float(env.get(BARRIER_TIMEOUT_ENV, "20") or 20)
        except (TypeError, ValueError):
            return None
        if world < 2 or not 0 <= rank < world:
            return None
        return cls(root, world, rank, timeout_s=timeout_s)

    def _marker(self, step: int, rank: int) -> str:
        return os.path.join(self.root, f"step-{step:08d}.{rank}")

    def _leave_path(self, rank: int) -> str:
        return os.path.join(self.root, f"leave.{rank}")

    def _publish(self, path: str) -> None:
        """tmp+replace even for a marker: peers read the arrival stamp,
        and a torn file still satisfies os.path.exists."""
        tmp = f"{path}.tmp.{self.rank}"
        with open(tmp, "w") as f:
            f.write(str(round(time.time(), 6)))
        os.replace(tmp, path)

    def wait(self, step: int) -> float:
        """Arrive at ``step``'s barrier; block until every live rank has
        arrived (or ``timeout_s``); return the seconds spent waiting."""
        t0 = time.perf_counter()
        try:
            self._publish(self._marker(step, self.rank))
        except OSError:
            return 0.0  # barrier storage gone: free-run, don't crash
        deadline = t0 + self.timeout_s
        while True:
            arrived = 0
            for r in range(self.world):
                if (os.path.exists(self._marker(step, r))
                        or os.path.exists(self._leave_path(r))):
                    arrived += 1
            if arrived >= self.world or time.perf_counter() >= deadline:
                break
            time.sleep(self.poll_s)
        # best-effort GC of my stale markers, keeping a REPLAY WINDOW of
        # recent steps: a member restored from a checkpoint behind its
        # peers must find their already-published markers and catch up at
        # full speed instead of paying timeout_s per replayed step.  The
        # window must exceed the furthest a free-running survivor can
        # drift during one migration (leave -> restore), else the
        # replayer times out per step and never closes the gap.
        with contextlib.suppress(OSError):
            os.remove(self._marker(step - REPLAY_WINDOW_STEPS, self.rank))
        return time.perf_counter() - t0

    def leave(self) -> None:
        """Say goodbye: peers count this rank as arrived from now on."""
        with contextlib.suppress(OSError):
            self._publish(self._leave_path(self.rank))


# ---------------------------------------------------------------------------
# operator side


class ProfileEngine:
    """Fleet-side step-phase aggregation + per-slice straggler detection.

    Fed by ``FleetAggregator.ingest_push`` (step windows riding the
    workload push hop) and by the clusterpolicy pass's cached node list
    (slice membership from ``consts.SLICE_REQUEST_LABEL`` stamps — zero
    extra API verbs).  Thread-hostile like every controller object here:
    single asyncio loop, synchronous cheap methods."""

    def __init__(self, metrics=None, ledger=None, clock=time.monotonic,
                 window_s: float = float(consts.FLEET_WINDOWS[0])):
        self.metrics = metrics
        self.ledger = ledger
        self.clock = clock
        self.window_s = window_s
        # config (ProfilingSpec; configure() re-syncs each reconcile pass)
        self.enabled = True
        self.feed_health_engine = False
        self.skew_ratio_threshold = 0.25
        self.sustained_steps = 3
        self.min_hosts = 2
        # node -> owning slice request (from node label stamps)
        self._node_slice: dict[str, str] = {}
        # phase -> deque[(ts, seconds)] — fleet-wide rollup rings
        self._phase_rings: dict[str, deque] = {
            p: deque(maxlen=_PHASE_RING) for p in STEP_PHASES
        }
        # (ts, wall_s, collective_wait_s) — the idle-fraction ring
        self._wall_ring: deque = deque(maxlen=_PHASE_RING)
        # slice -> step_seq -> host -> {wall, cw, ts}
        self._slices: dict[str, OrderedDict] = {}
        # (node, check) -> (set of seen seqs, eviction ring) — the
        # out-of-order / re-delivered window dedup (satellite: step_seq +
        # host identity make merged windows idempotent, not double-counted)
        self._seen: dict[tuple, tuple] = {}
        # slice -> rolling streak state for hysteresis
        self._streaks: dict[str, dict] = {}
        # slice -> newest evaluated verdict (snapshot surface)
        self._verdicts: dict[str, dict] = {}
        # slice -> active straggler {node, ratio, skew_s, step_seq, since}
        self._active: dict[str, dict] = {}
        self._eval_hwm: dict[str, int] = {}
        self.steps_ingested = 0
        self.duplicates_dropped = 0
        self.windows_rejected = 0
        self.stragglers_detected_total = 0
        self._exported_stragglers = 0

    # -- config --------------------------------------------------------
    def configure(self, spec) -> None:
        """Sync knobs from the CR's observability.profiling spec (called
        from the clusterpolicy pass; a None spec keeps defaults)."""
        if spec is None:
            return
        self.enabled = bool(getattr(spec, "enabled", True))
        self.feed_health_engine = bool(
            getattr(spec, "feed_health_engine", False)
        )
        thr = _finite(getattr(spec, "skew_ratio_threshold", None))
        if thr:
            self.skew_ratio_threshold = thr
        try:
            self.sustained_steps = max(
                1, int(getattr(spec, "sustained_steps", self.sustained_steps))
            )
            self.min_hosts = max(
                2, int(getattr(spec, "min_hosts", self.min_hosts))
            )
        except (TypeError, ValueError):
            pass

    # -- membership ----------------------------------------------------
    def observe_nodes(self, nodes: Iterable[dict]) -> None:
        """Refresh node→slice membership from the cached node list the
        clusterpolicy pass already holds (zero API verbs)."""
        live: dict[str, str] = {}
        for node in nodes or ():
            name = deep_get(node, "metadata", "name", default="")
            labels = deep_get(node, "metadata", "labels", default={}) or {}
            owner = labels.get(consts.SLICE_REQUEST_LABEL, "")
            if name and owner:
                live[name] = owner
        self._node_slice = live
        owned = set(live.values())
        for gone in [s for s in self._slices if s not in owned]:
            # released slice: drop its barriers/streaks; an active verdict
            # is resolved by evaluate() (emits the recovery event)
            self._slices.pop(gone, None)
            self._streaks.pop(gone, None)
            self._eval_hwm.pop(gone, None)

    # -- ingest (the push hop) -----------------------------------------
    def observe_push(self, node: str, workloads: dict,
                     now: Optional[float] = None) -> None:
        """Fold one agent push's step windows (mirror of
        ``ChipTimeLedger.observe_push``, called from the same
        ``ingest_push`` hook)."""
        if not self.enabled:
            return
        for check, payload in (workloads or {}).items():
            steps = (payload or {}).get("steps")
            if steps:
                self.observe_steps(node, check, steps, now=now)

    def observe_steps(self, node: str, check: str, steps,
                      now: Optional[float] = None) -> None:
        now = self.clock() if now is None else now
        entries = clean_steps(steps)
        if len(entries) != len(steps or ()):
            self.windows_rejected += len(steps or ()) - len(entries)
        slice_name = self._node_slice.get(node, "")
        seen = self._seen.get((node, check))
        if seen is None:
            seen = (set(), deque(maxlen=_SEEN_PER_SOURCE))
            self._seen[(node, check)] = seen
        seen_set, seen_ring = seen
        for entry in entries:
            seq = entry["step_seq"]
            if seq in seen_set:
                self.duplicates_dropped += 1
                continue
            if len(seen_ring) == seen_ring.maxlen:
                seen_set.discard(seen_ring[0])
            seen_ring.append(seq)
            seen_set.add(seq)
            self.steps_ingested += 1
            wall = entry["wall_s"]
            phases = entry["phases"]
            cw = min(phases.get(PHASE_COLLECTIVE_WAIT, 0.0), wall)
            for name, v in phases.items():
                self._phase_rings[name].append((now, v))
            self._wall_ring.append((now, wall, cw))
            if not slice_name:
                continue
            host = entry["host"] or node
            barriers = self._slices.setdefault(slice_name, OrderedDict())
            row = barriers.setdefault(seq, {})
            if len(row) < _HOSTS_PER_BARRIER:
                row[host] = {"wall": wall, "cw": cw, "ts": now}
            while len(barriers) > _BARRIERS_PER_SLICE:
                barriers.popitem(last=False)

    # -- the detector --------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> list[dict]:
        """One detection pass; returns transition events for the Manager
        to post (``kind`` fired|recovered, plus the verdict fields).

        Skew is computed over per-host **work** (wall − collective-wait):
        with a real barrier every host's wall converges on the slowest
        host, so raw wall skew reads ~0 exactly when a straggler exists —
        the slow host is the one doing the most work (equivalently,
        waiting the least)."""
        now = self.clock() if now is None else now
        events: list[dict] = []
        if self.enabled:
            for slice_name, barriers in self._slices.items():
                self._evaluate_slice(slice_name, barriers, now)
        # resolve verdicts whose slice released/recovered
        for slice_name in list(self._active):
            verdict = self._active[slice_name]
            streak = self._streaks.get(slice_name, {})
            released = slice_name not in self._slices
            clean = streak.get("clean", 0) >= self.sustained_steps
            if released or clean or not self.enabled:
                self._active.pop(slice_name)
                if not self.enabled:
                    # drop the streak too: a re-enable must re-earn the
                    # sustained evidence, not re-fire off stale state
                    self._streaks.pop(slice_name, None)
                events.append({
                    "kind": "recovered",
                    "slice": slice_name,
                    "node": verdict["node"],
                    "ratio": streak.get("ratio", 0.0),
                    "reason": "released" if released else "clean",
                })
        # fire the new ones (after recoveries so a re-fire orders sanely)
        for slice_name, streak in self._streaks.items():
            if (self.enabled
                    and streak.get("count", 0) >= self.sustained_steps
                    and slice_name not in self._active):
                verdict = {
                    "node": streak["host"],
                    "ratio": round(streak["ratio"], 6),
                    "skew_s": round(streak["skew_s"], 6),
                    "step_seq": streak["step_seq"],
                    "since": round(now, 3),
                }
                self._active[slice_name] = verdict
                self.stragglers_detected_total += 1
                events.append({"kind": "fired", "slice": slice_name, **verdict})
        return events

    def _evaluate_slice(self, slice_name: str, barriers: OrderedDict,
                        now: float) -> None:
        hwm = self._eval_hwm.get(slice_name, -1)
        streak = self._streaks.setdefault(
            slice_name,
            {"host": "", "count": 0, "clean": 0, "ratio": 0.0,
             "skew_s": 0.0, "step_seq": -1},
        )
        for seq in sorted(s for s in barriers if s > hwm):
            row = barriers[seq]
            if len(row) < self.min_hosts:
                newest = max(r["ts"] for r in row.values())
                if now - newest <= _INCOMPLETE_GRACE_S:
                    # peers may still arrive; later seqs wait behind it so
                    # barriers are judged in order
                    break
                self._eval_hwm[slice_name] = seq
                continue
            work = {
                h: max(0.0, r["wall"] - r["cw"]) for h, r in row.items()
            }
            mean_wall = sum(r["wall"] for r in row.values()) / len(row)
            slow = max(work, key=lambda h: work[h])
            skew = work[slow] - min(work.values())
            ratio = skew / mean_wall if mean_wall > 0 else 0.0
            self._eval_hwm[slice_name] = seq
            self._verdicts[slice_name] = {
                "step_seq": seq,
                "hosts": sorted(row),
                "slow_host": slow,
                "skew_seconds": round(skew, 6),
                "skew_ratio": round(ratio, 6),
                "mean_wall_s": round(mean_wall, 6),
                "idle_fraction": round(
                    sum(r["cw"] for r in row.values())
                    / max(1e-9, sum(r["wall"] for r in row.values())),
                    6,
                ),
            }
            if ratio >= self.skew_ratio_threshold:
                if streak["host"] == slow:
                    streak["count"] += 1
                else:
                    streak.update(host=slow, count=1)
                streak.update(
                    clean=0, ratio=ratio, skew_s=skew, step_seq=seq
                )
            else:
                streak.update(count=0, ratio=ratio, skew_s=skew,
                              step_seq=seq)
                streak["clean"] += 1

    # -- actuation coupling (opt-in) -----------------------------------
    def node_offenders(self, node: str) -> list[str]:
        """Sustained health-engine signals for ``node``: one
        ``straggler:<slice>`` per active verdict naming it as the slow
        host.  Empty unless ``feedHealthEngine`` — fleet ingest is an
        unauthenticated route, so detection drives actuation only when an
        operator opted this trust boundary in (the SLOSpec precedent)."""
        if not (self.enabled and self.feed_health_engine):
            return []
        return [
            f"straggler:{slice_name}"
            for slice_name, verdict in sorted(self._active.items())
            if verdict.get("node") == node
        ]

    # -- read side -----------------------------------------------------
    def _window_rollups(self, now: float) -> tuple[dict, float, float]:
        """(per-phase rollups, idle_fraction, wall_sum) over the window."""
        cutoff = now - self.window_s
        phases = {}
        for name, ring in self._phase_rings.items():
            phases[name] = _roll(v for ts, v in ring if ts >= cutoff)
        wall_sum = cw_sum = 0.0
        for ts, wall, cw in self._wall_ring:
            if ts >= cutoff:
                wall_sum += wall
                cw_sum += cw
        idle = cw_sum / wall_sum if wall_sum > 0 else 0.0
        return phases, idle, wall_sum

    def skew_ratio(self) -> float:
        """Headline gauge: the worst newest-barrier skew ratio across
        slices (0 with no multi-host evidence)."""
        return max(
            (v["skew_ratio"] for v in self._verdicts.values()), default=0.0
        )

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The ``GET /debug/profile`` document."""
        now = self.clock() if now is None else now
        phases, idle, wall_sum = self._window_rollups(now)
        slices = {}
        for slice_name, verdict in sorted(self._verdicts.items()):
            active = self._active.get(slice_name)
            streak = self._streaks.get(slice_name, {})
            slices[slice_name] = {
                **verdict,
                "straggler": active is not None,
                "sustained_over": streak.get("count", 0),
                **({"detected": active} if active else {}),
            }
        doc = {
            "ts": round(now, 3),
            "enabled": self.enabled,
            "feed_health_engine": self.feed_health_engine,
            "window_seconds": self.window_s,
            "skew_ratio_threshold": self.skew_ratio_threshold,
            "sustained_steps": self.sustained_steps,
            "phases": phases,
            "step_idle_fraction": round(idle, 6),
            "step_skew_ratio": round(self.skew_ratio(), 6),
            "slices": slices,
            "stragglers": {
                name: dict(v) for name, v in sorted(self._active.items())
            },
            "counters": {
                "steps_ingested": self.steps_ingested,
                "duplicates_dropped": self.duplicates_dropped,
                "windows_rejected": self.windows_rejected,
                "stragglers_detected_total": self.stragglers_detected_total,
            },
        }
        if self.ledger is not None:
            # MFU/idle attribution against the chip-time ledger: split the
            # carved busy_useful chip-seconds by the window's phase mix —
            # the compute share is real progress, the collective-wait
            # share is the straggler/topology tax inside "useful" time
            try:
                rollup = self.ledger.rollup(now)
                cons = self.ledger.conservation(now)
                states, _ = self.ledger._carve()
                useful = states.get("busy_useful", 0.0)
                doc["attribution"] = {
                    "busy_useful_chip_seconds": round(useful, 6),
                    "busy_useful_compute": round(useful * (1 - idle), 6),
                    "busy_useful_collective_wait": round(useful * idle, 6),
                    "goodput_ratio": rollup["goodput_ratio"],
                    "chip_utilization": rollup["chip_utilization"],
                    "wall_chip_seconds": cons["wall_chip_seconds"],
                }
            except Exception:  # noqa: BLE001 — read-side join is best-effort
                doc["attribution"] = None
        return doc

    # -- export --------------------------------------------------------
    def export(self, now: Optional[float] = None) -> None:
        """Refresh the bounded Prometheus families (called from the
        Manager's fleet-eval tick, after evaluate())."""
        if self.metrics is None:
            return
        now = self.clock() if now is None else now
        phases, idle, _ = self._window_rollups(now)
        for name, roll in phases.items():
            for q in _QUANTILE_KEYS:
                self.metrics.step_phase_seconds.labels(
                    phase=name, quantile=q
                ).set(roll[q])
        self.metrics.step_idle_fraction.set(round(idle, 6))
        self.metrics.step_skew_ratio.set(round(self.skew_ratio(), 6))
        delta = self.stragglers_detected_total - self._exported_stragglers
        if delta > 0:
            self.metrics.stragglers_detected_total.inc(delta)
            self._exported_stragglers = self.stragglers_detected_total
