"""Lightweight span/trace API with contextvar reconcile-id propagation.

Fills the observability role controller-runtime's built-in instrumentation
plays for the reference (per-controller reconcile duration histograms,
controller_runtime_reconcile_* families): every reconcile pass opens a root
span carrying a fresh reconcile id; nested spans (per-operand-state sync,
k8s requests, apply calls, validator phases) inherit it through a
contextvar, so one pass is correlatable across the four controllers, the
apply layer, and the log stream without threading ids by hand.

Completed spans feed the duration Histograms on ``OperatorMetrics`` (keyed
by span kind) and completed ROOT spans are serialized into a bounded ring
buffer the Manager serves as JSON at ``/debug/traces``.

Spans are deliberately synchronous context managers: they only stamp
timestamps on enter/exit, so wrapping ``await``-ing code is safe — each
asyncio task carries its own context copy, and set/reset happen within the
owning task.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
import uuid
from collections import deque
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Iterator, Optional

# Span kinds — each maps to one Histogram family on OperatorMetrics.
KIND_RECONCILE = "reconcile"  # reconcile_duration_seconds{controller}
KIND_STATE = "state"          # state_sync_duration_seconds{state}
KIND_K8S = "k8s"              # k8s_request_duration_seconds{verb}
KIND_APPLY = "apply"          # apply_duration_seconds{kind}
KIND_PHASE = "phase"          # workload_phase_duration_seconds{phase}

DEFAULT_MAX_TRACES = 64

_current_tracer: ContextVar[Optional["Tracer"]] = ContextVar(
    "tpu_operator_tracer", default=None
)
_current_span: ContextVar[Optional["Span"]] = ContextVar(
    "tpu_operator_span", default=None
)


def new_reconcile_id() -> str:
    return uuid.uuid4().hex[:12]


def new_span_id() -> str:
    return uuid.uuid4().hex[:12]


@dataclass
class Span:
    name: str
    kind: str = ""
    attrs: dict = field(default_factory=dict)
    reconcile_id: str = ""
    span_id: str = field(default_factory=new_span_id)
    parent: Optional["Span"] = field(default=None, repr=False)
    start_ts: float = 0.0  # wall clock, for humans reading /debug/traces
    duration_s: Optional[float] = None
    error: Optional[str] = None
    children: list = field(default_factory=list)
    _t0: float = field(default=0.0, repr=False)

    def to_dict(self) -> dict:
        d: dict = {
            "name": self.name,
            "kind": self.kind,
            "reconcile_id": self.reconcile_id,
            "span_id": self.span_id,
            "start_ts": round(self.start_ts, 6),
            "duration_s": self.duration_s,
        }
        attrs = {k: v for k, v in self.attrs.items() if v not in (None, "")}
        if attrs:
            d["attrs"] = attrs
        if self.error:
            d["error"] = self.error
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


def current_span() -> Optional[Span]:
    return _current_span.get()


def reconcile_id() -> str:
    sp = _current_span.get()
    return sp.reconcile_id if sp is not None else ""


def log_context() -> dict:
    """The correlation fields a log record should carry: the active
    reconcile id plus the nearest enclosing controller and operand state,
    found by walking the span chain upward."""
    out: dict = {}
    sp = _current_span.get()
    while sp is not None:
        if sp.reconcile_id and "reconcile_id" not in out:
            out["reconcile_id"] = sp.reconcile_id
        if sp.kind == KIND_RECONCILE and "controller" not in out:
            out["controller"] = sp.attrs.get("controller", "")
        if sp.kind == KIND_STATE and "state" not in out:
            out["state"] = sp.attrs.get("state", "")
        sp = sp.parent
    return out


class Tracer:
    """Span factory + completed-trace ring buffer.

    One Tracer is shared by the manager and every reconciler so a single
    ``/debug/traces`` endpoint sees all controllers; ``metrics`` (an
    ``OperatorMetrics``) is optional — spans still form traces without it
    (standalone validator / workload processes).
    """

    def __init__(self, metrics=None, max_traces: int = DEFAULT_MAX_TRACES, fleet=None):
        self.metrics = metrics
        # optional obs.fleet.FleetAggregator sink: completed reconcile root
        # spans become fleet duration samples carrying exemplar span ids,
        # so an SLO breach jumps straight to /debug/traces?reconcile_id=
        self.fleet = fleet
        self.traces: deque = deque(maxlen=max_traces)  # newest first
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def activate(self) -> Iterator["Tracer"]:
        """Make this tracer ambient for the current context, so the
        module-level ``span()`` used by library code (k8s client, apply,
        workload checks) records into it without plumbing."""
        token = _current_tracer.set(self)
        try:
            yield self
        finally:
            _current_tracer.reset(token)

    @contextlib.contextmanager
    def reconcile(self, controller: str, key: str = "") -> Iterator[Span]:
        """Root span of one reconcile pass; mints the pass's reconcile id."""
        with self.span(
            f"reconcile/{controller}",
            kind=KIND_RECONCILE,
            reconcile_id=new_reconcile_id(),
            controller=controller,
            key=key,
        ) as sp:
            yield sp

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        kind: str = "",
        reconcile_id: Optional[str] = None,
        **attrs,
    ) -> Iterator[Span]:
        parent = _current_span.get()
        rid = reconcile_id or (parent.reconcile_id if parent is not None else "")
        sp = Span(
            name=name,
            kind=kind,
            attrs=attrs,
            reconcile_id=rid,
            parent=parent,
            start_ts=time.time(),
            _t0=time.monotonic(),
        )
        if parent is not None:
            parent.children.append(sp)
        span_token = _current_span.set(sp)
        tracer_token = _current_tracer.set(self)
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"[:500]
            raise
        finally:
            sp.duration_s = round(time.monotonic() - sp._t0, 6)
            _current_span.reset(span_token)
            _current_tracer.reset(tracer_token)
            self._observe(sp)
            if parent is None:
                with self._lock:
                    self.traces.appendleft(sp.to_dict())

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self.traces)

    def _observe(self, sp: Span) -> None:
        if self.fleet is not None:
            self.fleet.observe_span(sp)  # swallows its own failures
        m = self.metrics
        if m is None or sp.duration_s is None:
            return
        try:
            if sp.kind == KIND_RECONCILE:
                m.reconcile_duration.labels(
                    controller=sp.attrs.get("controller", "")
                ).observe(sp.duration_s)
            elif sp.kind == KIND_STATE:
                m.state_sync_duration.labels(
                    state=sp.attrs.get("state", "")
                ).observe(sp.duration_s)
            elif sp.kind == KIND_K8S:
                m.k8s_request_duration.labels(
                    verb=sp.attrs.get("verb", "")
                ).observe(sp.duration_s)
            elif sp.kind == KIND_APPLY:
                m.apply_duration.labels(
                    kind=sp.attrs.get("object_kind", "")
                ).observe(sp.duration_s)
            elif sp.kind == KIND_PHASE:
                m.workload_phase_duration.labels(
                    phase=sp.attrs.get("phase", "")
                ).observe(sp.duration_s)
        except Exception as e:  # noqa: BLE001 — timing is evidence, not control flow
            logging.getLogger("tpu_operator.obs.trace").debug(
                "span metric emission failed: %s", e
            )


@contextlib.contextmanager
def span(name: str, kind: str = "", **attrs) -> Iterator[Optional[Span]]:
    """Span on the AMBIENT tracer; yields None (near-zero cost) when no
    tracer is active — library code (k8s client, apply layer, workload
    checks) instruments unconditionally and only pays when a reconcile
    pass or an activated tracer is on the context."""
    tracer = _current_tracer.get()
    if tracer is None:
        yield None
        return
    with tracer.span(name, kind=kind, **attrs) as sp:
        yield sp
