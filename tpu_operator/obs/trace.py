"""Lightweight span/trace API with contextvar reconcile-id propagation.

Fills the observability role controller-runtime's built-in instrumentation
plays for the reference (per-controller reconcile duration histograms,
controller_runtime_reconcile_* families): every reconcile pass opens a root
span carrying a fresh reconcile id; nested spans (per-operand-state sync,
k8s requests, apply calls, validator phases) inherit it through a
contextvar, so one pass is correlatable across the four controllers, the
apply layer, and the log stream without threading ids by hand.

Cross-PROCESS causality rides a serializable :class:`TraceContext`
(``trace_id``/``span_id``/``reconcile_id``) carried in the
``TPU_TRACEPARENT`` env var: the operator mints one per rollout, stamps it
into the rendered operand/validator pods (state/render_data.py), and every
downstream process — validator components, workload pods, flight recorders,
the agents' push hop — ``Tracer.adopt()``\\ s it, so its spans and samples
join the originating trace instead of starting disconnected ones.

Completed spans feed the duration Histograms on ``OperatorMetrics`` (keyed
by span kind) and completed ROOT spans are serialized into a bounded ring
buffer the Manager serves as JSON at ``/debug/traces``
(``TPU_OPERATOR_MAX_TRACES`` sizes it; traces referenced by live fleet
exemplars or an unresolved SLO breach are pinned against eviction).

Spans are deliberately synchronous context managers: they only stamp
timestamps on enter/exit, so wrapping ``await``-ing code is safe — each
asyncio task carries its own context copy, and set/reset happen within the
owning task.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
import uuid
from collections import deque
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

# Span kinds — each maps to one Histogram family on OperatorMetrics.
KIND_RECONCILE = "reconcile"  # reconcile_duration_seconds{controller}
KIND_STATE = "state"          # state_sync_duration_seconds{state}
KIND_K8S = "k8s"              # k8s_request_duration_seconds{verb}
KIND_APPLY = "apply"          # apply_duration_seconds{kind}
KIND_PHASE = "phase"          # workload_phase_duration_seconds{phase}

DEFAULT_MAX_TRACES = 64
MAX_TRACES_ENV = "TPU_OPERATOR_MAX_TRACES"
# the cross-process trace-context contract (docs/OBSERVABILITY.md "Causal
# tracing & explain"): <trace_id>-<span_id>[-<reconcile_id>], 12-hex ids
TRACEPARENT_ENV = "TPU_TRACEPARENT"

_current_tracer: ContextVar[Optional["Tracer"]] = ContextVar(
    "tpu_operator_tracer", default=None
)
_current_span: ContextVar[Optional["Span"]] = ContextVar(
    "tpu_operator_span", default=None
)

_HEX = set("0123456789abcdef")


def new_reconcile_id() -> str:
    return uuid.uuid4().hex[:12]


def new_span_id() -> str:
    return uuid.uuid4().hex[:12]


def new_trace_id() -> str:
    return uuid.uuid4().hex[:12]


@dataclass(frozen=True)
class TraceContext:
    """The serializable cross-process slice of a span: enough for a child
    process to JOIN the trace (trace id), LINK to its remote parent span,
    and correlate logs (reconcile id)."""

    trace_id: str
    span_id: str = ""
    reconcile_id: str = ""

    def serialize(self) -> str:
        parts = [self.trace_id, self.span_id or "0"]
        if self.reconcile_id:
            parts.append(self.reconcile_id)
        return "-".join(parts)

    @staticmethod
    def parse(value: str) -> Optional["TraceContext"]:
        """None on anything malformed — a corrupt env var must degrade to
        an untraced process, never crash a workload."""
        if not isinstance(value, str) or not value:
            return None
        parts = value.strip().split("-")
        if len(parts) not in (2, 3):
            return None
        trace_id = parts[0]
        if not trace_id or len(trace_id) > 32 or set(trace_id) - _HEX:
            return None
        span_id = parts[1] if parts[1] != "0" else ""
        reconcile_id = parts[2] if len(parts) == 3 else ""
        for part in (span_id, reconcile_id):
            if part and (len(part) > 32 or set(part) - _HEX):
                return None
        return TraceContext(trace_id, span_id, reconcile_id)

    @staticmethod
    def from_env() -> Optional["TraceContext"]:
        return TraceContext.parse(os.environ.get(TRACEPARENT_ENV, ""))


@dataclass
class Span:
    name: str
    kind: str = ""
    attrs: dict = field(default_factory=dict)
    reconcile_id: str = ""
    trace_id: str = ""
    span_id: str = field(default_factory=new_span_id)
    # remote parent span id (set on root spans opened under an adopted
    # TraceContext): the cross-process link /debug/traces readers follow
    remote_parent: str = ""
    parent: Optional["Span"] = field(default=None, repr=False)
    start_ts: float = 0.0  # wall clock, for humans reading /debug/traces
    duration_s: Optional[float] = None
    error: Optional[str] = None
    children: list = field(default_factory=list)
    _t0: float = field(default=0.0, repr=False)

    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id, self.reconcile_id)

    def to_dict(self) -> dict:
        d: dict = {
            "name": self.name,
            "kind": self.kind,
            "reconcile_id": self.reconcile_id,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "start_ts": round(self.start_ts, 6),
            "duration_s": self.duration_s,
        }
        if self.remote_parent:
            d["remote_parent"] = self.remote_parent
        attrs = {k: v for k, v in self.attrs.items() if v not in (None, "")}
        if attrs:
            d["attrs"] = attrs
        if self.error:
            d["error"] = self.error
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


def current_span() -> Optional[Span]:
    return _current_span.get()


def reconcile_id() -> str:
    sp = _current_span.get()
    return sp.reconcile_id if sp is not None else ""


def trace_id() -> str:
    sp = _current_span.get()
    return sp.trace_id if sp is not None else ""


def current_traceparent() -> str:
    """The active span's serialized context, ready for a ``TPU_TRACEPARENT``
    env var / pod annotation; empty when untraced."""
    sp = _current_span.get()
    return sp.context().serialize() if sp is not None else ""


def log_context() -> dict:
    """The correlation fields a log record should carry: the active
    reconcile id plus the nearest enclosing controller and operand state,
    found by walking the span chain upward."""
    out: dict = {}
    sp = _current_span.get()
    while sp is not None:
        if sp.reconcile_id and "reconcile_id" not in out:
            out["reconcile_id"] = sp.reconcile_id
        if sp.kind == KIND_RECONCILE and "controller" not in out:
            out["controller"] = sp.attrs.get("controller", "")
        if sp.kind == KIND_STATE and "state" not in out:
            out["state"] = sp.attrs.get("state", "")
        sp = sp.parent
    return out


class Tracer:
    """Span factory + completed-trace ring buffer.

    One Tracer is shared by the manager and every reconciler so a single
    ``/debug/traces`` endpoint sees all controllers; ``metrics`` (an
    ``OperatorMetrics``) is optional — spans still form traces without it
    (standalone validator / workload processes).
    """

    def __init__(
        self,
        metrics=None,
        max_traces: Optional[int] = None,
        fleet=None,
        pinned: Optional[Callable[[], set]] = None,
    ):
        self.metrics = metrics
        # optional obs.fleet.FleetAggregator sink: completed reconcile root
        # spans become fleet duration samples carrying exemplar span ids,
        # so an SLO breach jumps straight to /debug/traces?reconcile_id=
        self.fleet = fleet
        if max_traces is None:
            try:
                max_traces = max(1, int(os.environ.get(MAX_TRACES_ENV, "")))
            except ValueError:
                max_traces = DEFAULT_MAX_TRACES
        self.max_traces = max_traces
        # zero-arg callable returning the trace/reconcile ids that must
        # survive eviction (live fleet exemplars, unresolved SLO breaches);
        # defaults to the fleet sink's own referenced set when it has one
        self.pinned = pinned
        # explicit pins, keyed so a new holder REPLACES its predecessor
        # (e.g. the clusterpolicy reconciler pins the live rollout trace —
        # every rendered pod's TPU_TRACEPARENT points at it, so it must
        # stay resolvable for the rollout's lifetime, and re-pinning on the
        # next spec change releases the old one)
        self._pins: dict[str, str] = {}
        self.traces: deque = deque()  # newest first; evicted by _evict
        self._lock = threading.Lock()
        # adoption point for cross-process propagation: root spans opened
        # while set JOIN this remote context instead of minting a trace id
        self._adopted: ContextVar[Optional[TraceContext]] = ContextVar(
            "tpu_operator_adopted", default=None
        )

    @contextlib.contextmanager
    def activate(self) -> Iterator["Tracer"]:
        """Make this tracer ambient for the current context, so the
        module-level ``span()`` used by library code (k8s client, apply,
        workload checks) records into it without plumbing."""
        token = _current_tracer.set(self)
        try:
            yield self
        finally:
            _current_tracer.reset(token)

    @contextlib.contextmanager
    def adopt(self, ctx: Optional[TraceContext]) -> Iterator["Tracer"]:
        """Activate this tracer AND join the remote trace context: root
        spans opened inside inherit ``ctx.trace_id`` (and the reconcile id
        when the local span doesn't mint one), with ``ctx.span_id`` recorded
        as their remote parent.  ``None`` degrades to plain activation, so
        call sites pass ``TraceContext.from_env()`` unconditionally."""
        token = self._adopted.set(ctx) if ctx is not None else None
        try:
            with self.activate():
                yield self
        finally:
            if token is not None:
                self._adopted.reset(token)

    @contextlib.contextmanager
    def reconcile(self, controller: str, key: str = "") -> Iterator[Span]:
        """Root span of one reconcile pass; mints the pass's reconcile id."""
        with self.span(
            f"reconcile/{controller}",
            kind=KIND_RECONCILE,
            reconcile_id=new_reconcile_id(),
            controller=controller,
            key=key,
        ) as sp:
            yield sp

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        kind: str = "",
        reconcile_id: Optional[str] = None,
        **attrs,
    ) -> Iterator[Span]:
        parent = _current_span.get()
        adopted = self._adopted.get() if parent is None else None
        rid = reconcile_id or (parent.reconcile_id if parent is not None else "")
        if not rid and adopted is not None:
            rid = adopted.reconcile_id
        if parent is not None:
            tid = parent.trace_id
        elif adopted is not None:
            tid = adopted.trace_id
        else:
            tid = new_trace_id()
        sp = Span(
            name=name,
            kind=kind,
            attrs=attrs,
            reconcile_id=rid,
            trace_id=tid,
            remote_parent=adopted.span_id if adopted is not None else "",
            parent=parent,
            start_ts=time.time(),
            _t0=time.monotonic(),
        )
        if parent is not None:
            parent.children.append(sp)
        span_token = _current_span.set(sp)
        tracer_token = _current_tracer.set(self)
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"[:500]
            raise
        finally:
            sp.duration_s = round(time.monotonic() - sp._t0, 6)
            _current_span.reset(span_token)
            _current_tracer.reset(tracer_token)
            self._observe(sp)
            if parent is None:
                with self._lock:
                    self.traces.appendleft(sp.to_dict())
                    self._evict()

    def pin(self, key: str, trace_id: str) -> None:
        """Pin ``trace_id`` against ring eviction under ``key``; a later
        pin with the same key replaces it (and an empty id releases it)."""
        with self._lock:
            if trace_id:
                self._pins[key] = trace_id
            else:
                self._pins.pop(key, None)

    def _pinned_ids(self) -> set:
        out = set(self._pins.values())
        pinned = self.pinned
        if pinned is None and self.fleet is not None:
            pinned = getattr(self.fleet, "referenced_trace_ids", None)
        if pinned is None:
            return out
        try:
            return out | set(pinned())
        except Exception as e:  # noqa: BLE001 — eviction must never fail a span
            logging.getLogger("tpu_operator.obs.trace").debug(
                "pinned-trace lookup failed: %s", e
            )
            return out

    def _evict(self) -> None:
        """Enforce the ring policy (lock held).  UNPINNED traces obey
        ``max_traces``, oldest dropped first; pinned traces — referenced by
        a live fleet exemplar, an unresolved SLO breach, or an explicit
        pin like the live rollout context — don't count against the cap
        and survive whole (they are being held on behalf of readers whose
        ids must not dangle).  A pathologically large pinned history is
        still bounded: past a hard limit of 4× the cap, the oldest traces
        collapse to tombstones — the id stays joinable, the span tree is
        honestly marked evicted instead of silently vanishing."""
        if len(self.traces) <= self.max_traces:
            return
        pinned_ids = self._pinned_ids()

        def pinned(trace: dict) -> bool:
            return bool(pinned_ids) and not trace.get("evicted") and (
                trace.get("trace_id") in pinned_ids
                or trace.get("reconcile_id") in pinned_ids
            )

        overflow = (
            sum(1 for t in self.traces if not pinned(t)) - self.max_traces
        )
        if overflow > 0:
            kept = []
            for trace in reversed(self.traces):  # oldest → newest
                if overflow > 0 and not pinned(trace):
                    overflow -= 1
                    continue
                kept.append(trace)
            kept.reverse()
            self.traces = deque(kept)
        extra = len(self.traces) - self.max_traces * 4
        idx = len(self.traces) - 1
        while extra > 0 and idx >= 0:
            trace = self.traces[idx]
            if not trace.get("evicted"):
                self.traces[idx] = {
                    "name": trace.get("name", ""),
                    "kind": trace.get("kind", ""),
                    "trace_id": trace.get("trace_id", ""),
                    "reconcile_id": trace.get("reconcile_id", ""),
                    "start_ts": trace.get("start_ts"),
                    "evicted": True,
                }
                extra -= 1
            idx -= 1

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self.traces)

    def _observe(self, sp: Span) -> None:
        if self.fleet is not None:
            self.fleet.observe_span(sp)  # swallows its own failures
        m = self.metrics
        if m is None or sp.duration_s is None:
            return
        try:
            if sp.kind == KIND_RECONCILE:
                m.reconcile_duration.labels(
                    controller=sp.attrs.get("controller", "")
                ).observe(sp.duration_s)
            elif sp.kind == KIND_STATE:
                m.state_sync_duration.labels(
                    state=sp.attrs.get("state", "")
                ).observe(sp.duration_s)
            elif sp.kind == KIND_K8S:
                m.k8s_request_duration.labels(
                    verb=sp.attrs.get("verb", "")
                ).observe(sp.duration_s)
            elif sp.kind == KIND_APPLY:
                m.apply_duration.labels(
                    kind=sp.attrs.get("object_kind", "")
                ).observe(sp.duration_s)
            elif sp.kind == KIND_PHASE:
                m.workload_phase_duration.labels(
                    phase=sp.attrs.get("phase", "")
                ).observe(sp.duration_s)
        except Exception as e:  # noqa: BLE001 — timing is evidence, not control flow
            logging.getLogger("tpu_operator.obs.trace").debug(
                "span metric emission failed: %s", e
            )


@contextlib.contextmanager
def span(name: str, kind: str = "", **attrs) -> Iterator[Optional[Span]]:
    """Span on the AMBIENT tracer; yields None (near-zero cost) when no
    tracer is active — library code (k8s client, apply layer, workload
    checks) instruments unconditionally and only pays when a reconcile
    pass or an activated tracer is on the context."""
    tracer = _current_tracer.get()
    if tracer is None:
        yield None
        return
    with tracer.span(name, kind=kind, **attrs) as sp:
        yield sp
