"""Manifest template renderer.

Reference analogue: ``internal/render/render.go`` — text/template + sprig with
``missingkey=error``, multi-doc YAML split, decode to unstructured.  Here the
template language is Jinja2 with StrictUndefined (the missingkey=error
equivalent) plus the helpers the reference gets from sprig/custom funcs:
``toYaml`` (render.go's "yaml" func), ``indent``/``nindent``, ``default``,
``quote``, ``b64enc``.

Templates live one directory per operand state (assets/<state>/NNNN_kind.yaml),
rendered in sorted filename order so apply order is deterministic
(resource_manager.go:92 sorts the same way).
"""

from __future__ import annotations

import base64
import os
from typing import Any, Optional

import jinja2
import yaml

from tpu_operator.utils import files_with_suffix


def _to_yaml(value: Any, indent: int = 0) -> str:
    dumped = yaml.safe_dump(value, default_flow_style=False, sort_keys=False).rstrip("\n")
    if indent:
        pad = " " * indent
        dumped = "\n".join(pad + line if line else line for line in dumped.splitlines())
    return dumped


def _quote(value: Any) -> str:
    # JSON string quoting is valid YAML and escapes newlines/control chars,
    # matching sprig's quote semantics.
    import json

    return json.dumps(str(value))


def _b64enc(value: str) -> str:
    return base64.b64encode(value.encode()).decode()


class RenderError(Exception):
    pass


class Renderer:
    """Renders one template directory into unstructured objects."""

    def __init__(self, root: str):
        self.root = root
        self.env = jinja2.Environment(
            loader=jinja2.FileSystemLoader(root),
            undefined=jinja2.StrictUndefined,
            trim_blocks=True,
            lstrip_blocks=True,
            keep_trailing_newline=True,
        )
        self.env.filters["toYaml"] = _to_yaml
        self.env.filters["quote"] = _quote
        self.env.filters["b64enc"] = _b64enc

    def render_file(self, relpath: str, data: dict) -> list[dict]:
        try:
            text = self.env.get_template(relpath.replace(os.sep, "/")).render(**data)
        except jinja2.UndefinedError as e:
            raise RenderError(f"{relpath}: missing template variable: {e}") from e
        except jinja2.TemplateError as e:
            raise RenderError(f"{relpath}: {e}") from e
        objs: list[dict] = []
        try:
            for doc in yaml.safe_load_all(text):
                if not doc:
                    continue
                if not isinstance(doc, dict) or "kind" not in doc:
                    raise RenderError(f"{relpath}: rendered doc is not a k8s object")
                objs.append(doc)
        except yaml.YAMLError as e:
            raise RenderError(f"{relpath}: rendered invalid YAML: {e}") from e
        return objs

    def render_dir(self, subdir: str, data: dict) -> list[dict]:
        """Render every template in assets/<subdir>/ in sorted order."""
        dir_path = os.path.join(self.root, subdir)
        if not os.path.isdir(dir_path):
            raise RenderError(f"no such asset dir: {dir_path}")
        out: list[dict] = []
        for path in files_with_suffix(dir_path, ".yaml", ".yml"):
            rel = os.path.relpath(path, self.root)
            out.extend(self.render_file(rel, data))
        return out


_DEFAULT_ASSETS = os.path.join(os.path.dirname(__file__), "..", "assets")


def default_assets_dir() -> str:
    """Asset root: $OPERATOR_ASSETS override, else the in-repo assets/ tree
    (baked into the operator image at /opt/tpu-operator, Dockerfile pattern
    of docker/Dockerfile:84-86)."""
    from tpu_operator import consts

    env = os.environ.get(consts.ASSETS_DIR_ENV)
    if env:
        return env
    return os.path.normpath(_DEFAULT_ASSETS)


def new_renderer(root: Optional[str] = None) -> Renderer:
    return Renderer(root or default_assets_dir())
