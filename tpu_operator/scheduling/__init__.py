"""Pure slice-placement logic for the elastic multi-slice scheduler.

The controller half lives in ``tpu_operator/controllers/slicescheduler.py``;
everything here is side-effect free over plain inputs (node dicts in,
plans out) so placement behaviour is unit-testable without a cluster —
the Placeto lesson applied conservatively: a *scored* placement function
whose inputs and ranking are inspectable, not a learned black box.
"""

from tpu_operator.scheduling.placement import (  # noqa: F401
    Arc,
    Compaction,
    Grant,
    Reclaim,
    Request,
    arcs_from_nodes,
    fragmentation,
    plan_compaction,
    plan_placement,
    plan_reclaim,
    request_from_spec,
    victim_score,
)
