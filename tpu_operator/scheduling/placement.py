"""Scored slice placement over ICI arcs.

The capacity model: an **arc** is the schedulable slice unit the fleet
already exposes through node labels — a multi-host slice's node-pool group
(``controllers/labels.slice_group_key``) or a single host — carrying one
contiguous ICI mesh (its topology label), one accelerator generation, and
an allocation ledger (``consts.SLICE_REQUEST_LABEL`` stamped on members).
Granting always assigns *whole arcs*: an arc is contiguous by
construction, so a single-arc grant is a contiguous-ICI grant, and a
multi-arc (DCN multislice) grant is taken only when no one mesh is big
enough and the request opted in.

Scoring (lower tuple wins), in ranking order:

1. **satisfaction** — distance of the granted chip count from the desired
   topology's (exact fit first; when tied, the larger grant wins: an
   elastic request prefers growing toward ``maxTopology`` over shrinking
   toward ``minTopology``);
2. **waste** — arc chips beyond the grant (best-fit packing: never burn a
   4x4x4 on a 2x2 when a 2x4 is free — this is what keeps fragmentation
   down *before* defrag has to undo it);
3. **tiling** — embeddings that keep the mesh axis-divisible
   (``slices.shape_divides``) beat mere fits;
4. **generation abundance** — place on the generation with the most free
   chips, preserving scarce pools (v5p stays available for requests that
   pin it);
5. arc key, for determinism.

Everything is pure over its inputs; the controller owns reads/writes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from tpu_operator import consts, slices
from tpu_operator.controllers.labels import slice_group_key
from tpu_operator.k8s import nodeinfo
from tpu_operator.utils import deep_get, topology_chips


@dataclass(frozen=True)
class Arc:
    """One schedulable slice unit (a contiguous ICI mesh)."""

    key: str                 # nodepool (multi-host) or node name
    nodes: tuple[str, ...]   # member node names, sorted
    topology: str            # the arc's full ICI mesh ("2x4", "4x4x4")
    generation: str          # GKE accelerator label value
    chips: int
    eligible: bool           # complete + every member healthy/schedulable
    assigned: str            # TPUSliceRequest name bound here ("" = free)
    admin_group: str         # pre-existing multislice group NOT owned by us

    @property
    def free(self) -> bool:
        return self.eligible and not self.assigned


@dataclass(frozen=True)
class Request:
    """A TPUSliceRequestSpec reduced to the numbers placement ranks on."""

    name: str
    topology: str
    desired_chips: int
    min_chips: int
    max_chips: int
    generation: str
    multislice: bool
    max_slices: int
    priority: int
    tier: str = "guaranteed"          # capacity tier (preemption economy)
    park_timeout_seconds: int = 0     # 0 = parked requests wait forever


@dataclass(frozen=True)
class Grant:
    """A placement decision: which arcs, and the shape the job meshes over
    (single-arc grants whose arc is bigger than ``maxTopology`` carve the
    desired box; everything else uses the arcs' own shapes)."""

    arcs: tuple[Arc, ...]
    topology: str        # what TPU_JOB_TOPOLOGY-style consumers should use
    chips: int
    multislice: bool


@dataclass(frozen=True)
class Compaction:
    """Move ``request``'s grant from ``source`` onto the smaller free
    ``target``, freeing the bigger contiguous box."""

    request: str
    source: Arc
    target: Arc
    granted_topology: str
    freed_chips: int


@dataclass(frozen=True)
class Reclaim:
    """Reclaim ``victim``'s arc for a pending guaranteed ``claimant``:
    demote the victim onto ``target`` (checkpoint-reshard down toward its
    elastic minimum) when one fits, else park it (``target`` is None —
    snapshot published, arc released, auto-resume when capacity returns)."""

    claimant: str
    victim: str
    source: Arc                      # the victim's arc, freed for the claimant
    target: Optional[Arc]            # demotion target; None = park
    granted_topology: str            # victim's shape on target ("" when parked)


def request_from_spec(name: str, spec) -> Request:
    """Reduce a TPUSliceRequestSpec; raises ValueError on an incoherent
    elastic range (the controller surfaces it as Unschedulable with the
    message — admission cannot relate two topology fields)."""
    desired = topology_chips(spec.topology)
    min_chips = (
        topology_chips(spec.min_topology) if spec.min_topology else desired
    )
    max_chips = (
        topology_chips(spec.max_topology) if spec.max_topology else desired
    )
    if not min_chips <= desired <= max_chips:
        raise ValueError(
            f"elastic range incoherent: minTopology ({min_chips} chips) <= "
            f"topology ({desired}) <= maxTopology ({max_chips}) must hold"
        )
    return Request(
        name=name,
        topology=spec.topology,
        desired_chips=desired,
        min_chips=min_chips,
        max_chips=max_chips,
        generation=spec.generation,
        multislice=bool(spec.multislice),
        max_slices=max(1, int(spec.max_slices)),
        priority=int(spec.priority),
        tier=str(getattr(spec, "tier", "") or "guaranteed"),
        park_timeout_seconds=max(
            0, int(getattr(spec, "park_timeout_seconds", 0) or 0)
        ),
    )


# ---------------------------------------------------------------------------
# Capacity model.


def _member_healthy(node: dict) -> bool:
    """An arc member the scheduler may count as capacity: schedulable, no
    health-engine verdict, not owned by the upgrade machine.  Mirrors
    ``controllers.migration.node_is_healthy_target`` minus the allocatable
    check — allocation is a *label* grant, and a slice mid-join (plugin
    not advertising yet) is still placeable capacity."""
    if deep_get(node, "spec", "unschedulable"):
        return False
    labels = deep_get(node, "metadata", "labels", default={}) or {}
    if labels.get(consts.TPU_HEALTH_LABEL) == consts.HEALTH_UNHEALTHY:
        return False
    if labels.get(consts.HEALTH_STATE_LABEL, "") not in ("", consts.HEALTH_OK):
        return False
    from tpu_operator.controllers.upgrade import NON_TERMINAL_STATES

    return labels.get(consts.UPGRADE_STATE_LABEL, "") not in NON_TERMINAL_STATES


def arcs_from_nodes(nodes: list[dict]) -> list[Arc]:
    """Group the fleet into arcs.  A multi-host slice is eligible only
    when COMPLETE (members == expected hosts) and every member healthy —
    granting a partial slice would bind a job to a mesh that cannot form."""
    groups: dict[str, list[dict]] = {}
    for node in nodes:
        attrs = nodeinfo.attributes(node)
        if not attrs.accelerator or not attrs.topology:
            continue
        key = slice_group_key(node) or node["metadata"]["name"]
        groups.setdefault(key, []).append(node)

    arcs: list[Arc] = []
    for key, members in sorted(groups.items()):
        names = tuple(sorted(m["metadata"]["name"] for m in members))
        first = members[0]
        labels = deep_get(first, "metadata", "labels", default={}) or {}
        topology = labels.get(consts.GKE_TPU_TOPOLOGY_LABEL, "")
        try:
            chips = topology_chips(topology)
        except ValueError:
            continue
        expected = max(nodeinfo.slice_hosts(m) for m in members)
        eligible = len(members) >= max(1, expected) and all(
            _member_healthy(m) for m in members
        )
        assigned = ""
        admin_group = ""
        for m in members:
            m_labels = deep_get(m, "metadata", "labels", default={}) or {}
            assigned = assigned or m_labels.get(consts.SLICE_REQUEST_LABEL, "")
            group = m_labels.get(consts.MULTISLICE_GROUP_LABEL, "")
            if group and group != assigned:
                admin_group = group
        generation = labels.get(consts.GKE_TPU_ACCELERATOR_LABEL, "")
        arcs.append(Arc(
            key=key, nodes=names, topology=topology, generation=generation,
            chips=chips, eligible=eligible, assigned=assigned,
            admin_group=admin_group,
        ))
    return arcs


def fragmentation(arcs: list[Arc]) -> float:
    """1 - largest_free_arc / total_free chips over eligible free arcs: 0
    when one contiguous box holds everything still free (or nothing is),
    approaching 1 as free capacity scatters into small meshes."""
    free = [a.chips for a in arcs if a.free]
    total = sum(free)
    if total <= 0:
        return 0.0
    return round(1.0 - max(free) / total, 4)


# ---------------------------------------------------------------------------
# Placement.


def _single_grant_topology(request: Request, arc: Arc) -> Optional[str]:
    """The shape ``request`` would mesh over on ``arc`` alone, or None
    when the arc cannot satisfy even the elastic minimum.  Whole-arc
    grants take the arc's own shape (trivially contiguous; elastic jobs
    reshard to it); an arc bigger than ``maxTopology`` carves the desired
    box instead — contiguity then requires the embedding to exist."""
    if arc.chips < request.min_chips:
        return None
    if arc.chips <= request.max_chips:
        return arc.topology
    if slices.shape_fits(request.topology, arc.topology):
        return request.topology
    return None


def _gen_free_chips(arcs: list[Arc]) -> dict[str, int]:
    out: dict[str, int] = {}
    for a in arcs:
        if a.free:
            out[a.generation] = out.get(a.generation, 0) + a.chips
    return out


def _score(request: Request, arc: Arc, granted: str, gen_free: dict[str, int]) -> tuple:
    granted_chips = topology_chips(granted)
    return (
        abs(granted_chips - request.desired_chips),
        -granted_chips,                      # ties: grow beats shrink
        arc.chips - granted_chips,           # best-fit: minimal stranded chips
        0 if slices.shape_divides(granted, arc.topology) else 1,
        -gen_free.get(arc.generation, 0),    # abundant generation first
        arc.key,
    )


def plan_placement(request: Request, arcs: list[Arc]) -> Optional[Grant]:
    """Best grant for ``request`` over the current capacity, or None."""
    free = [a for a in arcs if a.free]
    if request.generation:
        free = [a for a in free if a.generation == request.generation]
    gen_free = _gen_free_chips(arcs)

    best: Optional[tuple[tuple, Arc, str]] = None
    for arc in free:
        granted = _single_grant_topology(request, arc)
        if granted is None:
            continue
        score = _score(request, arc, granted, gen_free)
        if best is None or score < best[0]:
            best = (score, arc, granted)
    single: Optional[Grant] = None
    if best is not None:
        _, arc, granted = best
        single = Grant(
            arcs=(arc,), topology=granted,
            chips=topology_chips(granted), multislice=False,
        )
    if not request.multislice:
        return single
    split = _plan_multislice(request, free)
    # an elastic minimum can make a lone small arc "satisfy" a request a
    # DCN split would serve far better — pick whichever lands closer to
    # the desired chips, single-mesh winning ties (ICI beats DCN)
    if single is None:
        return split
    if split is not None and (
        abs(split.chips - request.desired_chips)
        < abs(single.chips - request.desired_chips)
    ):
        return split
    return single


def _plan_multislice(request: Request, free: list[Arc]) -> Optional[Grant]:
    """DCN-split grant: same-generation arcs (a mixed-generation data-
    parallel mesh steps at the slowest member's pace), largest-first so
    the slice count stays minimal, arcs already claimed by an admin
    multislice group excluded (we must not overwrite their rendezvous
    labels).  Aims for the desired chip count, accepts the elastic
    minimum, never exceeds ``maxSlices`` arcs or ``maxTopology`` chips."""
    by_gen: dict[str, list[Arc]] = {}
    for a in free:
        if a.admin_group:
            continue
        by_gen.setdefault(a.generation, []).append(a)

    best: Optional[Grant] = None
    for gen in sorted(by_gen):
        candidates = sorted(by_gen[gen], key=lambda a: (-a.chips, a.key))
        chosen: list[Arc] = []
        total = 0
        for a in candidates:
            if len(chosen) >= request.max_slices or total >= request.desired_chips:
                break
            if total + a.chips > request.max_chips:
                continue
            chosen.append(a)
            total += a.chips
        # a single arc is not "multislice" — the single-arc pass already
        # rejected every one of these, so the split needs at least two
        if len(chosen) < 2 or total < request.min_chips:
            continue
        grant = Grant(
            arcs=tuple(chosen),
            topology="+".join(a.topology for a in chosen),
            chips=total,
            multislice=True,
        )
        if (
            best is None
            or abs(grant.chips - request.desired_chips)
            < abs(best.chips - request.desired_chips)
            or (
                abs(grant.chips - request.desired_chips)
                == abs(best.chips - request.desired_chips)
                and len(grant.arcs) < len(best.arcs)
            )
        ):
            best = grant
    return best


# ---------------------------------------------------------------------------
# Defragmentation.


def plan_compaction(
    arcs: list[Arc],
    bound: dict[str, Request],
    threshold: float,
    exclude: Optional[set[str]] = None,
) -> Optional[Compaction]:
    """The single most productive compaction move, or None.

    Armed only when :func:`fragmentation` exceeds ``threshold``.  A move
    relocates one single-arc grant onto a strictly smaller free arc that
    still grants AT LEAST its desired chips — defrag trims over-provision
    (an elastic grant sprawled past its desired shape), it never demotes
    a grant below what it asked for just for tidiness: that asymmetry is
    what keeps compaction and the elastic grow path (which only fires
    below desired) from endlessly reversing each other, and
    demand-driven demotion is the preemption economy's job (ROADMAP).
    A qualifying move must strictly GROW the largest free contiguous box
    — the property a pending too-big request is waiting on.  Multi-arc
    (multislice) grants are never compacted: their capacity is already
    split, and moving one leg cannot grow any contiguous box.
    ``exclude`` names requests the caller has vetoed (e.g. a
    non-migratable workload pod on the grant)."""
    if fragmentation(arcs) <= threshold:
        return None
    free = [a for a in arcs if a.free]
    if not free:
        return None
    largest_free = max(a.chips for a in free)

    # single-arc grants only: arcs assigned to a request that owns exactly
    # one arc (a multislice grant shows the same name on several)
    owned: dict[str, list[Arc]] = {}
    for a in arcs:
        if a.assigned:
            owned.setdefault(a.assigned, []).append(a)

    best: Optional[Compaction] = None
    for name, held in sorted(owned.items()):
        request = bound.get(name)
        if request is None or len(held) != 1 or name in (exclude or ()):
            continue
        source = held[0]
        if not source.eligible or source.chips <= largest_free:
            # freeing it would not beat the box we already have
            continue
        for target in sorted(free, key=lambda a: (a.chips, a.key)):
            if target.chips >= source.chips:
                break  # sorted ascending: nothing smaller remains
            if request.generation and target.generation != request.generation:
                continue
            granted = _single_grant_topology(request, target)
            if granted is None:
                continue
            if topology_chips(granted) < request.desired_chips:
                continue  # never demote below desired for tidiness
            move = Compaction(
                request=name, source=source, target=target,
                granted_topology=granted, freed_chips=source.chips,
            )
            if best is None or (
                (-move.freed_chips, move.target.chips, move.request)
                < (-best.freed_chips, best.target.chips, best.request)
            ):
                best = move
            break  # smallest fitting target for THIS grant found
    return best


# ---------------------------------------------------------------------------
# Preemption economy (reclaim-by-demotion; docs/SCHEDULING.md).


def victim_score(
    victim: Request, source: Arc, claimant: Request, at_risk: dict
) -> tuple:
    """Rank one reclaim candidate (lower wins): lowest ``priority``
    first, then the least chip-seconds of useful work at risk per the
    ledger, then the tightest freed-surplus fit (chips the claimant
    would strand on the freed arc), then the victim name for
    determinism."""
    granted = _single_grant_topology(claimant, source)
    surplus = source.chips - (topology_chips(granted) if granted else 0)
    return (
        victim.priority,
        round(float(at_risk.get(victim.name, 0.0)), 6),
        surplus,
        victim.name,
    )


def plan_reclaim(
    claimant: Request,
    arcs: list[Arc],
    bound: dict[str, Request],
    at_risk: Optional[dict] = None,
    exclude: Optional[set] = None,
) -> Optional[Reclaim]:
    """The reclaim move that lands a Pending **guaranteed** ``claimant``
    on capacity currently bound to a reclaimable grant, or None.

    Victim selection is pure and scored (:func:`victim_score`).  The
    chosen victim is demoted onto whatever smaller/fragmented free
    capacity still satisfies its elastic ``minTopology``; when nothing
    fits it parks (``target`` is None) — demote-or-park, never kill.
    ``exclude`` names grants the caller has vetoed (a non-migratable
    workload pod on the grant) or that are already mid-move."""
    if claimant.tier != "guaranteed":
        return None
    at_risk = at_risk or {}
    owned: dict[str, list[Arc]] = {}
    for a in arcs:
        if a.assigned:
            owned.setdefault(a.assigned, []).append(a)

    best: Optional[tuple[tuple, Request, Arc]] = None
    for name, held in sorted(owned.items()):
        victim = bound.get(name)
        if victim is None or victim.tier != "reclaimable":
            continue
        if name in (exclude or ()) or len(held) != 1:
            continue
        source = held[0]
        if not source.eligible or source.admin_group:
            continue
        if claimant.generation and source.generation != claimant.generation:
            continue
        if _single_grant_topology(claimant, source) is None:
            continue  # freeing this arc still would not fit the claimant
        score = victim_score(victim, source, claimant, at_risk)
        if best is None or score < best[0]:
            best = (score, victim, source)
    if best is None:
        return None
    _, victim, source = best

    # demotion target: the best free arc that still satisfies the
    # victim's elastic range — the claimant takes the source, so the
    # source is NOT free for the victim.  One contiguous mesh only: a
    # demotion reshard is a single-arc restore, never a DCN split.
    free_view = [a for a in arcs if a.free and a.key != source.key]
    grant = plan_placement(victim, free_view)
    if grant is not None and (grant.multislice or len(grant.arcs) != 1):
        grant = None
    return Reclaim(
        claimant=claimant.name,
        victim=victim.name,
        source=source,
        target=grant.arcs[0] if grant is not None else None,
        granted_topology=grant.topology if grant is not None else "",
    )
