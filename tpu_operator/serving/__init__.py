"""The serving front door (docs/SERVING.md "Front door").

One logical endpoint over the PR-15 replica fleet: session-affine
admission-aware routing off the pushed ``/debug/fleet`` capacity rollups,
honest 429 shedding, a per-session retry budget with a single
idempotent-prefill hedge, draining-replica handoff that follows the
migration checkpoint, and SLO-burn-driven autoscaling through elastic
``TPUSliceRequest`` grants.
"""

from tpu_operator.serving.autoscaler import AutoscaleConfig, ReplicaAutoscaler
from tpu_operator.serving.frontdoor import (
    FrontDoor,
    FrontDoorConfig,
    SessionTraffic,
    build_app,
)
from tpu_operator.serving.replicas import LocalReplica, ReplicaGone, TokenEvent

__all__ = [
    "AutoscaleConfig",
    "FrontDoor",
    "FrontDoorConfig",
    "LocalReplica",
    "ReplicaAutoscaler",
    "ReplicaGone",
    "SessionTraffic",
    "TokenEvent",
    "build_app",
]
