"""Burn-driven replica autoscaling: the loop-closer.

The PR-6 SLO engine already watches the pushed serving rollups and says
WHEN the fleet is burning (`breached_slos()` on p99 TPOT burn rate);
the front door already knows HOW LOADED each replica is (its routed
queue depths).  :class:`ReplicaAutoscaler` folds both into one desired
replica count with hysteresis — sustained burn or sustained queue
pressure grows the fleet, sustained idleness shrinks it, and a cooldown
keeps a restore/scale-up from immediately triggering the next verdict
off its own transient.

The desired count is actuated by ``controllers/servescaler.py`` as
elastic ``TPUSliceRequest`` objects (guaranteed floor + reclaimable
burst — PR-14 min/max grants, PR-18 preemption economy), NOT by this
class: observe() is pure control law, deterministic from its inputs,
and is unit-tested that way.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class AutoscaleConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    # how long pressure must be sustained before acting (transient spikes
    # and single stale pushes must not thrash the fleet)
    up_after_s: float = 2.0
    down_after_s: float = 8.0
    # minimum spacing between scaling verdicts in either direction
    cooldown_s: float = 4.0
    # mean routed queue depth at/below which the fleet is idle, and
    # at/above which it is busy even without an SLO burn
    idle_queue_depth: float = 0.5
    busy_queue_depth: float = 6.0


class ReplicaAutoscaler:
    """Deterministic control law: feed it (now, ready, mean queue depth,
    burning?) each evaluation tick; it returns the desired replica count.

    ``burning`` is the caller's reading of the SLO engine —
    ``bool(fleet.slo_engine.breached_slos())`` filtered to the serving
    SLOs — so this class stays import-light and trivially testable.
    """

    def __init__(self, cfg: Optional[AutoscaleConfig] = None):
        self.cfg = cfg or AutoscaleConfig()
        self.desired = self.cfg.min_replicas
        self._busy_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_change: float = float("-inf")

    def observe(
        self,
        now: float,
        ready: int,
        queue_depth_mean: float,
        burning: bool,
    ) -> int:
        cfg = self.cfg
        busy = burning or queue_depth_mean >= cfg.busy_queue_depth
        idle = (
            not burning
            and queue_depth_mean <= cfg.idle_queue_depth
            # never call an under-provisioned fleet idle: grants still
            # materialising must not be shrunk out from under the ramp
            and ready >= self.desired
        )
        if busy:
            self._idle_since = None
            if self._busy_since is None:
                self._busy_since = now
        elif idle:
            self._busy_since = None
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._busy_since = None
            self._idle_since = None
        in_cooldown = now - self._last_change < cfg.cooldown_s
        if (
            self._busy_since is not None
            and now - self._busy_since >= cfg.up_after_s
            and not in_cooldown
            and self.desired < cfg.max_replicas
        ):
            self.desired += 1
            self._last_change = now
            self._busy_since = now  # a further step needs fresh sustain
        elif (
            self._idle_since is not None
            and now - self._idle_since >= cfg.down_after_s
            and not in_cooldown
            and self.desired > cfg.min_replicas
        ):
            self.desired -= 1
            self._last_change = now
            self._idle_since = now
        return self.desired
