"""Serving front door: one fault-tolerant endpoint over the replica fleet.

The router tier ROADMAP item 2 asks for.  Clients see ONE logical
endpoint; behind it a session-affine request stream is load-balanced
across the PR-15 replica fleet entirely off the capacity evidence the
fleet plane already carries — the pushed ``serving_kv_blocks_free`` /
queue-depth rollups on ``/debug/fleet`` (``FleetAggregator.serving_view``),
freshness-stamped so stale evidence means "replica unknown", never
"replica fine".

The contracts, in routing order:

- **Affinity**: a session sticks to its bound replica while that replica
  is fresh and under the admission ceiling (KV reuse, ordered streams).
  New sessions spill onto the least-loaded fresh replica.
- **Admission / shed**: when no fresh replica has queue headroom the
  request is shed with an honest 429 + ``Retry-After`` — BEFORE a
  replica queue blows its latency SLO, and never a silent drop.  Sheds
  are counted apart from failures; the serve-fleet soak gates failures
  at zero while sheds are allowed to breathe.
- **Retry budget**: each session carries a replica-loss budget.  A dead
  replica (SIGKILL, or capacity evidence stale past the dead bound — the
  blackhole detector) costs one budget unit to re-place each of the
  session's in-flight requests; an exhausted budget fails the request
  honestly.  Token positions already delivered are deduped, so a retry
  re-decodes but never re-bills.
- **Single hedge, prefill only**: a request whose FIRST token is overdue
  gets at most one hedge onto a second replica.  Prefill is idempotent —
  nothing was delivered, nothing double-bills; the first source to
  deliver wins and the loser is cancelled before it can decode on the
  client's bill.  A request that has started decoding never hedges.
- **Drain handoff**: when ``MigrationCoordinator.drain_pod`` checkpoints
  a replica, the router parks that replica's sessions (new arrivals wait
  at the router — latency, not errors), follows the checkpoint to the
  restored replica, and replays exactly the in-flight requests the
  snapshot does NOT contain, in arrival order.  Rids inside the
  snapshot's schedule are never resubmitted; rids outside it are never
  skipped.

Autoscaling closes the loop in ``serving/autoscaler.py`` (burn-driven
desired count) and ``controllers/servescaler.py`` (elastic
``TPUSliceRequest`` reconciliation).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from tpu_operator import consts
from tpu_operator.serving.replicas import LocalReplica, ReplicaGone, TokenEvent
from tpu_operator.workloads.serving import PoissonTraffic, Request, _percentile

# replica states as the router sees them (frontdoor_replicas gauge)
READY = "ready"
DRAINING = "draining"
PARKED = "parked"
UNKNOWN = "unknown"
DEAD = "dead"
REPLICA_STATES = (READY, DRAINING, PARKED, UNKNOWN, DEAD)

# submit() verdicts
ACCEPTED = "accepted"
SHED = "shed"

# routed outcomes
ROUTE_STICKY = "sticky"
ROUTE_SPILLOVER = "spillover"
ROUTE_RETRY = "retry"
ROUTE_REPLAY = "replay"


@dataclass
class FrontDoorConfig:
    # replica-loss retries one session may spend before failing honestly
    retry_budget: int = consts.FRONTDOOR_RETRY_BUDGET
    # first-token deadline before the single idempotent-prefill hedge
    hedge_after_s: float = consts.FRONTDOOR_HEDGE_AFTER_SECONDS
    # capacity evidence older than this = replica UNKNOWN (route away)
    stale_after_s: float = (
        consts.FRONTDOOR_STALE_PUSHES * consts.SERVE_PUSH_INTERVAL_SECONDS
    )
    # UNKNOWN replica still holding in-flight work is declared DEAD after
    # this long without a push (the blackhole detector)
    dead_after_s: float = consts.FRONTDOOR_DEAD_AFTER_SECONDS
    # per-replica admission ceiling: a routed queue depth at/above this
    # sheds instead (set from the replica's SLO headroom, not its limits)
    shed_queue_depth: float = 12.0
    # Retry-After floor/ceiling on sheds
    retry_after_min_s: float = 0.25
    retry_after_max_s: float = 5.0
    # estimated per-replica request drain rate backing the Retry-After
    # hint (requests/s a healthy replica retires)
    drain_rate_rps: float = 8.0


@dataclass
class _Replica:
    name: str
    handle: LocalReplica
    node: str = ""
    state: str = READY
    # newest pushed capacity evidence (the ONLY routing input besides
    # liveness — the router never peeks into a handle's engine)
    evidence_ts: float = 0.0
    queue_depth: float = 0.0
    kv_blocks_free: float = 0.0
    retiring: bool = False
    ckpt_dir: str = ""
    # rids the drain checkpoint carried (set while PARKED)
    schedule: list = field(default_factory=list)


@dataclass
class _Session:
    sid: str
    replica: Optional[str] = None
    retry_budget: int = 0


@dataclass
class _Track:
    """One client request's lifetime at the endpoint."""

    rid: str
    sid: str
    prompt: list
    max_new_tokens: int
    submitted_at: float
    primary: Optional[str] = None     # replica currently decoding it
    hedge: Optional[str] = None       # second replica while a hedge races
    hedged: bool = False              # single-hedge-ever latch
    pending: bool = False             # parked at the router (drain handoff)
    delivered: int = 0                # generated positions billed so far
    tokens: list = field(default_factory=list)
    first_token_at: Optional[float] = None
    last_token_at: Optional[float] = None
    done: bool = False

    @property
    def decoding(self) -> bool:
        return self.delivered > 0


class FrontDoor:
    """The router.  All public methods are thread-safe behind one lock —
    the soak drives ticks from the bench loop while the migration mirror
    drains from the asyncio side.  Time is always an explicit ``now``
    (the repo's deterministic-clock idiom); nothing in here sleeps."""

    def __init__(
        self,
        cfg: Optional[FrontDoorConfig] = None,
        metrics=None,
    ):
        self.cfg = cfg or FrontDoorConfig()
        self.metrics = metrics
        self._lock = threading.RLock()
        self._replicas: dict[str, _Replica] = {}
        self._sessions: dict[str, _Session] = {}
        self._tracks: dict[str, _Track] = {}
        self._completed: dict[str, _Track] = {}
        self._failed: list[str] = []
        # rids awaiting a replica (parked handoff or retry with no
        # capacity), in arrival order — the replay schedule
        self._waiting: list[str] = []
        self._next_rid = 0
        self.counts: dict[str, int] = {
            "routed": 0, "shed": 0, "failed": 0, "completed": 0,
            "retries": 0, "hedges_fired": 0, "hedges_won": 0,
            "hedges_wasted": 0, "handoff_parked": 0, "handoff_restored": 0,
            "handoff_replayed": 0, "tokens_billed": 0, "dup_tokens": 0,
        }
        self._ttft: list[float] = []
        self._tpot: list[float] = []

    # ------------------------------------------------------------------
    # Fleet membership.

    def add_replica(
        self,
        name: str,
        handle: LocalReplica,
        node: str = "",
        now: Optional[float] = None,
        ckpt_dir: str = "",
    ) -> None:
        now = time.time() if now is None else now
        with self._lock:
            # a fresh replica has not pushed yet: grant it one staleness
            # window of benefit of the doubt before UNKNOWN kicks in
            self._replicas[name] = _Replica(
                name=name, handle=handle, node=node,
                evidence_ts=now, ckpt_dir=ckpt_dir,
                kv_blocks_free=float(handle.cfg.num_blocks),
            )

    def retire_replica(self, name: str) -> None:
        """Graceful scale-down: stop routing new work; the replica leaves
        once its in-flight work completes (checked each tick)."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is not None:
                rep.retiring = True

    def replica_states(self) -> dict[str, str]:
        with self._lock:
            return {name: rep.state for name, rep in self._replicas.items()}

    def ready_count(self) -> int:
        with self._lock:
            return sum(
                1 for rep in self._replicas.values()
                if rep.state == READY and not rep.retiring
            )

    # ------------------------------------------------------------------
    # Capacity evidence (satellite: freshness-stamped serving_view).

    def observe_fleet(self, view: dict, now: Optional[float] = None) -> None:
        """Ingest ``FleetAggregator.serving_view()`` (or the ``serving``
        key of ``/debug/fleet``): newest per-replica capacity + freshness.
        Stale evidence does NOT update the routing numbers — it ages the
        replica toward UNKNOWN instead."""
        now = time.time() if now is None else now
        with self._lock:
            for name, entry in (view or {}).items():
                rep = self._replicas.get(name)
                if rep is None:
                    continue
                ts = float(entry.get("ts") or 0.0)
                if ts <= rep.evidence_ts and not entry.get("fresh", True):
                    continue
                rep.evidence_ts = max(rep.evidence_ts, ts)
                metrics = entry.get("metrics") or {}
                if "queue_depth" in metrics:
                    rep.queue_depth = float(metrics["queue_depth"])
                if "kv_blocks_free" in metrics:
                    rep.kv_blocks_free = float(metrics["kv_blocks_free"])
            self._refresh_states(now)

    def _refresh_states(self, now: float) -> None:
        for rep in self._replicas.values():
            if rep.state in (DRAINING, PARKED, DEAD):
                continue
            if not rep.handle.alive:
                continue  # tick's dead-scan owns the DEAD transition
            age = now - rep.evidence_ts
            if age > self.cfg.stale_after_s:
                rep.state = UNKNOWN
            else:
                rep.state = READY

    def _eligible(self, now: float, exclude: tuple = ()) -> list[_Replica]:
        """Fresh, live, non-retiring replicas — the only routing targets.
        UNKNOWN is excluded by construction: stale evidence must mean
        'route away', not 'assume the last numbers still hold'."""
        out = []
        for rep in self._replicas.values():
            if rep.name in exclude or rep.retiring:
                continue
            if rep.state != READY or not rep.handle.alive:
                continue
            if now - rep.evidence_ts > self.cfg.stale_after_s:
                continue
            out.append(rep)
        return out

    # ------------------------------------------------------------------
    # The endpoint.

    def submit(
        self,
        sid: str,
        prompt: list,
        max_new_tokens: int,
        now: Optional[float] = None,
        rid: Optional[str] = None,
    ) -> dict:
        """Route one request.  Returns ``{"status": "accepted", "rid"}``
        or ``{"status": "shed", "retry_after_s"}`` — never an exception,
        never a silent drop."""
        now = time.time() if now is None else now
        with self._lock:
            session = self._sessions.get(sid)
            if session is None:
                session = self._sessions[sid] = _Session(
                    sid=sid, retry_budget=self.cfg.retry_budget
                )
            if rid is None:
                rid = f"rid-{self._next_rid}"
                self._next_rid += 1
            track = _Track(
                rid=rid, sid=sid, prompt=list(prompt),
                max_new_tokens=int(max_new_tokens), submitted_at=now,
            )
            # a session whose replica is mid-handoff parks new arrivals at
            # the router: the client sees latency, not an error
            bound = (
                self._replicas.get(session.replica)
                if session.replica else None
            )
            if bound is not None and bound.state in (DRAINING, PARKED):
                track.pending = True
                track.primary = bound.name
                self._tracks[rid] = track
                self._waiting.append(rid)
                return {"status": ACCEPTED, "rid": rid, "parked": True}
            target, outcome = self._pick(session, now)
            if target is None:
                retry_after = self._retry_after(now)
                self.counts["shed"] += 1
                if self.metrics is not None:
                    self.metrics.frontdoor_shed_total.inc()
                return {"status": SHED, "retry_after_s": retry_after}
            self._place(track, target, now, outcome)
            session.replica = target.name
            self._tracks[rid] = track
            return {"status": ACCEPTED, "rid": rid}

    def _pick(
        self, session: _Session, now: float, exclude: tuple = ()
    ) -> tuple[Optional[_Replica], str]:
        eligible = self._eligible(now, exclude=exclude)
        under = [
            r for r in eligible if r.queue_depth < self.cfg.shed_queue_depth
        ]
        if not under:
            return None, ""
        bound = session.replica
        for rep in under:
            if rep.name == bound:
                return rep, ROUTE_STICKY
        # spillover: emptiest queue first, most free KV as the tiebreak
        under.sort(key=lambda r: (r.queue_depth, -r.kv_blocks_free, r.name))
        return under[0], ROUTE_SPILLOVER

    def _place(
        self, track: _Track, rep: _Replica, now: float, outcome: str
    ) -> None:
        req = Request(
            rid=track.rid, prompt=list(track.prompt),
            max_new_tokens=track.max_new_tokens, arrival=now,
        )
        rep.handle.submit(req)
        track.primary = rep.name
        track.pending = False
        # optimistic local bump so a burst between pushes spreads out
        # instead of piling onto the replica whose evidence looked emptiest
        rep.queue_depth += 1.0
        self.counts["routed"] += 1
        if self.metrics is not None:
            self.metrics.frontdoor_routed_total.labels(outcome=outcome).inc()

    def _retry_after(self, now: float) -> float:
        """An honest hint: how long until the least-backed-up replica
        drains back under the admission ceiling."""
        depths = [
            rep.queue_depth for rep in self._replicas.values()
            if rep.state == READY and rep.handle.alive
        ]
        if not depths:
            return self.cfg.retry_after_max_s
        over = max(0.0, min(depths) - self.cfg.shed_queue_depth + 1.0)
        est = over / max(self.cfg.drain_rate_rps, 1e-6)
        return round(
            min(
                max(est, self.cfg.retry_after_min_s),
                self.cfg.retry_after_max_s,
            ), 3,
        )

    # ------------------------------------------------------------------
    # The tick: step local replicas, collect tokens, hedge, detect loss.

    def tick(self, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        with self._lock:
            self._refresh_states(now)
            for rep in list(self._replicas.values()):
                if rep.state in (PARKED, DEAD):
                    continue
                rep.handle.step(now)
                events, _finished = rep.handle.poll(now)
                self._on_tokens(rep, events, now)
            self._hedge_scan(now)
            self._dead_scan(now)
            self._drain_waiting(now)
            self._reap_retired()
            if self.metrics is not None:
                self._export()
            return {
                "now": now,
                "live_tracks": len(self._tracks),
                "waiting": len(self._waiting),
                "ready": self.ready_count(),
            }

    def _on_tokens(
        self, rep: _Replica, events: list[TokenEvent], now: float
    ) -> None:
        for ev in events:
            track = self._tracks.get(ev.rid)
            if track is None or track.done:
                continue  # cancelled or already completed elsewhere
            if rep.name not in (track.primary, track.hedge):
                continue  # a detached loser still flushing
            if ev.position < track.delivered:
                # an already-billed position (hedge loser, retry replay,
                # post-restore overlap): discarded, never re-billed
                self.counts["dup_tokens"] += 1
                if self.metrics is not None:
                    self.metrics.frontdoor_dup_tokens_total.inc()
                continue
            if track.hedge is not None:
                # first delivery settles the race: the other source is
                # cancelled while the request is still on ITS prefill —
                # decode only ever runs (and bills) on the winner
                winner, loser = (
                    (track.primary, track.hedge)
                    if rep.name == track.primary
                    else (track.hedge, track.primary)
                )
                self._cancel_on(loser, track.rid)
                won = winner == track.hedge
                self.counts["hedges_won" if won else "hedges_wasted"] += 1
                if self.metrics is not None:
                    self.metrics.frontdoor_hedges_total.labels(
                        outcome="won" if won else "wasted"
                    ).inc()
                track.primary = winner
                track.hedge = None
                self._sessions[track.sid].replica = winner
            track.delivered += 1
            track.tokens.append(ev.token)
            self.counts["tokens_billed"] += 1
            if self.metrics is not None:
                self.metrics.frontdoor_tokens_billed_total.inc()
            if track.first_token_at is None:
                track.first_token_at = ev.ts
                ttft = ev.ts - track.submitted_at
                self._ttft.append(ttft)
                if self.metrics is not None:
                    self.metrics.frontdoor_ttft_seconds.observe(max(ttft, 0.0))
            else:
                tpot = ev.ts - track.last_token_at
                self._tpot.append(tpot)
                if self.metrics is not None:
                    self.metrics.frontdoor_tpot_seconds.observe(max(tpot, 0.0))
            track.last_token_at = ev.ts
            if track.delivered >= track.max_new_tokens:
                self._complete(track)

    def _cancel_on(self, name: Optional[str], rid: str) -> None:
        rep = self._replicas.get(name or "")
        if rep is not None and rep.handle.alive:
            rep.handle.cancel(rid)

    def _complete(self, track: _Track) -> None:
        track.done = True
        self._tracks.pop(track.rid, None)
        self._completed[track.rid] = track
        self.counts["completed"] += 1

    def _fail(self, track: _Track) -> None:
        track.done = True
        self._tracks.pop(track.rid, None)
        self._failed.append(track.rid)
        self.counts["failed"] += 1
        if self.metrics is not None:
            self.metrics.frontdoor_failed_total.inc()

    def _hedge_scan(self, now: float) -> None:
        for track in list(self._tracks.values()):
            if (
                track.done or track.pending or track.hedged
                or track.decoding
                or now - track.submitted_at < self.cfg.hedge_after_s
            ):
                continue
            target, _ = self._pick(
                self._sessions[track.sid], now,
                exclude=(track.primary or "",),
            )
            track.hedged = True  # one attempt ever, placed or not
            if target is None:
                continue
            req = Request(
                rid=track.rid, prompt=list(track.prompt),
                max_new_tokens=track.max_new_tokens, arrival=now,
            )
            try:
                target.handle.submit(req)
            except ReplicaGone:
                continue
            track.hedge = target.name
            target.queue_depth += 1.0
            self.counts["hedges_fired"] += 1
            if self.metrics is not None:
                self.metrics.frontdoor_hedges_total.labels(
                    outcome="fired"
                ).inc()

    def _dead_scan(self, now: float) -> None:
        for rep in list(self._replicas.values()):
            if rep.state in (PARKED, DEAD, DRAINING):
                continue
            evidence_age = now - rep.evidence_ts
            if not rep.handle.alive:
                rep.state = DEAD
            elif evidence_age > self.cfg.dead_after_s and self._has_work(rep):
                # a blackhole: accepting connections, pushing nothing —
                # only the freshness trail convicts it
                rep.state = DEAD
            else:
                continue
            self._evacuate(rep, now)

    def _has_work(self, rep: _Replica) -> bool:
        return any(
            rep.name in (t.primary, t.hedge)
            for t in self._tracks.values()
            if not t.pending
        )

    def _evacuate(self, rep: _Replica, now: float) -> None:
        """Re-place every in-flight request of a DEAD replica.  A live
        hedge partner absorbs the loss for free — the race just lost a
        contender.  Everything else charges the session's retry budget
        ONCE per loss event: a session's requests all rode the same
        replica (that is what affinity means), so one crash is one
        strike, however many requests were in flight."""
        orphans: dict[str, list[_Track]] = {}
        for track in list(self._tracks.values()):
            if track.done or track.pending:
                continue
            if rep.name not in (track.primary, track.hedge):
                continue
            survivor = (
                track.hedge if track.primary == rep.name else track.primary
            )
            if track.hedge is not None and survivor is not None:
                other = self._replicas.get(survivor)
                if other is not None and other.handle.alive:
                    track.primary = survivor
                    track.hedge = None
                    self._sessions[track.sid].replica = survivor
                    continue
                track.hedge = None
            orphans.setdefault(track.sid, []).append(track)
        for sid, tracks in orphans.items():
            session = self._sessions[sid]
            if session.retry_budget <= 0:
                for track in tracks:
                    self._fail(track)
                continue
            session.retry_budget -= 1
            self.counts["retries"] += 1
            for track in tracks:
                self._reroute(track, session, now, lost=rep.name)

    def _reroute(
        self, track: _Track, session: _Session, now: float, lost: str
    ) -> None:
        target, _ = self._pick(session, now, exclude=(lost,))
        if target is None:
            # no capacity right now: wait at the router, re-placed by
            # _drain_waiting once a replica frees up — latency, not loss
            track.primary = None
            track.hedge = None
            track.pending = True
            self._waiting.append(track.rid)
            return
        track.hedge = None
        self._place(track, target, now, ROUTE_RETRY)
        session.replica = target.name

    def _drain_waiting(self, now: float) -> None:
        """Re-place router-parked work (retry backlog whose replicas were
        full, or drain-parked arrivals whose replica DIED instead of
        restoring).  Handoff-parked tracks stay put while their replica is
        DRAINING/PARKED — restore_replica replays those."""
        still: list[str] = []
        for rid in self._waiting:
            track = self._tracks.get(rid)
            if track is None or track.done:
                continue
            bound = self._replicas.get(track.primary or "")
            if bound is not None and bound.state in (DRAINING, PARKED):
                still.append(rid)  # the handoff owns this one
                continue
            session = self._sessions[track.sid]
            target, _ = self._pick(session, now)
            if target is None:
                still.append(rid)
                continue
            self._place(track, target, now, ROUTE_RETRY)
            session.replica = target.name
        self._waiting = still

    def _reap_retired(self) -> None:
        for name, rep in list(self._replicas.items()):
            if not rep.retiring:
                continue
            busy = any(
                name in (t.primary, t.hedge)
                for t in self._tracks.values()
            )
            if not busy:
                del self._replicas[name]

    # ------------------------------------------------------------------
    # Drain handoff (MigrationCoordinator.drain_pod follows this exactly:
    # drain_replica() IS the pod's "checkpoint complete" — the fake
    # kubelet reports Succeeded once it returns — and restore_replica()
    # is the restore pod's startup).

    def drain_replica(
        self, name: str, ckpt_dir: str = "", now: Optional[float] = None
    ) -> list[str]:
        """Checkpoint ``name`` for a drain: final token sweep, park its
        sessions, snapshot engine + schedule.  Returns the schedule (the
        rids riding inside the snapshot)."""
        now = time.time() if now is None else now
        with self._lock:
            rep = self._replicas[name]
            ckpt_dir = ckpt_dir or rep.ckpt_dir
            rep.state = DRAINING
            # final sweep: everything decoded up to the checkpoint cut is
            # delivered BEFORE the snapshot, so restore-side re-announce
            # dedup starts from a consistent count
            events, _ = rep.handle.poll(now)
            self._on_tokens(rep, events, now)
            sessions = {
                t.sid for t in self._tracks.values()
                if name in (t.primary, t.hedge)
            }
            schedule = rep.handle.checkpoint(
                ckpt_dir,
                # drained_at marks the checkpoint cut: the restored
                # replica rebases in-flight timing past the pause
                extra={"sessions": sorted(sessions), "drained_at": now},
            )
            rep.schedule = list(schedule)
            rep.ckpt_dir = ckpt_dir
            rep.state = PARKED
            # in-flight work parks with its sessions; a racing hedge pair
            # collapses to the parked side deterministically
            for track in self._tracks.values():
                if track.done:
                    continue
                if name in (track.primary, track.hedge):
                    if track.hedge is not None:
                        other = (
                            track.primary
                            if track.hedge == name else track.hedge
                        )
                        self._cancel_on(other, track.rid)
                        track.hedge = None
                    track.primary = name
            self.counts["handoff_parked"] += len(sessions)
            if self.metrics is not None and sessions:
                self.metrics.frontdoor_handoffs_total.labels(
                    outcome="parked"
                ).inc(len(sessions))
            return schedule

    def restore_replica(
        self,
        name: str,
        handle: LocalReplica,
        node: str = "",
        now: Optional[float] = None,
    ) -> dict:
        """Attach the restored replica and replay the handoff backlog.

        The snapshot's schedule resumes INSIDE the restored engine at its
        exact request-schedule position — those rids are only re-tracked,
        never resubmitted.  Everything else the router holds for this
        replica (arrivals parked mid-drain) is replayed in arrival order.
        """
        now = time.time() if now is None else now
        with self._lock:
            rep = self._replicas[name]
            in_snapshot = set(rep.schedule)
            rep.handle = handle
            rep.node = node or rep.node
            rep.state = READY
            rep.evidence_ts = now  # restore grace, like add_replica
            rep.queue_depth = 0.0
            replayed = 0
            still: list[str] = []
            for rid in self._waiting:
                track = self._tracks.get(rid)
                if track is None or track.done or track.primary != name:
                    still.append(rid)
                    continue
                if rid in in_snapshot:
                    # already riding the snapshot: resubmitting would
                    # duplicate it at the engine — the no-dup contract
                    track.pending = False
                    continue
                self._place(track, rep, now, ROUTE_REPLAY)
                replayed += 1
            self._waiting = still
            rep.schedule = []
            self.counts["handoff_restored"] += 1
            self.counts["handoff_replayed"] += replayed
            if self.metrics is not None:
                self.metrics.frontdoor_handoffs_total.labels(
                    outcome="restored"
                ).inc()
                if replayed:
                    self.metrics.frontdoor_handoffs_total.labels(
                        outcome="replayed"
                    ).inc(replayed)
            return {"replayed": replayed, "resumed": len(in_snapshot)}

    # ------------------------------------------------------------------
    # Introspection.

    def _export(self) -> None:
        states = {s: 0 for s in REPLICA_STATES}
        for rep in self._replicas.values():
            states[rep.state] = states.get(rep.state, 0) + 1
        for state, n in states.items():
            self.metrics.frontdoor_replicas.labels(state=state).set(n)
        self.metrics.frontdoor_sessions.set(len(self._sessions))

    def result(self, rid: str) -> Optional[dict]:
        with self._lock:
            track = self._completed.get(rid) or self._tracks.get(rid)
            if track is None:
                state = "failed" if rid in self._failed else "unknown"
                return {"rid": rid, "state": state} if state != "unknown" else None
            return {
                "rid": rid,
                "state": "done" if track.done else (
                    "parked" if track.pending else "running"
                ),
                "delivered": track.delivered,
                "tokens": list(track.tokens),
            }

    def mean_queue_depth(self) -> float:
        with self._lock:
            ready = [
                rep.queue_depth for rep in self._replicas.values()
                if rep.state == READY and not rep.retiring
            ]
            return sum(ready) / len(ready) if ready else 0.0

    def stats(self, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        with self._lock:
            states = {s: 0 for s in REPLICA_STATES}
            for rep in self._replicas.values():
                states[rep.state] += 1
            return {
                "ts": round(now, 3),
                "replicas": states,
                "replica_names": {
                    name: {
                        "state": rep.state, "node": rep.node,
                        "queue_depth": rep.queue_depth,
                        "evidence_age_s": round(now - rep.evidence_ts, 3),
                        "retiring": rep.retiring,
                    }
                    for name, rep in self._replicas.items()
                },
                "sessions": len(self._sessions),
                "live_requests": len(self._tracks),
                "waiting": len(self._waiting),
                "counts": dict(self.counts),
                "failed_rids": list(self._failed),
                "ttft_p99_s": round(_percentile(sorted(self._ttft), 0.99), 6),
                "tpot_p99_s": round(_percentile(sorted(self._tpot), 0.99), 6),
            }


# ---------------------------------------------------------------------------
# Session-affine traffic: the open-loop stream the soak pours at the door.


class SessionTraffic:
    """Wraps :class:`PoissonTraffic` with a seeded session assignment —
    the same deterministic schedule contract (rate, arrival cursor, rng
    bit state), plus each request draws one of ``n_sessions`` session
    ids.  ``rate`` is mutable mid-stream: the ramp profile just sets it."""

    def __init__(
        self,
        rate: float,
        n_sessions: int = 16,
        prompt_tokens: tuple = (24, 64),
        new_tokens: tuple = (12, 32),
        seed: int = 0,
        prefix: str = "fd",
    ):
        self.traffic = PoissonTraffic(
            rate, prompt_tokens=prompt_tokens, new_tokens=new_tokens,
            seed=seed, prefix=prefix,
        )
        self.n_sessions = n_sessions
        self._srng = np.random.default_rng(seed + 1)

    @property
    def rate(self) -> float:
        return self.traffic.rate

    @rate.setter
    def rate(self, value: float) -> None:
        self.traffic.rate = value

    def due(self, now: float) -> list[tuple[str, Request]]:
        return [
            (f"s{int(self._srng.integers(0, self.n_sessions))}", req)
            for req in self.traffic.due(now)
        ]


# ---------------------------------------------------------------------------
# The HTTP face: one logical endpoint.


def build_app(fd: FrontDoor):
    """aiohttp application exposing the front door:

    - ``POST /v1/generate`` ``{"session", "prompt", "max_new_tokens"}`` →
      202 ``{"rid"}`` or 429 with a ``Retry-After`` header
    - ``GET /v1/result/{rid}`` → request state + delivered tokens
    - ``GET /debug/frontdoor`` → router stats
    - ``GET /healthz``
    """
    from aiohttp import web

    async def generate(request):
        try:
            body = await request.json()
            sid = str(body["session"])
            prompt = [int(t) for t in body["prompt"]]
            max_new = int(body.get("max_new_tokens") or 16)
        except (KeyError, TypeError, ValueError):
            return web.json_response({"error": "bad request"}, status=400)
        verdict = fd.submit(sid, prompt, max_new)
        if verdict["status"] == SHED:
            return web.json_response(
                verdict, status=429,
                headers={"Retry-After": f"{verdict['retry_after_s']:g}"},
            )
        return web.json_response(verdict, status=202)

    async def result(request):
        out = fd.result(request.match_info["rid"])
        if out is None:
            return web.json_response({"error": "unknown rid"}, status=404)
        return web.json_response(out)

    async def debug(request):
        return web.json_response(fd.stats())

    async def healthz(request):
        return web.json_response({"ok": True, "ready": fd.ready_count()})

    app = web.Application()
    app.router.add_post("/v1/generate", generate)
    app.router.add_get("/v1/result/{rid}", result)
    app.router.add_get("/debug/frontdoor", debug)
    app.router.add_get("/healthz", healthz)
    return app
