"""Replica handles: the engines the front door routes onto.

A :class:`LocalReplica` wraps one PR-15 :class:`ServingEngine` behind the
narrow surface the router needs — submit / cancel / step / poll /
telemetry — plus the two seams everything fault-tolerant about the front
door is tested through:

- ``kill()``: the SIGKILL story.  Engine state (KV cache, batch, queue)
  is gone with no checkpoint; any further call raises
  :class:`ReplicaGone`.  Only the router's session retry budget brings
  the in-flight work back.
- ``blackhole()``: the failure mode a liveness probe misses.  The
  replica keeps ACCEPTING submissions but never steps, never emits a
  token, and never reports telemetry again — so its pushed capacity
  evidence goes stale and the router's freshness rule (obs/fleet
  ``serving_view``) is the only detector.

``checkpoint()`` / ``restore()`` ride the PR-8 checkpoint machinery
(atomic manifest-last snapshots, hash-verified restore): the engine's
full snapshot plus a ``frontdoor`` extra carrying the in-flight request
SCHEDULE — the ordered rids inside the snapshot — which is the
no-duplicate/no-skip contract the router's drain handoff replays
against.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from tpu_operator.workloads import checkpoint as ckpt_api
from tpu_operator.workloads.serving import (
    DONE,
    Request,
    ServeConfig,
    ServingEngine,
    ServingError,
)


class ReplicaGone(Exception):
    """The replica's process is dead; nothing on it can be reached."""


@dataclass(frozen=True)
class TokenEvent:
    """One decoded token surfaced to the router.

    ``position`` is the generated-token index (0-based, prompt excluded):
    the dedup/billing key.  Two sources (a hedge pair, a pre- and
    post-restore engine) emitting the same ``(rid, position)`` must bill
    once — the model is deterministic greedy decode, so the token VALUES
    agree and the router only has to count positions.
    """

    rid: str
    position: int
    token: int
    ts: float


class LocalReplica:
    """One in-process serving replica (the soak's fleet unit).

    All calls arrive from the front door under its lock — the handle
    itself keeps no lock.  ``_tracked`` holds live references to the
    engine's own :class:`Request` objects; the engine mutates them in
    place, so :meth:`poll` surfaces new tokens by diffing each request's
    generated count against what was already reported.
    """

    def __init__(
        self,
        name: str,
        cfg: Optional[ServeConfig] = None,
        node: str = "",
        engine: Optional[ServingEngine] = None,
    ):
        self.name = name
        self.cfg = cfg or ServeConfig(name=name)
        self.node = node
        self.engine = engine if engine is not None else ServingEngine(self.cfg)
        self.alive = True
        self.blackholed = False
        # rid -> live engine Request; rid -> generated tokens already polled
        self._tracked: dict[str, Request] = {}
        self._reported: dict[str, int] = {}
        # submissions swallowed while blackholed (accepted, never served)
        self.swallowed: list[str] = []
        # checkpoint cut time awaiting the first post-restore step: the
        # drain→restore pause is not service time (the subprocess serve
        # loop runs on elapsed service time and never sees it; a
        # wall-clock caller must rebase instead)
        self._rebase_from: Optional[float] = None

    # -- the router-facing surface -------------------------------------
    def submit(self, req: Request) -> bool:
        if not self.alive:
            raise ReplicaGone(self.name)
        if self.blackholed:
            # connection accepted, request swallowed: the blackhole
            # contract — the caller sees success and waits forever
            self.swallowed.append(req.rid)
            return True
        ok = self.engine.submit(req)
        if ok:
            self._tracked[req.rid] = req
            self._reported.setdefault(req.rid, 0)
        return ok

    def cancel(self, rid: str) -> bool:
        if not self.alive or self.blackholed:
            return False
        self._tracked.pop(rid, None)
        self._reported.pop(rid, None)
        return self.engine.cancel(rid)

    def step(self, now: float) -> Optional[dict]:
        """One engine iteration; None when dead or blackholed (a black
        hole makes no progress — that is the point)."""
        if not self.alive or self.blackholed:
            return None
        if self._rebase_from is not None:
            pause = now - self._rebase_from
            self._rebase_from = None
            if pause > 0:
                # shift in-flight timing past the handoff gap so TPOT
                # and TTFT keep measuring decode latency, not the
                # migration pause (which handoff metrics already count)
                for req in (*self.engine.queued, *self.engine.prefilling,
                            *self.engine.running):
                    if req.last_token_at is not None:
                        req.last_token_at += pause
                    if req.first_token_at is None:
                        req.arrival += pause
        return self.engine.step(now)

    def poll(self, now: float) -> tuple[list[TokenEvent], list[str]]:
        """(new token events since last poll, rids that finished)."""
        events: list[TokenEvent] = []
        finished: list[str] = []
        if not self.alive or self.blackholed:
            return events, finished
        for rid, req in list(self._tracked.items()):
            seen = self._reported.get(rid, 0)
            gen = req.generated
            base = len(req.prompt)
            for pos in range(seen, gen):
                events.append(TokenEvent(rid, pos, req.tokens[base + pos], now))
            if gen > seen:
                self._reported[rid] = gen
            if req.state == DONE:
                finished.append(rid)
                del self._tracked[rid]
                self._reported.pop(rid, None)
        return events, finished

    def telemetry(self, now: float) -> Optional[dict]:
        """The ``serve_*`` capacity evidence the push hop forwards; None
        when dead or blackholed — the push simply stops, the fleet-side
        freshness stamp ages out, and the router routes away."""
        if not self.alive or self.blackholed:
            return None
        return self.engine.telemetry(now)

    @property
    def inflight(self) -> int:
        return len(self._tracked)

    # -- chaos seams ---------------------------------------------------
    def kill(self) -> None:
        """SIGKILL: all engine state is gone, no checkpoint, no goodbye."""
        self.alive = False
        self.engine = None  # type: ignore[assignment]
        self._tracked.clear()
        self._reported.clear()

    def blackhole(self, on: bool = True) -> None:
        self.blackholed = on

    # -- drain / restore (the PR-8 migration contract) -----------------
    def checkpoint(self, ckpt_dir: str, extra: Optional[dict] = None) -> list[str]:
        """Full-state snapshot for a drain; returns the SCHEDULE — the
        in-flight rids inside the snapshot, in the engine's queue order
        (queued → prefilling → running).  The restored engine resumes
        exactly these; anything the router holds beyond them must be
        replayed, anything on this list must NOT be."""
        if not self.alive or self.blackholed:
            raise ReplicaGone(self.name)
        arrays, eng_extra = self.engine.snapshot()
        schedule = [entry["rid"] for entry in eng_extra["requests"]]
        eng_extra["frontdoor"] = {
            **(extra or {}),
            "replica": self.name,
            "schedule": schedule,
        }
        ckpt_api.save_checkpoint(
            ckpt_dir, step=self.engine.steps, arrays=arrays, extra=eng_extra
        )
        return schedule

    @classmethod
    def restore(
        cls,
        name: str,
        cfg: ServeConfig,
        ckpt_dir: str,
        node: str = "",
    ) -> tuple["LocalReplica", dict]:
        """(restored replica, the checkpoint's ``frontdoor`` extra).

        Every snapshot request re-registers as tracked with its generated
        count marked already-reported: those tokens were delivered by the
        pre-drain replica, and the router's position dedup absorbs any
        overlap regardless.
        """
        snap = ckpt_api.load_checkpoint(ckpt_dir)
        if snap is None:
            raise ServingError(f"no restorable checkpoint in {ckpt_dir}")
        engine = ServingEngine.from_snapshot(cfg, snap.arrays, snap.extra)
        replica = cls(name, cfg, node=node, engine=engine)
        for req in (*engine.queued, *engine.prefilling, *engine.running):
            replica._tracked[req.rid] = req
            replica._reported[req.rid] = req.generated
        fd_extra = dict(snap.extra.get("frontdoor") or {})
        drained_at = fd_extra.get("drained_at")
        if drained_at is not None:
            replica._rebase_from = float(drained_at)
        return replica, fd_extra
