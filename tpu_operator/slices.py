"""ICI topology partitioning.

Reference analogue: MIG device partitioning — mig-parted profiles
(assets/state-mig-manager/0400_configmap.yaml) splitting one GPU into typed
slices.  The TPU analogue splits an ICI mesh (e.g. v5p 4x4x4) into
sub-slices: each partition is an axis-aligned box of chips, the whole set
must tile the mesh exactly, and every box must be contiguous so intra-slice
traffic stays on ICI.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass

from tpu_operator.utils import parse_topology, topology_chips


class PartitionError(ValueError):
    pass


@dataclass(frozen=True)
class Partition:
    shape: tuple[int, ...]
    origin: tuple[int, ...]

    @property
    def chips(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    def coords(self) -> list[tuple[int, ...]]:
        ranges = [range(o, o + s) for o, s in zip(self.origin, self.shape)]
        return [tuple(c) for c in itertools.product(*ranges)]


def _fits(shape: tuple[int, ...], mesh: tuple[int, ...]) -> bool:
    return len(shape) == len(mesh) and all(s <= m and m % s == 0 for s, m in zip(shape, mesh))


def partition_topology(topology: str, shapes: list[str]) -> list[Partition]:
    """Place ``shapes`` (e.g. ["2x4x4", "2x4x4"]) into ``topology`` (4x4x4).

    Greedy first-fit over the mesh in lexicographic order; raises
    PartitionError unless the shapes exactly tile the mesh (MIG semantics:
    a profile either fits the device exactly or is rejected — no partial
    layouts).
    """
    mesh = parse_topology(topology)
    want = [parse_topology(s) for s in shapes]
    if not want:
        return []
    total = sum(topology_chips(s) for s in shapes)
    if total != topology_chips(topology):
        raise PartitionError(
            f"shapes {shapes} cover {total} chips; topology {topology} has "
            f"{topology_chips(topology)}"
        )
    for shape in want:
        if not _fits(shape, mesh):
            raise PartitionError(f"shape {'x'.join(map(str, shape))} does not tile {topology}")

    occupied: set[tuple[int, ...]] = set()
    placed: list[Partition] = []

    def all_coords():
        return itertools.product(*[range(m) for m in mesh])

    # big boxes first → greedy packing succeeds for axis-divisible tilings
    for shape in sorted(want, key=lambda s: -topology_chips("x".join(map(str, s)))):
        placed_one = False
        for origin in all_coords():
            if any(o + s > m for o, s, m in zip(origin, shape, mesh)):
                continue
            part = Partition(shape=shape, origin=origin)
            coords = part.coords()
            if any(c in occupied for c in coords):
                continue
            occupied.update(coords)
            placed.append(part)
            placed_one = True
            break
        if not placed_one:
            raise PartitionError(f"cannot place {'x'.join(map(str, shape))} in {topology}")
    return placed


def chip_assignments(topology: str, shapes: list[str], chips_per_host: int) -> list[dict]:
    """Partition layout with flat chip ids + owning hosts.

    Chips are numbered in row-major mesh order; host h owns chips
    [h*chips_per_host, (h+1)*chips_per_host).  Returns one dict per
    partition: {shape, origin, chip_ids, hosts}.
    """
    mesh = parse_topology(topology)
    parts = partition_topology(topology, shapes)

    strides = [1] * len(mesh)
    for i in range(len(mesh) - 2, -1, -1):
        strides[i] = strides[i + 1] * mesh[i + 1]

    out = []
    for part in parts:
        ids = sorted(sum(c * s for c, s in zip(coord, strides)) for coord in part.coords())
        hosts = sorted({i // chips_per_host for i in ids}) if chips_per_host else []
        out.append(
            {
                "shape": "x".join(map(str, part.shape)),
                "origin": list(part.origin),
                "chip_ids": ids,
                "hosts": hosts,
            }
        )
    return out


def shape_dims(topology: str) -> tuple[int, ...]:
    """Parsed topology with leading 1-axes stripped ("1x2x4" == "2x4"):
    the canonical coordinate form the scheduler compares shapes in."""
    dims = tuple(parse_topology(topology))
    while len(dims) > 1 and dims[0] == 1:
        dims = dims[1:]
    return dims


def shape_fits(shape: str, mesh: str) -> bool:
    """True when an axis-aligned contiguous box of ``shape`` can be carved
    out of ``mesh`` — the contiguity test behind the slice scheduler's
    single-arc placement (a grant must stay on ICI; only a multislice
    grant may span meshes).  A lower-dimensional shape embeds by padding
    with 1-axes (a 2x4 box fits a 4x4x4 mesh as 1x2x4), and axes may be
    reoriented: sorting both dimension lists descending and comparing
    pairwise decides whether an injective axis assignment with
    ``s <= m`` exists."""
    s = shape_dims(shape)
    m = shape_dims(mesh)
    if len(s) > len(m):
        return False
    s_sorted = sorted(s, reverse=True)
    m_sorted = sorted(m, reverse=True)
    return all(a <= b for a, b in zip(s_sorted, m_sorted))


def shape_divides(shape: str, mesh: str) -> bool:
    """Like :func:`shape_fits` but each assigned mesh axis must also be
    divisible by the shape axis — the tiling-compatible embedding that
    keeps a partially-granted mesh partitionable by the slice manager.

    Unlike the ``<=`` relation (where sorted-descending pairwise
    comparison decides matchability), divisibility is not monotone — 2x3
    tiles 3x4 via the assignment 3→3, 2→4, which sorted pairing (3→4)
    misses — so this searches the axis assignments outright (meshes have
    at most 3 axes; the permutation space is trivial)."""
    s = shape_dims(shape)
    m = shape_dims(mesh)
    if len(s) > len(m):
        return False
    return any(
        all(a <= b and b % a == 0 for a, b in zip(s, assignment))
        for assignment in itertools.permutations(m, len(s))
    )


def load_profile(config: dict, profile: str, accelerator: str, topology: str) -> list[str]:
    """Resolve a named profile from the slice-config ConfigMap schema
    (assets/state-slice-manager/0400_configmap.yaml) to partition shapes for
    this node's accelerator/topology.  Empty list → whole-slice default."""
    profiles = config.get("slice-configs") or {}
    if profile not in profiles:
        raise PartitionError(f"unknown slice profile {profile!r}")
    for rule in profiles[profile]:
        accels = rule.get("accelerators") or ["*"]
        if "*" not in accels and accelerator not in accels:
            continue
        rule_topo = rule.get("topology")
        if rule_topo and rule_topo != topology:
            continue
        return list(rule.get("partitions") or [])
    raise PartitionError(
        f"profile {profile!r} has no rule for accelerator={accelerator} topology={topology}"
    )
