"""ICI topology partitioning.

Reference analogue: MIG device partitioning — mig-parted profiles
(assets/state-mig-manager/0400_configmap.yaml) splitting one GPU into typed
slices.  The TPU analogue splits an ICI mesh (e.g. v5p 4x4x4) into
sub-slices: each partition is an axis-aligned box of chips, the whole set
must tile the mesh exactly, and every box must be contiguous so intra-slice
traffic stays on ICI.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass

from tpu_operator.utils import parse_topology, topology_chips


class PartitionError(ValueError):
    pass


@dataclass(frozen=True)
class Partition:
    shape: tuple[int, ...]
    origin: tuple[int, ...]

    @property
    def chips(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    def coords(self) -> list[tuple[int, ...]]:
        ranges = [range(o, o + s) for o, s in zip(self.origin, self.shape)]
        return [tuple(c) for c in itertools.product(*ranges)]


def _fits(shape: tuple[int, ...], mesh: tuple[int, ...]) -> bool:
    return len(shape) == len(mesh) and all(s <= m and m % s == 0 for s, m in zip(shape, mesh))


def partition_topology(topology: str, shapes: list[str]) -> list[Partition]:
    """Place ``shapes`` (e.g. ["2x4x4", "2x4x4"]) into ``topology`` (4x4x4).

    Greedy first-fit over the mesh in lexicographic order; raises
    PartitionError unless the shapes exactly tile the mesh (MIG semantics:
    a profile either fits the device exactly or is rejected — no partial
    layouts).
    """
    mesh = parse_topology(topology)
    want = [parse_topology(s) for s in shapes]
    if not want:
        return []
    total = sum(topology_chips(s) for s in shapes)
    if total != topology_chips(topology):
        raise PartitionError(
            f"shapes {shapes} cover {total} chips; topology {topology} has "
            f"{topology_chips(topology)}"
        )
    for shape in want:
        if not _fits(shape, mesh):
            raise PartitionError(f"shape {'x'.join(map(str, shape))} does not tile {topology}")

    occupied: set[tuple[int, ...]] = set()
    placed: list[Partition] = []

    def all_coords():
        return itertools.product(*[range(m) for m in mesh])

    # big boxes first → greedy packing succeeds for axis-divisible tilings
    for shape in sorted(want, key=lambda s: -topology_chips("x".join(map(str, s)))):
        placed_one = False
        for origin in all_coords():
            if any(o + s > m for o, s, m in zip(origin, shape, mesh)):
                continue
            part = Partition(shape=shape, origin=origin)
            coords = part.coords()
            if any(c in occupied for c in coords):
                continue
            occupied.update(coords)
            placed.append(part)
            placed_one = True
            break
        if not placed_one:
            raise PartitionError(f"cannot place {'x'.join(map(str, shape))} in {topology}")
    return placed


def chip_assignments(topology: str, shapes: list[str], chips_per_host: int) -> list[dict]:
    """Partition layout with flat chip ids + owning hosts.

    Chips are numbered in row-major mesh order; host h owns chips
    [h*chips_per_host, (h+1)*chips_per_host).  Returns one dict per
    partition: {shape, origin, chip_ids, hosts}.
    """
    mesh = parse_topology(topology)
    parts = partition_topology(topology, shapes)

    strides = [1] * len(mesh)
    for i in range(len(mesh) - 2, -1, -1):
        strides[i] = strides[i + 1] * mesh[i + 1]

    out = []
    for part in parts:
        ids = sorted(sum(c * s for c, s in zip(coord, strides)) for coord in part.coords())
        hosts = sorted({i // chips_per_host for i in ids}) if chips_per_host else []
        out.append(
            {
                "shape": "x".join(map(str, part.shape)),
                "origin": list(part.origin),
                "chip_ids": ids,
                "hosts": hosts,
            }
        )
    return out


def load_profile(config: dict, profile: str, accelerator: str, topology: str) -> list[str]:
    """Resolve a named profile from the slice-config ConfigMap schema
    (assets/state-slice-manager/0400_configmap.yaml) to partition shapes for
    this node's accelerator/topology.  Empty list → whole-slice default."""
    profiles = config.get("slice-configs") or {}
    if profile not in profiles:
        raise PartitionError(f"unknown slice profile {profile!r}")
    for rule in profiles[profile]:
        accels = rule.get("accelerators") or ["*"]
        if "*" not in accels and accelerator not in accels:
            continue
        rule_topo = rule.get("topology")
        if rule_topo and rule_topo != topology:
            continue
        return list(rule.get("partitions") or [])
    raise PartitionError(
        f"profile {profile!r} has no rule for accelerator={accelerator} topology={topology}"
    )
