"""Declarative state engine.

Reference analogue: ``internal/state/`` — a Manager running an ordered list of
State implementations, each rendering templated manifests and applying them
with ownerRef + state label + hash-skip, then gating on readiness
(state.go:34-39, state_skel.go:223-444, manager.go:31-108).
"""

from tpu_operator.state.skel import OperandState, SyncState  # noqa: F401
from tpu_operator.state.manager import StateManager, ClusterContext  # noqa: F401
