"""Ordered state walk.

Reference analogue: ClusterPolicyController.init()/step()/last()
(controllers/state_manager.go:754-990) merged with internal/state/manager.go's
SyncState/Results aggregation — one engine, no legacy/declarative split.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Optional

from tpu_operator import consts
from tpu_operator.api.types import TPUClusterPolicy
from tpu_operator.k8s.client import ApiClient
from tpu_operator.obs import trace
from tpu_operator.render import Renderer, new_renderer
from tpu_operator.state.render_data import STATE_DEFS, ClusterContext
from tpu_operator.state.skel import OperandState, StateResult, SyncState

log = logging.getLogger("tpu_operator.state")


@dataclass
class SyncResults:
    results: list[StateResult] = field(default_factory=list)

    @property
    def ready(self) -> bool:
        return all(r.state in (SyncState.READY, SyncState.DISABLED, SyncState.IGNORE) for r in self.results)

    @property
    def not_ready_states(self) -> list[StateResult]:
        return [r for r in self.results if r.state == SyncState.NOT_READY]

    @property
    def error_states(self) -> list[StateResult]:
        return [r for r in self.results if r.state == SyncState.ERROR]

    def message(self) -> str:
        parts = [f"{r.name}: {r.message or r.state}" for r in self.results if r.state in (SyncState.NOT_READY, SyncState.ERROR)]
        return "; ".join(parts)


class StateManager:
    """Walks every state in order each reconcile pass, aggregating results.

    Unlike the reference's idx-cursor step() (state_manager.go:945-983) the
    whole chain runs per pass — states are independent DaemonSets whose
    init-container gating enforces the node-level ordering, so applying all
    manifests up front converges faster than one-state-per-requeue while the
    per-node file gates (validations dir) preserve correctness.  That same
    independence makes the walk safe to run CONCURRENTLY (bounded): apply
    order between states never was the ordering mechanism, the per-node
    gates are.  Results stay in STATE_DEFS order regardless of completion
    order, so status messages and transition Events are deterministic.
    """

    def __init__(self, renderer: Optional[Renderer] = None, concurrency: Optional[int] = None):
        self.renderer = renderer or new_renderer()
        self.states = [OperandState(sdef, self.renderer) for sdef in STATE_DEFS]
        # None → consts value at sync time (lets the reconcile bench A/B a
        # serial walk without rebuilding the manager)
        self.concurrency = concurrency

    async def sync(
        self,
        client: ApiClient,
        ctx: ClusterContext,
        policy: TPUClusterPolicy,
    ) -> SyncResults:
        limit = self.concurrency or consts.STATE_SYNC_CONCURRENCY
        sem = asyncio.Semaphore(max(1, limit))

        async def run(state: OperandState) -> StateResult:
            async with sem:
                try:
                    # feeds state_sync_duration_seconds{state} + the span tree
                    with trace.span(
                        f"state/{state.name}", kind=trace.KIND_STATE, state=state.name
                    ):
                        return await state.sync(client, ctx, policy)
                except Exception as e:  # noqa: BLE001
                    log.exception("state %s sync failed", state.name)
                    return StateResult(state.name, SyncState.ERROR, str(e))

        out = SyncResults()
        out.results = list(await asyncio.gather(*(run(s) for s in self.states)))
        return out
