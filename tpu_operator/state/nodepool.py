"""TPU node-pool partitioning for per-pool runtime DaemonSets.

Reference analogue: internal/state/nodepool.go:55-133 — the driver state
splits GPU nodes into pools (per kernel for precompiled, per RHCOS on OCP,
else per osVersion) and renders one DaemonSet per pool.  TPU pools split on
what actually differentiates the runtime payload: (accelerator type, ICI
topology) — a v5e 2x4 host and a v5p 4x4x4 host pin different libtpu builds.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpu_operator import consts
from tpu_operator.k8s import nodeinfo
from tpu_operator.utils import fnv1a_64


@dataclass(frozen=True)
class NodePool:
    accelerator: str
    topology: str
    node_count: int
    # nodeSelector that uniquely targets this pool's nodes
    selector: dict = field(hash=False, default_factory=dict)

    @property
    def name(self) -> str:
        """Short pool id used in DaemonSet names (getDriverName analogue)."""
        accel = self.accelerator.replace("tpu-", "").replace("-podslice", "").replace("-slice", "")
        return f"{accel}-{self.topology}".replace(".", "-").lower()


def hashed_name(base: str, suffix: str, cap: int = 63) -> str:
    """DNS-1123-capped unique name (getDriverAppName analogue,
    internal/state/driver.go:428-457)."""
    name = f"{base}-{suffix}"
    if len(name) <= cap:
        return name
    digest = format(fnv1a_64(name.encode()) & 0xFFFFFFFF, "08x")
    return f"{name[: cap - 9]}-{digest}"


def get_node_pools(nodes: list[dict], node_selector: dict | None = None) -> list[NodePool]:
    """Partition TPU nodes into runtime pools.

    ``node_selector``: the TPURuntime CR's own selector — only matching
    nodes join pools (nvidiadriver nodeSelector semantics).
    """
    f = nodeinfo.NodeFilter().tpu().selector(node_selector)
    groups = nodeinfo.Provider(f.apply(nodes)).pools()

    pools = []
    for (accel, topo), members in sorted(groups.items()):
        count = len(members)
        selector = dict(node_selector or {})
        selector[consts.GKE_TPU_ACCELERATOR_LABEL] = accel
        if topo:
            selector[consts.GKE_TPU_TOPOLOGY_LABEL] = topo
        pools.append(NodePool(accelerator=accel, topology=topo, node_count=count, selector=selector))
    return pools
