"""Per-state template render data.

Reference analogue: the Transform* functions of controllers/object_controls.go
(:757-2110) plus stateDriver's driverRenderData (internal/state/driver.go:84-93).
Unlike the reference's dual static-YAML+transform path, all per-spec variation
flows through ONE rendering pass: this module maps (cluster context, CR spec)
to the context consumed by assets/<state>/*.yaml templates.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpu_operator import consts
from tpu_operator.api import types as api_types
from tpu_operator.api.types import OperandSpec, TPUClusterPolicySpec


@dataclass
class ClusterContext:
    """Environment facts gathered once per reconcile (init() analogue,
    controllers/state_manager.go:754-889)."""

    namespace: str
    k8s_version: str = ""
    runtime: str = "containerd"
    service_monitors_available: bool = False
    tpu_node_count: int = 0
    openshift: bool = False
    # serialized obs.trace.TraceContext of the reconcile that initiated the
    # current rollout, minted by the clusterpolicy reconciler ONCE per spec
    # change (NOT per pass — a per-pass value would defeat the render memo
    # and rewrite every DaemonSet every reconcile, breaking the zero-write
    # steady state bench.py pins).  Rendered into operand pod templates as
    # the TPU_TRACEPARENT env contract + pod annotation, so validator
    # phases, workload flight records, and the agents' push hop all join
    # the operator's trace.  Empty (dev/standalone renders) renders nothing.
    traceparent: str = ""


# Default tolerations: GKE TPU node pools carry the google.com/tpu taint,
# and operand pods must keep running on health-quarantined nodes — the
# recovery proof (validator re-run, agent verdicts) comes from exactly the
# pods the quarantine taint would otherwise evict on reschedule
# (docs/ROBUSTNESS.md "Node health engine").
_DEFAULT_TOLERATIONS = [
    {"key": consts.TPU_RESOURCE, "operator": "Exists", "effect": "NoSchedule"},
    {"key": "node-role.kubernetes.io/master", "operator": "Exists", "effect": "NoSchedule"},
    {"key": consts.HEALTH_TAINT_KEY, "operator": "Exists", "effect": "NoSchedule"},
]


def _operand_image(spec: OperandSpec, component: str) -> str:
    try:
        return spec.image_path(component)
    except ValueError:
        # dev fallback so a bare CR works without the env ConfigMap the
        # production Deployment injects (config/manager/manager.yaml:67-69
        # pattern); production pins exact images via CR or env.
        from tpu_operator.version import __version__

        return f"ghcr.io/tpu-operator/tpu-{component}:{__version__}"


def _operand_block(spec: OperandSpec, component: str) -> dict:
    return {
        "name": component,
        "image": _operand_image(spec, component),
        "pull_policy": spec.image_pull_policy,
        "args": list(spec.args),
        "env": list(spec.env),
        "resources": spec.resources,
    }


def base_render_data(ctx: ClusterContext, spec: TPUClusterPolicySpec) -> dict:
    """Context keys every template/macro may rely on."""
    ds = spec.daemonsets
    tolerations = list(_DEFAULT_TOLERATIONS) + list(ds.tolerations)
    return {
        "namespace": ctx.namespace,
        "runtime_class": spec.operator.runtime_class,
        "default_runtime_handler": spec.operator.default_runtime,
        "priority_class": ds.priority_class_name,
        "tolerations": tolerations,
        "ds_labels": dict(ds.labels),
        "ds_annotations": dict(ds.annotations),
        "update_strategy": ds.update_strategy,
        "rolling_update": ds.rolling_update,
        # per-operand imagePullSecrets are stamped by StateDef.render_data;
        # states without an operand spec run no pods
        "image_pull_secrets": [],
        "deploy_label_prefix": consts.DEPLOY_LABEL_PREFIX,
        # cross-process trace propagation (obs/trace.py TraceContext):
        # macros render it as the TPU_TRACEPARENT env + the traceparent
        # pod annotation on every operand/validator pod template
        "traceparent": ctx.traceparent,
        "traceparent_annotation": consts.TRACEPARENT_ANNOTATION,
        # live-migration patience window (migration.timeoutSeconds): stamped
        # into validator pod env (and through it the workload pods it
        # spawns) so a checkpoint-on-drain workload knows how long the
        # operator waits before falling back to evict — snapshot work past
        # it is wasted.  0 renders nothing (migration disabled).
        "migration_timeout_seconds": (
            spec.migration.timeout_seconds if spec.migration.enabled else 0
        ),
        "validation_dir": consts.VALIDATION_DIR,
        "validation_dir_root": consts.VALIDATION_DIR.rsplit("/", 1)[0],
        "compile_cache_dir": consts.COMPILE_CACHE_DIR,
        # fleet compile-artifact cache (workloads/compile_cache.py): the
        # validator (and through it its workload pods) reaches the
        # operator's /compile-cache/* surface via the node metrics agent's
        # relay on its localhost hostPort — rendered as TPU_FLEET_CACHE_URL
        "fleet_cache_url": f"http://127.0.0.1:{spec.metrics_agent.host_port}",
        "service_monitors_available": ctx.service_monitors_available,
        "validator": {
            "image": _operand_image(spec.validator, "validator"),
            "pull_policy": spec.validator.image_pull_policy,
            "plugin_env": list(spec.validator.plugin.env),
            "jax_env": list(spec.validator.jax.env),
            # post-ready probe budget (validator.perfProbes): rendered as
            # env only when set so defaults keep the built-in behavior
            "perf_checks": spec.validator.perf_probes.checks,
            "perf_budget_seconds": spec.validator.perf_probes.budget_seconds,
        },
        "slice_strategy": spec.slice_manager.strategy,
        # CDI (reference cdi sub-spec): the device plugin maintains the
        # host CDI spec when enabled and answers with CDI device names
        # when default
        "cdi": {"enabled": spec.cdi.enabled, "default": spec.cdi.default},
    }


@dataclass
class StateDef:
    """One operand state: asset dir + how to build its render data."""

    name: str
    operand: Optional[Callable[[TPUClusterPolicySpec], OperandSpec]] = None
    component: str = ""
    extras: Callable[[ClusterContext, TPUClusterPolicySpec], dict] = field(
        default=lambda ctx, spec: {}
    )
    # DS states are skipped (Ready) when the cluster has no TPU nodes
    # (object_controls.go:4046-4053); cluster-scope states always apply.
    requires_tpu_nodes: bool = True

    def render_data(self, ctx: ClusterContext, spec: TPUClusterPolicySpec) -> dict:
        data = base_render_data(ctx, spec)
        if self.operand is not None:
            operand_spec = self.operand(spec)
            data["operand"] = _operand_block(operand_spec, self.component)
            # union with the validator's secrets: most operand DS pods embed
            # validator-image init containers (wait/run_validation macros)
            merged = list(operand_spec.image_pull_secrets)
            merged += [s for s in spec.validator.image_pull_secrets if s not in merged]
            data["image_pull_secrets"] = merged
        data.update(self.extras(ctx, spec))
        return data


def _libtpu_extras(ctx: ClusterContext, spec: TPUClusterPolicySpec) -> dict:
    up = spec.libtpu.upgrade_policy
    return {
        "libtpu": {
            "libtpu_version": spec.libtpu.libtpu_version,
            "runtime_channel": spec.libtpu.runtime_channel,
            "drain_force": str(up.drain.force).lower(),
            "drain_timeout_seconds": up.drain.timeout_seconds,
        }
    }


def _runtime_prep_extras(ctx: ClusterContext, spec: TPUClusterPolicySpec) -> dict:
    return {
        "runtime_prep": {
            "device_permissions": spec.runtime_prep.device_permissions,
            "hugepages_gb": spec.runtime_prep.hugepages_gb,
        }
    }


def _device_plugin_extras(ctx: ClusterContext, spec: TPUClusterPolicySpec) -> dict:
    cfg = spec.device_plugin.config
    return {
        "device_plugin": {
            "config_map": cfg.name,
            "default_config": cfg.default or "default",
        }
    }


def _metrics_agent_extras(ctx: ClusterContext, spec: TPUClusterPolicySpec) -> dict:
    return {
        "metrics_agent": {
            "host_port": spec.metrics_agent.host_port,
            # the fleet telemetry hop (obs/fleet.py): agents forward their
            # /push traffic to the operator metrics Service's ingest route
            "fleet_push_url": (
                f"http://tpu-operator-metrics.{ctx.namespace}.svc:8080/push"
            ),
        },
    }


def _metrics_exporter_extras(ctx: ClusterContext, spec: TPUClusterPolicySpec) -> dict:
    me = spec.metrics_exporter
    return {
        "metrics_agent": {"host_port": spec.metrics_agent.host_port},
        "metrics_exporter": {
            "port": me.port,
            "config_map": me.metrics_config,
            "config_file": "/etc/tpu-metrics-exporter/counters.csv" if me.metrics_config else None,
            "service_monitor": me.service_monitor.enabled,
            "service_monitor_interval": me.service_monitor.interval,
            "service_monitor_honor_labels": me.service_monitor.honor_labels,
            "service_monitor_labels": me.service_monitor.additional_labels,
            "service_monitor_relabelings": me.service_monitor.relabelings,
        },
    }


def _feature_discovery_extras(ctx: ClusterContext, spec: TPUClusterPolicySpec) -> dict:
    return {"feature_discovery": {"sleep_interval": spec.feature_discovery.sleep_interval}}


# Anything outside the schema alphabets could smuggle separators into the
# agent's name=handler,... env contract, path components into the drop-in
# filename, or raw lines into the privileged containerd config.  Admission
# rejects malformed entries with a path'd error (api/types.py VM_* patterns,
# enforced by the apiserver / CEL-lite); this filter is defense in depth for
# objects that never passed admission.
_VM_CLASS_NAME_RE = re.compile(api_types.VM_CLASS_NAME_PATTERN)
_VM_HANDLER_RE = re.compile(api_types.VM_HANDLER_PATTERN)
_VM_CONFIG_DIR_RE = re.compile(api_types.VM_CONFIG_DIR_PATTERN)


def _vm_runtime_extras(ctx: ClusterContext, spec: TPUClusterPolicySpec) -> dict:
    vr = spec.vm_runtime
    # only well-formed entries reach the template: a malformed CR entry
    # must not render a RuntimeClass with a null handler, and hostile
    # name/handler strings must not reach the env/file/config contracts
    classes = [
        {"name": rc["name"], "handler": rc.get("handler") or rc["name"]}
        for rc in vr.runtime_classes
        if isinstance(rc, dict)
        and isinstance(rc.get("name"), str)
        and _VM_CLASS_NAME_RE.fullmatch(rc["name"])
        and _VM_HANDLER_RE.fullmatch(str(rc.get("handler") or rc["name"]))
    ]
    config_dir = vr.config_dir
    if not _VM_CONFIG_DIR_RE.fullmatch(config_dir or ""):
        # never let a traversal/unsafe path reach the hostPath template or
        # the agent's root-relative join (admission already rejects this)
        config_dir = "/etc/containerd/conf.d"
    return {
        "vm_runtime": {
            "runtime_classes": classes,
            # the agent's VM_RUNTIME_CLASSES env contract: name=handler list
            "classes_env": ",".join(f"{c['name']}={c['handler']}" for c in classes),
            "config_dir": config_dir,
        }
    }


def _slice_manager_extras(ctx: ClusterContext, spec: TPUClusterPolicySpec) -> dict:
    cfg = spec.slice_manager.config
    return {
        "slice_manager": {
            "config_map": cfg.name or "default-tpu-slice-config",
            "default_config": cfg.default or "all-disabled",
            "render_default_config": not cfg.name,
        }
    }


# Ordered registry (addState ×N analogue, controllers/state_manager.go:795-813).
STATE_DEFS: list[StateDef] = [
    StateDef("pre-requisites", requires_tpu_nodes=False),
    StateDef("state-operator-metrics", requires_tpu_nodes=False),
    StateDef("state-libtpu", lambda s: s.libtpu, "libtpu", _libtpu_extras),
    StateDef("state-runtime-prep", lambda s: s.runtime_prep, "runtime-prep", _runtime_prep_extras),
    StateDef("state-operator-validation", lambda s: s.validator, "validator"),
    StateDef("state-device-plugin", lambda s: s.device_plugin, "device-plugin", _device_plugin_extras),
    StateDef("state-metrics-agent", lambda s: s.metrics_agent, "metrics-agent", _metrics_agent_extras),
    StateDef("state-metrics-exporter", lambda s: s.metrics_exporter, "metrics-exporter", _metrics_exporter_extras),
    StateDef("tpu-feature-discovery", lambda s: s.feature_discovery, "feature-discovery", _feature_discovery_extras),
    StateDef("state-slice-manager", lambda s: s.slice_manager, "slice-manager", _slice_manager_extras),
    StateDef(
        "state-node-status-exporter", lambda s: s.node_status_exporter,
        "node-status-exporter", _metrics_agent_extras,
    ),
    StateDef("state-sandbox-validation", lambda s: s.validator, "validator"),
    StateDef("state-vfio-manager", lambda s: s.vfio_manager, "vfio-manager"),
    StateDef("state-vm-runtime", lambda s: s.vm_runtime, "vm-runtime", _vm_runtime_extras),
    StateDef("state-sandbox-device-plugin", lambda s: s.sandbox_device_plugin, "sandbox-device-plugin"),
]

if tuple(d.name for d in STATE_DEFS) != consts.STATE_NAMES:
    raise RuntimeError("STATE_DEFS registry out of sync with consts.STATE_NAMES")


def state_def(name: str) -> StateDef:
    for d in STATE_DEFS:
        if d.name == name:
            return d
    raise KeyError(name)
