"""State skeleton: render → apply → readiness, plus delete-on-disable.

Reference analogue: ``internal/state/state_skel.go`` — createOrUpdateObjs
(:223-285), addStateSpecificLabels (:287-294), getSupportedGVKs whitelist
(:62-165), getSyncState/isDaemonSetReady (:383-444) — and the legacy engine's
disabled-state deletion pattern (controllers/object_controls.go:267-274).
"""

from __future__ import annotations

import dataclasses
import logging
from dataclasses import dataclass, field as dc_field
from typing import Optional

from tpu_operator import consts
from tpu_operator.api.types import TPUClusterPolicy
from tpu_operator.k8s.apply import create_or_update, delete_if_exists
from tpu_operator.k8s.client import ApiClient
from tpu_operator.render import Renderer
from tpu_operator.state.render_data import ClusterContext, StateDef
from tpu_operator.utils import bounded_gather, deep_get, object_hash

log = logging.getLogger("tpu_operator.state")


class SyncState:
    """internal/state/state.go:34-39 SyncState values."""

    READY = "ready"
    NOT_READY = "notReady"
    DISABLED = "disabled"
    IGNORE = "ignore"
    ERROR = "error"


# Kinds a state may own and that delete-on-disable sweeps, in deletion order
# (getSupportedGVKs analogue, state_skel.go:62-165).
SUPPORTED_GVKS: tuple[tuple[str, str], ...] = (
    ("apps", "DaemonSet"),
    ("apps", "Deployment"),
    ("monitoring.coreos.com", "ServiceMonitor"),
    ("monitoring.coreos.com", "PrometheusRule"),
    ("", "Service"),
    ("", "ConfigMap"),
    ("rbac.authorization.k8s.io", "RoleBinding"),
    ("rbac.authorization.k8s.io", "Role"),
    ("rbac.authorization.k8s.io", "ClusterRoleBinding"),
    ("rbac.authorization.k8s.io", "ClusterRole"),
    ("", "ServiceAccount"),
    ("node.k8s.io", "RuntimeClass"),
)


def _obj_key(obj: dict) -> tuple[str, str, str]:
    return (
        obj.get("kind", ""),
        deep_get(obj, "metadata", "namespace", default="") or "",
        deep_get(obj, "metadata", "name", default=""),
    )


def daemonset_ready(ds: dict, empty_ok: bool = False) -> bool:
    """Desired == Available == Updated (OnDelete revision matching is
    approximated by updatedNumberScheduled, which our fake kubelet
    maintains).  Two rules for desired == 0, both from the reference:

    - ``empty_ok=False`` (per-pool runtime DS, state_skel.go:439-441):
      pools are derived from live nodes, so a zero-desired DS is stale —
      NOT ready.
    - ``empty_ok=True`` (ClusterPolicy operand chain,
      object_controls.go:3363-3366): operands are gated by per-node
      workload-config deploy labels, and a gate matching no nodes is a
      normal configuration (e.g. sandboxWorkloads enabled before any
      vm-passthrough node joins) — vacuously ready.  Unlike the
      reference (whose state_skel.go comment warns about the quirk), a
      zero-desired DS only counts as vacuously ready once the DS
      controller has actually processed it (status.observedGeneration
      caught up) — a freshly created DS with an unpopulated status must
      not flash the ClusterPolicy READY before pods are scheduled.  The
      same staleness gate covers desired > 0: a just-updated DS (spec PUT
      bumped metadata.generation) keeps its pre-update status counts until
      the DS controller observes the new revision — matching those stale
      counts must not report the rollout complete."""
    status = ds.get("status") or {}
    generation = deep_get(ds, "metadata", "generation", default=1) or 1
    if status.get("observedGeneration", 0) < generation:
        return False
    desired = status.get("desiredNumberScheduled", 0)
    if desired == 0:
        return empty_ok
    return (
        desired == status.get("numberAvailable", 0)
        and desired == status.get("updatedNumberScheduled", 0)
    )


def deployment_ready(dep: dict) -> bool:
    replicas = deep_get(dep, "spec", "replicas", default=1)
    status = dep.get("status") or {}
    return status.get("availableReplicas", 0) >= replicas


@dataclass
class StateResult:
    name: str
    state: str
    message: str = ""
    applied: int = 0


@dataclass
class OperandState:
    """One reconcile-chain state driven by a StateDef."""

    sdef: StateDef
    renderer: Renderer
    # deletion sweep runs once per enabled→disabled transition, not every
    # pass (the reference deletes in the disabled branch of each controlFunc
    # but its objects are tracked; we track via this flag)
    _cleaned: bool = dc_field(default=False, compare=False)
    # rendered-object keys from the previous pass; when the set shrinks
    # (conditional template blocks turned off), strays are pruned by label
    _last_rendered: frozenset = dc_field(default=frozenset(), compare=False)
    # (input hash, rendered objects) memo: rendering is pure in (ctx, spec)
    # and is the CPU hot path of a steady-state pass, so identical inputs
    # reuse the previous pass's manifests (safe: the apply layer deep-copies
    # before mutating, nothing else writes into them)
    _render_memo: Optional[tuple] = dc_field(default=None, compare=False)

    @property
    def name(self) -> str:
        return self.sdef.name

    async def sync(
        self,
        client: ApiClient,
        ctx: ClusterContext,
        policy: TPUClusterPolicy,
    ) -> StateResult:
        spec = policy.spec
        if not spec.state_enabled(self.name):
            if self._cleaned:
                return StateResult(self.name, SyncState.DISABLED, "state disabled")
            deleted = await self.delete_objects(client, ctx.namespace)
            self._cleaned = True
            self._last_rendered = frozenset()
            return StateResult(
                self.name, SyncState.DISABLED, f"state disabled; removed {deleted} objects"
            )
        self._cleaned = False
        if self.sdef.requires_tpu_nodes and ctx.tpu_node_count == 0:
            # no TPU nodes → nothing to schedule; state is vacuously ready
            # (object_controls.go:4046-4053)
            return StateResult(self.name, SyncState.READY, "no TPU nodes; state skipped")

        objs = self._render(ctx, policy)
        # Bounded fan-out: one state's objects (SA/RBAC/ConfigMap/Service/DS)
        # reference each other by NAME only — k8s resolves references at use
        # time, not admission time — so apply order within a state is free.
        results = await bounded_gather(
            (
                create_or_update(client, obj, owner=policy.obj, state_label=self.name)
                for obj in objs
            ),
            limit=consts.APPLY_CONCURRENCY,
        )
        live_objs = [live for live, _ in results]
        applied = sum(int(changed) for _, changed in results)

        # Prune objects that fell out of the rendered set (e.g. the
        # device-plugin RBAC after devicePlugin.config is removed, or a
        # ServiceMonitor after serviceMonitor.enabled flips off).  The sweep
        # runs when the rendered set changes — including the first pass after
        # an operator restart, when _last_rendered is empty.
        rendered = frozenset(_obj_key(o) for o in objs)
        if rendered != self._last_rendered:
            await self._prune(client, ctx.namespace, rendered)
            self._last_rendered = rendered

        ready, message = self._readiness(live_objs)
        return StateResult(
            self.name,
            SyncState.READY if ready else SyncState.NOT_READY,
            message,
            applied,
        )

    def _render(self, ctx: ClusterContext, policy: TPUClusterPolicy) -> list[dict]:
        if not consts.RENDER_MEMO:
            return self.renderer.render_dir(self.name, self.sdef.render_data(ctx, policy.spec))
        key = object_hash([dataclasses.asdict(ctx), policy.obj.get("spec") or {}])
        if self._render_memo is not None and self._render_memo[0] == key:
            return self._render_memo[1]
        objs = self.renderer.render_dir(self.name, self.sdef.render_data(ctx, policy.spec))
        self._render_memo = (key, objs)
        return objs

    def _readiness(self, live_objs: list[dict]) -> tuple[bool, str]:
        for obj in live_objs:
            kind = obj.get("kind")
            name = deep_get(obj, "metadata", "name", default="?")
            if kind == "DaemonSet" and not daemonset_ready(obj, empty_ok=True):
                return False, f"DaemonSet {name} not ready"
            if kind == "Deployment" and not deployment_ready(obj):
                return False, f"Deployment {name} not ready"
        return True, ""

    async def _prune(self, client: ApiClient, namespace: str, keep: frozenset) -> None:
        for item in await self._list_labeled(client, namespace):
            if _obj_key(item) not in keep:
                await delete_if_exists(client, item)
                log.info(
                    "state %s pruned stray %s %s", self.name, item.get("kind"),
                    deep_get(item, "metadata", "name"),
                )

    async def _list_labeled(self, client: ApiClient, namespace: str) -> list[dict]:
        """Everything this state ever applied, matched by state label.

        Namespaced kinds are listed in the operator namespace; cluster-scoped
        kinds cluster-wide.  A kind whose API is absent (e.g. ServiceMonitor
        without prometheus-operator) is skipped; real failures propagate so
        the state reports ERROR instead of lying about cleanup.
        """
        from tpu_operator.k8s import objects as obj_api
        from tpu_operator.k8s.client import ApiError

        selector = f"{consts.STATE_LABEL}={self.name}"

        async def list_one(group: str, kind: str) -> list[dict]:
            ns = namespace if obj_api.lookup(group, kind).namespaced else None
            try:
                items = await client.list_items(group, kind, ns, selector)
            except ApiError as e:
                if e.status in (404, 405):  # API/kind not served in this cluster
                    return []
                raise
            # list responses omit item kind; stamp it for _obj_key/delete
            for item in items:
                item.setdefault("kind", kind)
                item.setdefault("apiVersion", obj_api.lookup(group, kind).gvk.api_version)
            return items

        # fan the per-GVK lists out; flattened result keeps SUPPORTED_GVKS
        # order, which delete_objects relies on as its deletion order
        lists = await bounded_gather(
            (list_one(group, kind) for group, kind in SUPPORTED_GVKS),
            limit=consts.LIST_SWEEP_CONCURRENCY,
        )
        return [item for items in lists for item in items]

    async def delete_objects(self, client: ApiClient, namespace: str) -> int:
        deleted = 0
        for item in await self._list_labeled(client, namespace):
            await delete_if_exists(client, item)
            deleted += 1
        return deleted
