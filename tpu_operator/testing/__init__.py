"""Test infrastructure: in-process fake Kubernetes API server + node simulators.

Reference analogue: the fake client of controllers/object_controls_test.go:52-260
plus the e2e harness of tests/e2e/.  Unlike the reference (SURVEY §4: "multi-node
testing: not simulated"), this fake serves real HTTP + watch streams, so the
operator under test runs its actual network/client/informer stack against an
N-node simulated cluster, including kubelet-style DaemonSet scheduling.
"""

from tpu_operator.testing.chaos import ChaosConfig, ChaosEngine
from tpu_operator.testing.fakecluster import FakeCluster, SimConfig

__all__ = ["ChaosConfig", "ChaosEngine", "FakeCluster", "SimConfig"]
