"""Seeded fault injection for the fake apiserver (the chaos layer).

The reference operator is tested against a healthy fake client; real clusters
are not healthy.  ``ChaosConfig`` describes a reproducible fault schedule —
everything is drawn from one ``random.Random(seed)`` so a failing run replays
byte-identically — and ``ChaosEngine`` applies it at the fake apiserver's
choke points:

- per-request transient failures (429 with ``Retry-After``, 500, 503, raw
  connection aborts), weighted per verb and per resource when configured
- post-commit failures: the mutation IS applied server-side but the client
  sees a 5xx — the case that punishes blind POST replay with duplicate
  objects (the retry policy's non-idempotent classification plus the apply
  layer's adopt path must absorb it)
- latency spikes and hard hangs (flushing out missing request timeouts)
- watch-stream faults: 410 Gone on connect and mid-stream drops (flushing
  out informer relist/backoff taxonomy)
- background actor faults driven by the sim loop: validator-style pod
  crash-loops and node Ready-condition flaps
- ``FakeCluster.steal_lease`` (one-shot, not rate-driven) for leadership
  transitions

``stop()`` freezes all injection so a soak can assert the system returns to
its zero-write fixed point once chaos ends.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional

# sentinel fault kinds returned by ChaosEngine.request_fault
FAULT_429 = "429"
FAULT_500 = "500"
FAULT_503 = "503"
FAULT_RESET = "reset"
FAULT_HANG = "hang"


@dataclass
class ChaosConfig:
    seed: int = 0
    # chance any request draws a transient failure; per-verb / per-resource
    # overrides win over the default (verb first, then resource plural)
    error_rate: float = 0.0
    verb_error_rates: dict = field(default_factory=dict)    # {"POST": 0.2}
    kind_error_rates: dict = field(default_factory=dict)    # {"pods": 0.1} (plural)
    # relative weights of the injected failure flavours
    error_weights: dict = field(default_factory=lambda: {
        FAULT_429: 1.0, FAULT_500: 1.0, FAULT_503: 1.0, FAULT_RESET: 1.0,
    })
    retry_after_s: float = 0.05      # Retry-After carried by injected 429s
    # mutation applied server-side, then the response is swapped for a 500 —
    # the ambiguous-failure case that makes POST replay mint duplicates
    post_commit_error_rate: float = 0.0
    # latency: every request may draw an extra uniform(lo, hi) sleep
    latency_spike_rate: float = 0.0
    latency_spike_s: tuple = (0.02, 0.2)
    # hard hang: request parks until the client's per-try timeout fires
    hang_rate: float = 0.0
    hang_s: float = 30.0
    # watch faults
    watch_gone_rate: float = 0.0     # watch GET answered 410 Gone
    watch_drop_rate: float = 0.0     # chance a watch stream is given a drop deadline
    watch_drop_after_s: tuple = (0.1, 1.5)
    # background actors (driven from the sim loop at sim.tick cadence)
    pod_crashloop_selector: str = "" # label selector, e.g. app=tpu-operator-validator
    pod_crashloop_rate: float = 0.0  # per matching Running pod per tick
    pod_restart_after_s: float = 0.0 # 0 = stay Failed (deterministic tests)
    node_flap_interval: float = 0.0  # seconds between NotReady flaps (0 = off)
    node_flap_down_s: float = 0.5
    # agent-verdict faults: every interval one random node's (simulated)
    # node-status-exporter publishes tpu-health=unhealthy with the reason
    # code below, recovering to ok after down_s — the signal-plane input
    # the health engine's hysteresis must judge (chip scrape failures etc.)
    agent_unhealthy_interval: float = 0.0  # 0 = off
    agent_unhealthy_down_s: float = 3.0
    agent_unhealthy_reason: str = "chip-scrape-failed"
    # capacity shock: every interval one whole GKE nodepool (rng-chosen,
    # optionally restricted to pools whose name starts with the prefix)
    # goes agent-unhealthy at once — the correlated capacity loss that
    # forces the preemption economy to reclaim/park rather than nibble at
    # single-node faults — recovering together after down_s
    pool_shock_interval: float = 0.0  # 0 = off
    pool_shock_down_s: float = 5.0
    pool_shock_prefix: str = ""       # "" = any pool is fair game
    pool_shock_reason: str = "pool-capacity-shock"
    # serving front-door fleet actors (driven by the router soak at its
    # tick cadence, judged once per ready replica per tick): SIGKILL drops
    # a replica mid-decode with no checkpoint — every in-flight request
    # must come back through the session retry budget; blackhole makes a
    # replica accept submissions but never step or push telemetry — the
    # freshness detector must starve it of traffic and the hedge/retry
    # path must rescue what it swallowed.  Zero failed requests and no
    # duplicate decode billing are the gates (tests/test_frontdoor_chaos).
    replica_kill_rate: float = 0.0       # per ready replica per router tick
    replica_blackhole_rate: float = 0.0  # per ready replica per router tick
    # checkpoint faults (workloads/checkpoint.py TPU_CKPT_FAULT contract;
    # applied to signal-triggered snapshots only): kill_during_checkpoint
    # SIGKILLs the worker after the shard files but before the manifest —
    # the torn snapshot that must never be restored; slow_checkpoint_s
    # injects that much latency mid-snapshot so migration.timeoutSeconds
    # fires and the drain's timeout→evict fallback is exercised
    kill_during_checkpoint: bool = False
    slow_checkpoint_s: float = 0.0


class ChaosEngine:
    """Stateful, seeded interpreter of a :class:`ChaosConfig`.

    All randomness flows through ``self.rng`` — never the module-level
    ``random`` — so two engines with the same seed and the same call
    sequence inject the same schedule.  ``injected`` tallies every fault for
    the soak report.
    """

    def __init__(self, config: ChaosConfig):
        self.config = config
        self.rng = random.Random(config.seed)
        self.active = True
        # set to override every error-rate knob at once (blackout phases)
        self.force_error_rate: Optional[float] = None
        self.injected: dict[str, int] = {}

    def stop(self) -> None:
        """Freeze all injection (steady-state measurement phase)."""
        self.active = False

    def resume(self) -> None:
        self.active = True

    def _count(self, kind: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1

    # ------------------------------------------------------------------
    def _rate_for(self, method: str, plural: str) -> float:
        if self.force_error_rate is not None:
            return self.force_error_rate
        cfg = self.config
        if method in cfg.verb_error_rates:
            return cfg.verb_error_rates[method]
        if plural in cfg.kind_error_rates:
            return cfg.kind_error_rates[plural]
        return cfg.error_rate

    def latency_spike(self) -> float:
        """Extra seconds to sleep before handling, 0 for none."""
        if not self.active:
            return 0.0
        cfg = self.config
        if cfg.latency_spike_rate and self.rng.random() < cfg.latency_spike_rate:
            self._count("latency_spike")
            return self.rng.uniform(*cfg.latency_spike_s)
        return 0.0

    def request_fault(self, method: str, plural: str) -> Optional[str]:
        """Pre-dispatch fault for this request, or None.  Draws latency/hang
        first so the two knobs compose; the transient flavour is weighted."""
        if not self.active:
            return None
        cfg = self.config
        if cfg.hang_rate and self.rng.random() < cfg.hang_rate:
            self._count(FAULT_HANG)
            return FAULT_HANG
        rate = self._rate_for(method, plural)
        if rate and self.rng.random() < rate:
            kinds = [k for k, w in cfg.error_weights.items() if w > 0]
            weights = [cfg.error_weights[k] for k in kinds]
            kind = self.rng.choices(kinds, weights=weights)[0]
            self._count(kind)
            return kind
        return None

    def post_commit_fault(self, method: str) -> bool:
        """Swap a SUCCESSFUL mutation's response for a 500 (the write stuck)."""
        if not self.active or method not in ("POST", "PUT", "PATCH", "DELETE"):
            return False
        if (
            self.config.post_commit_error_rate
            and self.rng.random() < self.config.post_commit_error_rate
        ):
            self._count("post_commit_500")
            return True
        return False

    # ------------------------------------------------------------------
    def watch_gone(self) -> bool:
        if not self.active:
            return False
        if self.config.watch_gone_rate and self.rng.random() < self.config.watch_gone_rate:
            self._count("watch_410")
            return True
        return False

    def watch_drop_after(self) -> Optional[float]:
        """Seconds after which this watch stream is dropped, or None."""
        if not self.active:
            return None
        cfg = self.config
        if cfg.watch_drop_rate and self.rng.random() < cfg.watch_drop_rate:
            self._count("watch_drop")
            return self.rng.uniform(*cfg.watch_drop_after_s)
        return None

    # ------------------------------------------------------------------
    def checkpoint_fault(self) -> Optional[str]:
        """``TPU_CKPT_FAULT`` env value for a workload being launched, or
        None.  The launcher (the fake kubelet's pod executor) stamps the
        value into the worker env and workloads/checkpoint.py interprets
        it at the canonical torn point of its next final snapshot (shard
        files written, manifest not yet published)."""
        if not self.active:
            return None
        cfg = self.config
        if cfg.kill_during_checkpoint:
            self._count("ckpt_kill")
            return "kill"
        if cfg.slow_checkpoint_s:
            self._count("ckpt_slow")
            return f"slow:{cfg.slow_checkpoint_s:g}"
        return None

    def should_crash_pod(self) -> bool:
        if not self.active or not self.config.pod_crashloop_rate:
            return False
        if self.rng.random() < self.config.pod_crashloop_rate:
            self._count("pod_crash")
            return True
        return False

    # ------------------------------------------------------------------
    def should_kill_replica(self) -> bool:
        """SIGKILL one serving replica mid-decode: engine state (KV cache,
        batch, queue) is gone with NO checkpoint — the front door's
        session retry budget is the only way its in-flight work survives."""
        if not self.active or not self.config.replica_kill_rate:
            return False
        if self.rng.random() < self.config.replica_kill_rate:
            self._count("replica_kill")
            return True
        return False

    def should_blackhole_replica(self) -> bool:
        """Blackhole one serving replica: it keeps ACCEPTING submissions
        but never decodes another token and never pushes telemetry again —
        the failure mode a liveness probe misses and only capacity-evidence
        freshness catches."""
        if not self.active or not self.config.replica_blackhole_rate:
            return False
        if self.rng.random() < self.config.replica_blackhole_rate:
            self._count("replica_blackhole")
            return True
        return False

    def report(self) -> dict:
        return dict(sorted(self.injected.items()))
