"""In-process fake Kubernetes API server over real HTTP.

Implements the API-machinery subset the operator exercises: generic CRUD for
every kind registered in ``tpu_operator.k8s.objects``, resourceVersion
bookkeeping, label/field selectors, watch streams (newline-delimited JSON)
with a replay ring buffer, the ``status`` subresource, ownerReference garbage
collection, and a kubelet simulator that schedules DaemonSet pods onto
matching nodes and drives pod/DaemonSet readiness.
"""

from __future__ import annotations

import asyncio
import bisect
import copy
import json
import logging
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from aiohttp import web

from tpu_operator import consts
from tpu_operator.k8s import objects as obj_api
from tpu_operator.k8s import selectors
from tpu_operator.testing.chaos import (
    FAULT_429,
    FAULT_500,
    FAULT_503,
    FAULT_HANG,
    FAULT_RESET,
    ChaosConfig,
    ChaosEngine,
)
from tpu_operator.utils import deep_get, fnv1a_64

log = logging.getLogger("tpu_operator.fakecluster")


@dataclass
class SimConfig:
    enabled: bool = True
    tick: float = 0.02
    pod_ready_delay: float = 0.05     # DS pod creation → Ready
    plugin_capacity_delay: float = 0.05  # plugin pod Ready → node advertises google.com/tpu
    # per-request latency emulating a real apiserver's RTT (0 = localhost
    # speed).  The reconcile bench sets this so request-count wins translate
    # into the wall-time they buy against a non-in-process control plane.
    api_latency: float = 0.0
    # Hook: given a workload pod dict, return final phase ("Succeeded"/"Failed").
    # Called in a thread for pods with restartPolicy != Always (validator
    # workload pods). None ⇒ auto-succeed after pod_ready_delay.
    pod_executor: Optional[Callable[[dict], str]] = None


class Store:
    """Object store for one resource collection (group+plural)."""

    def __init__(self, cluster: "FakeCluster", info: obj_api.ResourceInfo):
        self.cluster = cluster
        self.info = info
        self.objects: dict[tuple[str, str], dict] = {}  # (ns, name) -> obj
        # (queue, ns, parsed selector requirements)
        self.watchers: list[tuple[asyncio.Queue, Optional[str], list[selectors.Requirement]]] = []
        # (rv, event, pre-update labels or None) — the old labels let
        # selector-filtered watch delivery synthesize view transitions
        self.events: deque[tuple[int, dict, Optional[dict]]] = deque(maxlen=2048)
        # sorted-key snapshot for list/list_page (see _keys_sorted)
        self._sorted_keys: Optional[list[tuple[str, str]]] = None

    def key(self, namespace: Optional[str], name: str) -> tuple[str, str]:
        return (namespace or "", name)

    @staticmethod
    def _view_event(
        evt: dict,
        old_labels: Optional[dict],
        ns: Optional[str],
        parsed_sel: list[selectors.Requirement],
    ) -> Optional[dict]:
        """What one watcher sees for one store event — real-apiserver
        label-selector watch semantics: a MODIFIED whose label change moves
        the object OUT of the watcher's view is delivered as DELETED (last
        visible state), one that moves it IN is delivered as ADDED, and a
        change invisible to the selector is not delivered at all.  This is
        what lets a partitioned informer (one view per operator shard)
        track a node whose ``tpu.google.com/shard`` label is re-stamped:
        the old shard's view sees a delete, the new shard's view an add."""
        obj = evt["object"]
        if ns and obj["metadata"].get("namespace") != ns:
            return None
        if not parsed_sel:
            return evt
        labels = obj["metadata"].get("labels") or {}
        matched = all(r.matches(labels) for r in parsed_sel)
        if evt["type"] != "MODIFIED" or old_labels is None:
            return evt if matched else None
        was = all(r.matches(old_labels) for r in parsed_sel)
        if was and matched:
            return evt
        if was and not matched:
            return {"type": "DELETED", "object": obj}
        if matched:
            return {"type": "ADDED", "object": obj}
        return None

    def _notify(self, event_type: str, obj: dict, old: Optional[dict] = None) -> None:
        rv = int(obj["metadata"]["resourceVersion"])
        evt = {"type": event_type, "object": copy.deepcopy(obj)}
        old_labels = (
            copy.deepcopy(old["metadata"].get("labels") or {})
            if old is not None
            else None
        )
        self.events.append((rv, evt, old_labels))
        for queue, ns, parsed_sel in list(self.watchers):
            delivery = self._view_event(evt, old_labels, ns, parsed_sel)
            if delivery is not None:
                queue.put_nowait(delivery)

    # -- CRUD ----------------------------------------------------------
    def _admit(self, obj: dict, old: Optional[dict] = None) -> None:
        """CEL-lite admission for the operator's OWN CRDs (enums, bounds,
        immutability) — the fake stands in for the real apiserver, which
        enforces the same generated schema, so mutation tests reject here
        exactly where production would (api/admission.py)."""
        from tpu_operator.api import admission

        schema = admission.spec_schema(self.info.gvk.group, self.info.gvk.kind)
        if schema is None:
            return
        if old is None:
            errors = admission.validate_spec(schema, obj.get("spec") or {})
        else:
            errors = admission.validate_spec(
                schema, obj.get("spec") or {}, old.get("spec") or {}
            )
        if errors:
            raise ApiException(422, "Invalid", "; ".join(errors))

    def create(self, obj: dict, namespace: Optional[str]) -> dict:
        self._admit(obj)
        meta = obj.setdefault("metadata", {})
        if self.info.namespaced:
            meta["namespace"] = namespace or meta.get("namespace") or "default"
        name = meta.get("name")
        if not name and meta.get("generateName"):
            name = meta["generateName"] + uuid.uuid4().hex[:5]
            meta["name"] = name
        if not name:
            raise ApiException(422, "Invalid", "metadata.name required")
        k = self.key(meta.get("namespace"), name)
        if k in self.objects:
            raise ApiException(409, "AlreadyExists", f"{self.info.plural} {name} already exists")
        meta["uid"] = str(uuid.uuid4())
        meta["creationTimestamp"] = _now()
        meta["generation"] = 1
        meta["resourceVersion"] = str(self.cluster.next_rv())
        obj.setdefault("apiVersion", self.info.gvk.api_version)
        obj.setdefault("kind", self.info.gvk.kind)
        self.objects[k] = obj
        self._sorted_keys = None
        # duplicate-side-effect ledger: the chaos soak asserts no (kind,
        # ns, name) is ever successfully created twice under fault storms
        ck = (self.info.plural, meta.get("namespace", "") or "", name)
        self.cluster.created_counts[ck] = self.cluster.created_counts.get(ck, 0) + 1
        self._notify("ADDED", obj)
        return obj

    def get(self, namespace: Optional[str], name: str) -> dict:
        k = self.key(namespace, name)
        if k not in self.objects:
            raise ApiException(404, "NotFound", f"{self.info.plural} {name} not found")
        return self.objects[k]

    @staticmethod
    def _is_noop(merged: dict, existing: dict) -> bool:
        """True when ``merged`` changes nothing but (at most) the
        resourceVersion — a real apiserver returns the stored object
        unchanged for such writes (no rv bump, no watch event), and that
        semantics matters: cache-lagged controllers re-asserting state must
        not generate event storms that keep their own caches behind."""
        if {k: v for k, v in merged.items() if k != "metadata"} != {
            k: v for k, v in existing.items() if k != "metadata"
        }:
            return False
        return {
            k: v for k, v in merged.get("metadata", {}).items() if k != "resourceVersion"
        } == {
            k: v for k, v in existing.get("metadata", {}).items() if k != "resourceVersion"
        }

    def update(self, obj: dict, namespace: Optional[str], name: str, status_only: bool = False) -> dict:
        existing = self.get(namespace, name)
        new_meta = obj.get("metadata", {})
        if new_meta.get("resourceVersion") and new_meta["resourceVersion"] != existing["metadata"]["resourceVersion"]:
            raise ApiException(409, "Conflict", f"resourceVersion conflict on {name}")
        if status_only:
            merged = copy.deepcopy(existing)
            merged["status"] = obj.get("status", {})
        else:
            merged = copy.deepcopy(obj)
            # preserve server-owned metadata + status on spec updates
            merged["metadata"] = {**new_meta}
            for f in ("uid", "creationTimestamp", "generation", "namespace"):
                if f in existing["metadata"]:
                    merged["metadata"][f] = existing["metadata"][f]
            merged["metadata"]["name"] = name
            if "status" not in merged and "status" in existing:
                merged["status"] = existing["status"]
            if merged.get("spec") != existing.get("spec"):
                self._admit(merged, old=existing)
                merged["metadata"]["generation"] = existing["metadata"].get("generation", 1) + 1
        merged["apiVersion"] = self.info.gvk.api_version
        merged["kind"] = self.info.gvk.kind
        if self._is_noop(merged, existing):
            return existing
        merged["metadata"]["resourceVersion"] = str(self.cluster.next_rv())
        self.objects[self.key(namespace, name)] = merged
        self._notify("MODIFIED", merged, old=existing)
        return merged

    def patch(self, namespace: Optional[str], name: str, patch: Any, status_only: bool = False) -> dict:
        existing = copy.deepcopy(self.get(namespace, name))
        if isinstance(patch, list):  # JSON patch: support add/replace/remove on simple paths
            for op in patch:
                _apply_json_patch_op(existing, op)
            merged = existing
        else:
            merged = _merge_patch(existing, patch)
        return self.update(merged, namespace, name, status_only=status_only)

    def delete(self, namespace: Optional[str], name: str) -> dict:
        obj = self.get(namespace, name)
        del self.objects[self.key(namespace, name)]
        self._sorted_keys = None
        obj = copy.deepcopy(obj)
        obj["metadata"]["resourceVersion"] = str(self.cluster.next_rv())
        self._notify("DELETED", obj)
        self.cluster.collect_garbage(obj["metadata"]["uid"])
        return obj

    def _keys_sorted(self) -> list[tuple[str, str]]:
        """Sorted key snapshot, cached until membership changes: at 100k
        objects a per-request sort is the difference between a usable
        multi-replica bench and a control plane that starves its own
        clients (create/delete invalidate; updates keep the key set)."""
        if self._sorted_keys is None or len(self._sorted_keys) != len(self.objects):
            self._sorted_keys = sorted(self.objects)
        return self._sorted_keys

    def list(
        self,
        namespace: Optional[str],
        label_selector: str = "",
        field_selector: str = "",
    ) -> list[dict]:
        out = []
        reqs = selectors.parse(label_selector) if label_selector else []
        for key in self._keys_sorted():
            obj = self.objects.get(key)
            if obj is None:
                continue
            if namespace and key[0] != namespace:
                continue
            labels = obj["metadata"].get("labels") or {}
            if reqs and not all(r.matches(labels) for r in reqs):
                continue
            if field_selector and not _match_fields(field_selector, obj):
                continue
            out.append(obj)
        return out

    def list_page(
        self,
        namespace: Optional[str],
        label_selector: str,
        field_selector: str,
        limit: int,
        after_key: Optional[list],
    ) -> tuple[list[dict], Optional[list]]:
        """One ``limit``-sized page starting AFTER ``after_key``: bisect
        into the sorted key snapshot and scan forward only until the page
        fills, so a full chunked relist costs one pass over the store
        total — not one pass per page (O(pages x store), the quadratic
        that pinned the fake apiserver at 100 % CPU during 100k-node
        multi-replica relists)."""
        keys = self._keys_sorted()
        start = 0
        if after_key:
            start = bisect.bisect_right(keys, tuple(after_key))
        reqs = selectors.parse(label_selector) if label_selector else []
        page: list[dict] = []
        last_key: Optional[list] = None
        for idx in range(start, len(keys)):
            key = keys[idx]
            obj = self.objects.get(key)
            if obj is None:
                continue
            if namespace and key[0] != namespace:
                continue
            labels = obj["metadata"].get("labels") or {}
            if reqs and not all(r.matches(labels) for r in reqs):
                continue
            if field_selector and not _match_fields(field_selector, obj):
                continue
            page.append(obj)
            if len(page) == limit:
                last_key = list(key)
                # continuation is only meaningful if anything matches past
                # this point; a dangling token costs one empty page, fine
                if idx + 1 < len(keys):
                    return page, last_key
                return page, None
        return page, None


class ApiException(Exception):
    def __init__(self, status: int, reason: str, message: str):
        self.status = status
        self.reason = reason
        self.message = message
        super().__init__(message)

    def response(self) -> web.Response:
        return web.json_response(
            {
                "kind": "Status",
                "apiVersion": "v1",
                "status": "Failure",
                "message": self.message,
                "reason": self.reason,
                "code": self.status,
            },
            status=self.status,
        )


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _merge_patch(base: Any, patch: Any) -> Any:
    """RFC 7386 JSON merge patch."""
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    if not isinstance(base, dict):
        base = {}
    out = copy.deepcopy(base)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _merge_patch(out.get(k), v)
    return out


def _apply_json_patch_op(obj: dict, op: dict) -> None:
    parts = [p.replace("~1", "/").replace("~0", "~") for p in op["path"].lstrip("/").split("/")]
    cur: Any = obj
    for p in parts[:-1]:
        cur = cur[int(p)] if isinstance(cur, list) else cur.setdefault(p, {})
    last = parts[-1]
    kind = op["op"]
    if kind in ("add", "replace"):
        if isinstance(cur, list):
            if last == "-":
                cur.append(op["value"])
            else:
                cur.insert(int(last), op["value"]) if kind == "add" else cur.__setitem__(int(last), op["value"])
        else:
            cur[last] = op["value"]
    elif kind == "remove":
        if isinstance(cur, list):
            del cur[int(last)]
        else:
            cur.pop(last, None)


def _match_fields(field_selector: str, obj: dict) -> bool:
    for part in field_selector.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            path, val = part.split("!=", 1)
            if str(deep_get(obj, *path.split("."), default="")) == val:
                return False
        elif "==" in part:
            path, val = part.split("==", 1)
            if str(deep_get(obj, *path.split("."), default="")) != val:
                return False
        elif "=" in part:
            path, val = part.split("=", 1)
            if str(deep_get(obj, *path.split("."), default="")) != val:
                return False
    return True


class FakeCluster:
    """Runs the fake apiserver on 127.0.0.1:<port> plus simulators."""

    def __init__(self, sim: Optional[SimConfig] = None, chaos: Optional[ChaosConfig] = None):
        self.sim = sim or SimConfig()
        # fault-injection layer (testing/chaos.py): None = perfectly healthy
        self.chaos: Optional[ChaosEngine] = ChaosEngine(chaos) if chaos else None
        self._rv = 0
        self.stores: dict[tuple[str, str], Store] = {}
        for (group, _kind), info in obj_api._REGISTRY.items():
            self.stores[(group, info.plural)] = self.stores.get((group, info.plural)) or Store(self, info)
        self._runner: Optional[web.AppRunner] = None
        self._sim_task: Optional[asyncio.Task] = None
        self._chaos_task: Optional[asyncio.Task] = None
        # strong refs to in-flight pod-executor tasks: without one a task
        # can be GC'd mid-flight and its exception vanishes; stop() cancels
        # any still running so a test teardown never leaks an executor
        self._exec_tasks: set[asyncio.Task] = set()
        self.port: Optional[int] = None
        self._pod_timers: dict[tuple[str, str], float] = {}
        # workload pods whose executor is currently running (concurrent:
        # multi-host validation pods rendezvous at a coordinator and must
        # all execute at once)
        self._executing: set[tuple[str, str]] = set()
        # apiserver request accounting: {(method, group/plural): count} —
        # the control-plane scale tests prove reconcile passes stay
        # O(states + nodes) in requests, not O(states x nodes^2)
        self.request_counts: dict[tuple[str, str], int] = {}
        # successful creations per (plural, ns, name) — duplicate detector
        self.created_counts: dict[tuple[str, str, str], int] = {}
        # chaos background-actor state
        self._flapped_node: Optional[tuple[str, float]] = None
        self._last_flap_at = 0.0
        self._crash_restarts: dict[tuple[str, str], float] = {}
        # agent-verdict faults: node name -> restore deadline (overlapping
        # episodes allowed — that is what exhausts a health budget)
        self._unhealthy_nodes: dict[str, float] = {}
        self._last_agent_fault_at = 0.0
        self._shocked_pool_nodes: dict[str, float] = {}
        self._last_pool_shock_at = 0.0
        # DELETE options observed per object: (plural, ns, name, grace) —
        # lets tests assert drain grace propagation without a real kubelet
        self.delete_options: list[tuple[str, str, str, Optional[str]]] = []

    def reset_request_counts(self) -> None:
        self.request_counts = {}

    def total_requests(self) -> int:
        return sum(self.request_counts.values())

    def duplicate_creations(
        self, exclude_plurals: tuple = ("pods", "events", "leases")
    ) -> dict[tuple[str, str, str], int]:
        """Objects successfully created more than once.  Pods (sim/crash-loop
        churn), Events (uuid-suffixed), and Leases are excluded — the signal
        is operand/config objects minted twice by a replayed create."""
        return {
            k: n for k, n in self.created_counts.items()
            if n > 1 and k[0] not in exclude_plurals
        }

    # ------------------------------------------------------------------
    def next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def store(self, group: str, plural: str) -> Store:
        key = (group, plural)
        if key not in self.stores:
            raise ApiException(404, "NotFound", f"unknown resource {group}/{plural}")
        return self.stores[key]

    def store_for_kind(self, group: str, kind: str) -> Store:
        info = obj_api.lookup(group, kind)
        return self.store(group, info.plural)

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def collect_garbage(self, owner_uid: str) -> None:
        """Delete objects owned (via ownerReferences) by a deleted uid."""
        for store in self.stores.values():
            for (ns, name), obj in list(store.objects.items()):
                if obj_api.owned_by(obj, owner_uid):
                    try:
                        store.delete(ns or None, name)
                    except ApiException:
                        pass

    # ------------------------------------------------------------------
    # Direct (in-process) manipulation helpers for tests.

    def put(self, obj: dict) -> dict:
        """Create-or-replace directly in the store (test setup)."""
        info = obj_api.info_of(obj)
        store = self.store(info.gvk.group, info.plural)
        meta = obj.setdefault("metadata", {})
        ns = meta.get("namespace") if info.namespaced else None
        try:
            store.get(ns, meta["name"])
            existing = store.get(ns, meta["name"])
            obj.setdefault("metadata", {})["resourceVersion"] = existing["metadata"]["resourceVersion"]
            return store.update(obj, ns, meta["name"])
        except ApiException:
            return store.create(obj, ns)

    def get_obj(self, group: str, kind: str, name: str, namespace: Optional[str] = None) -> dict:
        return self.store_for_kind(group, kind).get(namespace, name)

    def add_node(
        self,
        name: str,
        labels: Optional[dict] = None,
        tpu: bool = True,
        accelerator: str = "tpu-v5-lite-podslice",
        topology: str = "2x4",
        chips: int = 4,
    ) -> dict:
        """Add a simulated (GKE-style) node; TPU nodes carry GKE TPU labels."""
        node_labels = {
            "kubernetes.io/hostname": name,
            "kubernetes.io/arch": "amd64",
            "kubernetes.io/os": "linux",
        }
        if tpu:
            node_labels[consts.GKE_TPU_ACCELERATOR_LABEL] = accelerator
            node_labels[consts.GKE_TPU_TOPOLOGY_LABEL] = topology
        node_labels.update(labels or {})
        node = {
            "apiVersion": "v1",
            "kind": "Node",
            "metadata": {"name": name, "labels": node_labels, "annotations": {}},
            "spec": {},
            "status": {
                "capacity": {"cpu": "96", "memory": "200Gi"},
                "allocatable": {"cpu": "95", "memory": "190Gi"},
                "nodeInfo": {
                    "containerRuntimeVersion": "containerd://1.7.0",
                    "kubeletVersion": "v1.29.0",
                    "osImage": "Container-Optimized OS from Google",
                    "kernelVersion": "6.1.0-gke",
                },
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }
        if tpu:
            node["metadata"]["annotations"]["tpu.google.com/sim.chips"] = str(chips)
        return self.put(node)

    # ------------------------------------------------------------------
    # HTTP server.

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/version", self._handle_version)
        app.router.add_route("*", "/api/v1/{rest:.*}", self._handle_core)
        app.router.add_route("*", "/apis/{group}/{version}/{rest:.*}", self._handle_group)
        # access_log=None: at bench scale the per-request access-log line
        # (formatted eagerly) costs more than serving the request
        self._runner = web.AppRunner(app, shutdown_timeout=1.0, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        if self.sim.enabled:
            self._sim_task = asyncio.create_task(self._simulate())
        if self.chaos is not None:
            self._chaos_task = asyncio.create_task(self._chaos_actors())
        # default namespaces
        for ns in ("default", "kube-system", "tpu-operator"):
            try:
                self.store("", "namespaces").create(
                    {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": ns}}, None
                )
            except ApiException:
                pass

    async def stop(self) -> None:
        for task in (self._sim_task, self._chaos_task, *tuple(self._exec_tasks)):
            if task:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                except Exception:  # noqa: BLE001
                    log.debug("fake-cluster task errored during stop", exc_info=True)
        if self._runner:
            await self._runner.cleanup()

    async def __aenter__(self) -> "FakeCluster":
        await self.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    # ------------------------------------------------------------------
    async def _handle_version(self, request: web.Request) -> web.Response:
        return web.json_response({"gitVersion": "v1.29.0-fake", "major": "1", "minor": "29"})

    async def _handle_core(self, request: web.Request) -> web.StreamResponse:
        return await self._dispatch(request, "", "v1", request.match_info["rest"])

    async def _handle_group(self, request: web.Request) -> web.StreamResponse:
        return await self._dispatch(
            request, request.match_info["group"], request.match_info["version"], request.match_info["rest"]
        )

    def _count_request(self, method: str, group: str, plural: str) -> None:
        key = (method, f"{group + '/' if group else ''}{plural}")
        self.request_counts[key] = self.request_counts.get(key, 0) + 1

    async def _dispatch(self, request: web.Request, group: str, version: str, rest: str) -> web.StreamResponse:
        if self.sim.api_latency:
            await asyncio.sleep(self.sim.api_latency)
        try:
            parts = [p for p in rest.split("/") if p]
            namespace: Optional[str] = None
            subresource: Optional[str] = None
            if parts and parts[0] == "namespaces" and len(parts) >= 3:
                namespace = parts[1]
                parts = parts[2:]
            elif parts and parts[0] == "namespaces" and len(parts) == 2 and group == "":
                # operations on the Namespace object itself
                self._count_request(request.method, group, "namespaces")
                fault = await self._chaos_before(request, "namespaces")
                if fault is not None:
                    return fault
                return self._chaos_after(
                    request,
                    await self._handle_object(request, self.store("", "namespaces"), None, parts[1], None),
                )
            if not parts:
                raise ApiException(404, "NotFound", "no resource")
            plural = parts[0]
            self._count_request(request.method, group, plural)
            fault = await self._chaos_before(request, plural)
            if fault is not None:
                return fault
            name = parts[1] if len(parts) > 1 else None
            if len(parts) > 2:
                subresource = parts[2]
            store = self.store(group, plural)
            if name is None:
                return self._chaos_after(
                    request, await self._handle_collection(request, store, namespace)
                )
            return self._chaos_after(
                request,
                await self._handle_object(request, store, namespace, name, subresource),
            )
        except ApiException as e:
            return e.response()
        except json.JSONDecodeError as e:
            return ApiException(400, "BadRequest", f"invalid JSON body: {e}").response()
        except Exception as e:  # noqa: BLE001
            log.exception("fake apiserver internal error")
            return ApiException(500, "InternalError", str(e)).response()

    # ------------------------------------------------------------------
    # Chaos choke points (testing/chaos.py).

    async def _chaos_before(self, request: web.Request, plural: str) -> Optional[web.StreamResponse]:
        """Pre-dispatch injection: latency spikes, hangs, connection aborts,
        and transient 429/500/503 — the request never reaches a store."""
        if self.chaos is None:
            return None
        spike = self.chaos.latency_spike()
        if spike:
            await asyncio.sleep(spike)
        fault = self.chaos.request_fault(request.method, plural)
        if fault is None:
            return None
        if fault == FAULT_HANG:
            # park until well past any sane client timeout; the client's
            # per-try deadline is what ends this request from its side
            await asyncio.sleep(self.chaos.config.hang_s)
            return ApiException(504, "Timeout", "chaos hang").response()
        if fault == FAULT_RESET:
            if request.transport is not None:
                request.transport.abort()
            return web.Response(status=500, text="chaos reset")
        if fault == FAULT_429:
            resp = ApiException(429, "TooManyRequests", "chaos throttle").response()
            resp.headers["Retry-After"] = str(self.chaos.config.retry_after_s)
            return resp
        if fault == FAULT_500:
            return ApiException(500, "InternalError", "chaos 500").response()
        return ApiException(503, "ServiceUnavailable", "chaos 503").response()

    def _chaos_after(self, request: web.Request, resp: web.StreamResponse) -> web.StreamResponse:
        """Post-commit injection: the mutation WAS applied (store updated,
        watch event emitted) but the client is answered 500 — the ambiguous
        failure whose blind replay mints duplicate objects."""
        if self.chaos is None or not self.chaos.post_commit_fault(request.method):
            return resp
        return ApiException(
            500, "InternalError", "chaos post-commit failure (mutation applied)"
        ).response()

    async def _handle_collection(
        self, request: web.Request, store: Store, namespace: Optional[str]
    ) -> web.StreamResponse:
        q = request.rel_url.query
        if request.method == "GET" and q.get("watch") in ("1", "true"):
            return await self._serve_watch(request, store, namespace)
        if request.method == "GET":
            meta: dict = {"resourceVersion": str(self._rv)}
            limit = q.get("limit", "")
            token = q.get("continue", "")
            if limit or token:
                # chunked listing is incremental END TO END: bisect to the
                # continuation key, scan forward one page, deep-copy only
                # that page.  (The first cut listed+copied the whole store
                # per page — O(pages x store) work that pinned the fake
                # apiserver at 100% CPU under 100k-node multi-replica
                # relists and starved the replicas' Lease renewals.)
                items, cont = self._paginate(
                    store, namespace,
                    q.get("labelSelector", ""), q.get("fieldSelector", ""),
                    limit, token,
                )
                if cont:
                    meta["continue"] = cont
            else:
                items = store.list(
                    namespace, q.get("labelSelector", ""), q.get("fieldSelector", "")
                )
            items = copy.deepcopy(items)
            # real-apiserver fidelity: per-item TypeMeta is omitted in LIST
            # responses (kind/apiVersion live on the List object) — consumers
            # that need it must stamp it themselves (informer ingest,
            # state/skel._list_labeled), and tests must catch them forgetting
            for item in items:
                item.pop("kind", None)
                item.pop("apiVersion", None)
            return web.json_response(
                {
                    "kind": store.info.gvk.kind + "List",
                    "apiVersion": store.info.gvk.api_version,
                    "metadata": meta,
                    "items": items,
                }
            )
        if request.method == "POST":
            body = await request.json()
            return web.json_response(store.create(body, namespace), status=201)
        if request.method == "DELETE":
            items = store.list(namespace, q.get("labelSelector", ""), q.get("fieldSelector", ""))
            for item in list(items):
                store.delete(item["metadata"].get("namespace"), item["metadata"]["name"])
            return web.json_response({"status": "Success"})
        raise ApiException(405, "MethodNotAllowed", request.method)

    def _paginate(
        self,
        store: Store,
        namespace: Optional[str],
        label_selector: str,
        field_selector: str,
        limit: str,
        token: str,
    ) -> tuple[list[dict], Optional[str]]:
        """limit/continue chunking (``Store.list_page`` does the scan).

        The continue token is opaque to clients: base64 of the snapshot rv
        + the LAST SERVED (ns, name) key — continuation is key-based, as on
        a real apiserver, so objects created or deleted between pages never
        shift the cursor (an offset-based cursor would silently skip or
        duplicate items under churn).  Expiry mirrors the watch-window rule
        — once the store's event ring has wrapped past the token's rv the
        server can no longer promise a coherent continuation and answers
        410 ``Expired`` (the etcd-compaction behaviour), which sends the
        client back to a fresh list."""
        import base64

        try:
            n = int(limit) if limit else 0
        except ValueError:
            raise ApiException(400, "BadRequest", f"invalid limit {limit!r}")

        after_key: Optional[list] = None
        if token:
            try:
                rv0, after_key = json.loads(base64.b64decode(token))
            except Exception:
                raise ApiException(400, "BadRequest", "malformed continue token")
            ring_full = len(store.events) == (store.events.maxlen or 0)
            if ring_full and store.events and rv0 < store.events[0][0]:
                raise ApiException(
                    410, "Expired",
                    "The provided continue parameter is too old",
                )
        else:
            rv0 = self._rv
        page, last_key = store.list_page(
            namespace, label_selector, field_selector, n, after_key
        )
        cont: Optional[str] = None
        if last_key is not None:
            cont = base64.b64encode(
                json.dumps([rv0, last_key]).encode()
            ).decode()
        return page, cont

    async def _handle_object(
        self,
        request: web.Request,
        store: Store,
        namespace: Optional[str],
        name: str,
        subresource: Optional[str],
    ) -> web.StreamResponse:
        status_only = subresource == "status"
        if request.method == "GET":
            return web.json_response(copy.deepcopy(store.get(namespace, name)))
        if request.method == "PUT":
            body = await request.json()
            return web.json_response(store.update(body, namespace, name, status_only=status_only))
        if request.method == "PATCH":
            body = await request.json()
            return web.json_response(store.patch(namespace, name, body, status_only=status_only))
        if request.method == "DELETE":
            self.delete_options.append((
                store.info.plural, namespace or "", name,
                request.rel_url.query.get("gracePeriodSeconds"),
            ))
            return web.json_response(store.delete(namespace, name))
        raise ApiException(405, "MethodNotAllowed", request.method)

    async def _serve_watch(
        self, request: web.Request, store: Store, namespace: Optional[str]
    ) -> web.StreamResponse:
        q = request.rel_url.query
        selector = q.get("labelSelector", "")
        rv0 = int(q.get("resourceVersion") or 0)
        # real-apiserver watch-window semantics: when the replay ring has
        # wrapped (events evicted) a client resuming from before the oldest
        # retained event CANNOT be caught up — 410 Gone, client must relist.
        # Chaos can also force the expiry to exercise the same client path.
        ring_full = len(store.events) == (store.events.maxlen or 0)
        expired = ring_full and store.events and rv0 and rv0 < store.events[0][0]
        if expired or (self.chaos is not None and self.chaos.watch_gone()):
            return ApiException(
                410, "Expired", f"resourceVersion {rv0} is too old"
            ).response()
        drop_after = self.chaos.watch_drop_after() if self.chaos is not None else None
        drop_deadline = time.monotonic() + drop_after if drop_after is not None else None
        resp = web.StreamResponse(
            status=200, headers={"Content-Type": "application/json", "Transfer-Encoding": "chunked"}
        )
        await resp.prepare(request)
        queue: asyncio.Queue = asyncio.Queue()
        parsed_sel = selectors.parse(selector) if selector else []
        # replay buffered events newer than rv0 (same per-view transition
        # synthesis as live delivery, so a resuming partitioned informer
        # still observes label-driven view moves it was disconnected for)
        for rv, evt, old_labels in list(store.events):
            if rv > rv0:
                delivery = Store._view_event(evt, old_labels, namespace, parsed_sel)
                if delivery is not None:
                    queue.put_nowait(delivery)
        store.watchers.append((queue, namespace, parsed_sel))
        try:
            while True:
                if drop_deadline is not None and time.monotonic() >= drop_deadline:
                    break  # chaos: stream dies mid-watch, client must resume
                try:
                    evt = await asyncio.wait_for(queue.get(), timeout=0.2)
                except asyncio.TimeoutError:
                    if request.transport is None or request.transport.is_closing():
                        break
                    continue
                await resp.write(json.dumps(evt).encode() + b"\n")
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        finally:
            store.watchers.remove((queue, namespace, parsed_sel))
        return resp

    # ------------------------------------------------------------------
    # Chaos background actors: crash-looping pods, NotReady node flaps.

    async def _chaos_actors(self) -> None:
        while True:
            try:
                now = time.monotonic()
                self._chaos_crashloops(now)
                self._chaos_node_flap(now)
                self._chaos_agent_health(now)
                self._chaos_pool_shock(now)
            except Exception:  # noqa: BLE001
                log.exception("chaos actor error")
            await asyncio.sleep(self.sim.tick)

    def _chaos_crashloops(self, now: float) -> None:
        """Pods matching ``pod_crashloop_selector`` flap Running → Failed
        (restartCount bumped); with ``pod_restart_after_s`` they return to
        Pending so the kubelet sim re-runs them — a true crash-loop."""
        cfg = self.chaos.config
        if not cfg.pod_crashloop_selector:
            return
        reqs = selectors.parse(cfg.pod_crashloop_selector)
        pod_store = self.store("", "pods")
        for pod in list(pod_store.objects.values()):
            labels = pod["metadata"].get("labels") or {}
            if not all(r.matches(labels) for r in reqs):
                continue
            ns = pod["metadata"].get("namespace")
            name = pod["metadata"]["name"]
            phase = deep_get(pod, "status", "phase")
            restarts = deep_get(pod, "status", "containerStatuses", 0, "restartCount", default=0)
            if phase == "Running" and self.chaos.should_crash_pod():
                self._set_pod_phase(pod_store, ns, name, "Failed", restart_count=restarts + 1)
                if cfg.pod_restart_after_s:
                    self._crash_restarts[(ns, name)] = now + cfg.pod_restart_after_s
            elif phase == "Failed" and self._crash_restarts.get((ns, name), float("inf")) <= now:
                del self._crash_restarts[(ns, name)]
                self._set_pod_phase(pod_store, ns, name, "Pending", restart_count=restarts)
                self._pod_timers[(ns, name)] = now  # kubelet sim restarts it

    def _chaos_node_flap(self, now: float) -> None:
        """Every ``node_flap_interval`` seconds one random node goes
        NotReady for ``node_flap_down_s`` then recovers — the condition
        churn that drives predicate/watch storms in the operator."""
        cfg = self.chaos.config
        if not cfg.node_flap_interval:
            return
        node_store = self.store("", "nodes")
        if self._flapped_node is not None:
            name, restore_at = self._flapped_node
            if now >= restore_at:
                self._set_node_ready(node_store, name, True)
                self._flapped_node = None
            return
        if not self.chaos.active or now - self._last_flap_at < cfg.node_flap_interval:
            return
        names = sorted(n for (_, n) in node_store.objects)
        if not names:
            return
        name = self.chaos.rng.choice(names)
        self._set_node_ready(node_store, name, False)
        self.chaos._count("node_flap")
        self._flapped_node = (name, now + cfg.node_flap_down_s)
        self._last_flap_at = now

    def _chaos_agent_health(self, now: float) -> None:
        """Every ``agent_unhealthy_interval`` seconds one random node's
        simulated node-status-exporter publishes an ``unhealthy`` verdict
        on the tpu-health label (reason code attached), recovering to
        ``ok`` after ``agent_unhealthy_down_s``.  Episodes OVERLAP — many
        simultaneous verdicts are exactly how a lying signal source
        exhausts the health engine's disruption budget."""
        cfg = self.chaos.config
        if not cfg.agent_unhealthy_interval:
            return
        for name, restore_at in list(self._unhealthy_nodes.items()):
            if now >= restore_at:
                del self._unhealthy_nodes[name]
                self.set_agent_health(name, consts.HEALTH_OK)
        if not self.chaos.active:
            return
        if now - self._last_agent_fault_at < cfg.agent_unhealthy_interval:
            return
        node_store = self.store("", "nodes")
        names = sorted(n for (_, n) in node_store.objects)
        if not names:
            return
        name = self.chaos.rng.choice(names)
        self.set_agent_health(
            name, consts.HEALTH_UNHEALTHY, cfg.agent_unhealthy_reason
        )
        self.chaos._count("agent_unhealthy")
        self._unhealthy_nodes[name] = now + cfg.agent_unhealthy_down_s
        self._last_agent_fault_at = now

    def _chaos_pool_shock(self, now: float) -> None:
        """Every ``pool_shock_interval`` seconds one whole GKE nodepool
        (rng-chosen; restricted to pools named with ``pool_shock_prefix``
        when set) publishes ``unhealthy`` agent verdicts on EVERY member
        at once — the correlated capacity loss (maintenance event, rack
        power, switch failure) that drains a multi-host slice's entire
        arc and forces the scheduler to reclaim or park, not heal one
        node — all members recover together after ``pool_shock_down_s``."""
        cfg = self.chaos.config
        if not cfg.pool_shock_interval:
            return
        for name, restore_at in list(self._shocked_pool_nodes.items()):
            if now >= restore_at:
                del self._shocked_pool_nodes[name]
                self.set_agent_health(name, consts.HEALTH_OK)
        if not self.chaos.active:
            return
        if now - self._last_pool_shock_at < cfg.pool_shock_interval:
            return
        node_store = self.store("", "nodes")
        pools: dict[str, list[str]] = {}
        for (_, name), node in sorted(node_store.objects.items()):
            labels = node["metadata"].get("labels") or {}
            pool = labels.get(consts.GKE_NODEPOOL_LABEL, "")
            if not pool or not pool.startswith(cfg.pool_shock_prefix):
                continue
            pools.setdefault(pool, []).append(name)
        if not pools:
            return
        pool = self.chaos.rng.choice(sorted(pools))
        for name in pools[pool]:
            self.set_agent_health(
                name, consts.HEALTH_UNHEALTHY, cfg.pool_shock_reason
            )
            self._shocked_pool_nodes[name] = now + cfg.pool_shock_down_s
        self.chaos._count("pool_shock")
        self._last_pool_shock_at = now

    def set_agent_health(
        self, name: str, verdict: str, reason: str = ""
    ) -> None:
        """Directly publish a node's tpu-health verdict label, the way its
        node-status-exporter would (test/soak driver)."""
        node_store = self.store("", "nodes")
        try:
            node_store.patch(None, name, {
                "metadata": {
                    "labels": {consts.TPU_HEALTH_LABEL: verdict},
                    "annotations": {
                        consts.TPU_HEALTH_REASON_ANNOTATION: reason or None,
                    },
                },
            })
        except ApiException:
            pass

    def _set_node_ready(self, node_store: Store, name: str, ready: bool) -> None:
        try:
            node = node_store.get(None, name)
        except ApiException:
            return
        patched = copy.deepcopy(node)
        conds = patched.setdefault("status", {}).setdefault("conditions", [])
        for c in conds:
            if c.get("type") == "Ready":
                c["status"] = "True" if ready else "False"
                break
        else:
            conds.append({"type": "Ready", "status": "True" if ready else "False"})
        try:
            node_store.update(patched, None, name, status_only=True)
        except ApiException:
            pass

    def steal_lease(
        self,
        namespace: str,
        name: str = consts.LEADER_ELECTION_ID,
        holder: str = "chaos-rival",
    ) -> dict:
        """Overwrite the leader lease with a rival holder and a fresh
        renewTime: the current leader's next renew sees an unexpired foreign
        lease and must step down (then re-acquire once it expires, since the
        rival never renews)."""
        store = self.store("coordination.k8s.io", "leases")
        lease = copy.deepcopy(store.get(namespace, name))
        lease["spec"]["holderIdentity"] = holder
        # microsecond renewTime: the second-truncated _now() would age the
        # stolen lease by up to 1s, letting the victim re-acquire early
        now = time.time()
        lease["spec"]["renewTime"] = (
            time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(now))
            + ".%06dZ" % int((now % 1) * 1e6)
        )
        if self.chaos is not None:
            self.chaos._count("lease_steal")
        return store.update(lease, namespace, name)

    # ------------------------------------------------------------------
    # Kubelet / controller simulators.

    async def _simulate(self) -> None:
        while True:
            try:
                self._sim_daemonsets()
                self._sim_deployments()
                await self._sim_pods()
            except Exception:  # noqa: BLE001
                log.exception("simulator error")
            await asyncio.sleep(self.sim.tick)

    def _schedulable_nodes(self, pod_spec: dict, daemonset: bool = False) -> list[dict]:
        nodes = self.store("", "nodes").list(None)
        out = []
        for node in nodes:
            labels = node["metadata"].get("labels", {})
            # DaemonSet pods tolerate node.kubernetes.io/unschedulable by
            # default (real DS controller behaviour) — cordoned nodes still
            # run operands, which the upgrade flow depends on
            if node["spec"].get("unschedulable") and not daemonset:
                continue
            ns_sel = pod_spec.get("nodeSelector") or {}
            if any(labels.get(k) != v for k, v in ns_sel.items()):
                continue
            affinity = deep_get(
                pod_spec, "affinity", "nodeAffinity",
                "requiredDuringSchedulingIgnoredDuringExecution", "nodeSelectorTerms",
            )
            if affinity and not selectors.matches_node_selector_terms(affinity, labels):
                continue
            out.append(node)
        return out

    def _sim_daemonsets(self) -> None:
        ds_store = self.store("apps", "daemonsets")
        pod_store = self.store("", "pods")
        for ds in list(ds_store.objects.values()):
            ns = ds["metadata"]["namespace"]
            ds_name = ds["metadata"]["name"]
            pod_spec = deep_get(ds, "spec", "template", "spec", default={})
            pod_labels = deep_get(ds, "spec", "template", "metadata", "labels", default={})
            nodes = self._schedulable_nodes(pod_spec, daemonset=True)
            want = {n["metadata"]["name"] for n in nodes}
            have: dict[str, dict] = {}
            for pod in list(pod_store.objects.values()):
                if pod["metadata"].get("namespace") != ns:
                    continue
                # only manage pods this sim created (validator workload pods
                # carry the DS ownerRef too — reference pattern — but are NOT
                # DaemonSet replicas and must not be adopted/reaped)
                sim_created = "tpu.google.com/sim.ds-generation" in (
                    pod["metadata"].get("annotations") or {}
                )
                if sim_created and obj_api.owned_by(pod, ds["metadata"]["uid"]):
                    have[deep_get(pod, "spec", "nodeName", default="")] = pod
            generation = str(ds["metadata"].get("generation", 1))
            for node_name in want - set(have):
                base = f"{ds_name}-{node_name}"
                if len(base) > 63:  # keep names unique under the k8s length cap
                    base = base[:54] + "-" + format(fnv1a_64(base.encode()) & 0xFFFFFFFF, "08x")
                pod = {
                    "apiVersion": "v1",
                    "kind": "Pod",
                    "metadata": {
                        "name": base,
                        "namespace": ns,
                        "labels": dict(pod_labels),
                        "annotations": {"tpu.google.com/sim.ds-generation": generation},
                    },
                    "spec": {**copy.deepcopy(pod_spec), "nodeName": node_name},
                    "status": {"phase": "Pending"},
                }
                obj_api.set_owner_reference(pod, ds)
                try:
                    created = pod_store.create(pod, ns)
                    self._pod_timers[(ns, created["metadata"]["name"])] = time.monotonic()
                except ApiException:
                    pass
            for node_name, pod in list(have.items()):
                stale = (
                    pod["metadata"].get("annotations", {}).get("tpu.google.com/sim.ds-generation")
                    != generation
                )
                if node_name not in want or stale:
                    # template changed (OnDelete/rolling sim) or node no longer
                    # matches → remove; re-created next tick from new template
                    try:
                        pod_store.delete(ns, pod["metadata"]["name"])
                    except ApiException:
                        pass
            # recompute status over sim-created replicas only
            def _is_replica(p: dict) -> bool:
                return obj_api.owned_by(p, ds["metadata"]["uid"]) and (
                    "tpu.google.com/sim.ds-generation"
                    in (p["metadata"].get("annotations") or {})
                )

            ready = sum(
                1
                for p in pod_store.objects.values()
                if _is_replica(p) and deep_get(p, "status", "phase") == "Running"
            )
            scheduled = sum(1 for p in pod_store.objects.values() if _is_replica(p))
            status = {
                "desiredNumberScheduled": len(want),
                "currentNumberScheduled": scheduled,
                "numberReady": ready,
                "numberAvailable": ready,
                "updatedNumberScheduled": scheduled,
                "numberMisscheduled": 0,
                "observedGeneration": ds["metadata"].get("generation", 1),
            }
            if ds.get("status") != status:
                patched = copy.deepcopy(ds)
                patched["status"] = status
                try:
                    ds_store.update(patched, ns, ds_name, status_only=True)
                except ApiException:
                    pass

    def _sim_deployments(self) -> None:
        dep_store = self.store("apps", "deployments")
        for dep in list(dep_store.objects.values()):
            replicas = deep_get(dep, "spec", "replicas", default=1)
            status = {
                "replicas": replicas,
                "readyReplicas": replicas,
                "availableReplicas": replicas,
                "updatedReplicas": replicas,
                "observedGeneration": dep["metadata"].get("generation", 1),
            }
            if dep.get("status") != status:
                patched = copy.deepcopy(dep)
                patched["status"] = status
                try:
                    dep_store.update(patched, dep["metadata"]["namespace"], dep["metadata"]["name"], status_only=True)
                except ApiException:
                    pass

    async def _sim_pods(self) -> None:
        pod_store = self.store("", "pods")
        now = time.monotonic()
        for pod in list(pod_store.objects.values()):
            ns = pod["metadata"]["namespace"]
            name = pod["metadata"]["name"]
            # directly-created pods (validator workloads) have no status yet
            phase = deep_get(pod, "status", "phase") or "Pending"
            key = (ns, name)
            started = self._pod_timers.setdefault(key, now)
            if phase == "Pending" and now - started >= self.sim.pod_ready_delay:
                restart_policy = deep_get(pod, "spec", "restartPolicy", default="Always")
                if restart_policy != "Always" and self.sim.pod_executor is not None:
                    if key in self._executing:
                        continue
                    self._executing.add(key)
                    self._set_pod_phase(pod_store, ns, name, "Running")
                    task = asyncio.create_task(self._execute_pod(pod_store, ns, name, pod))
                    self._exec_tasks.add(task)
                    task.add_done_callback(self._exec_tasks.discard)
                elif restart_policy != "Always":
                    self._set_pod_phase(pod_store, ns, name, "Succeeded")
                else:
                    self._set_pod_phase(pod_store, ns, name, "Running")
                    self._maybe_advertise_tpu(pod)

    async def _execute_pod(self, pod_store: Store, ns: str, name: str, pod: dict) -> None:
        """Run the pod's executor off-loop; concurrent across pods so
        multi-process workloads can rendezvous."""
        try:
            final = await asyncio.get_event_loop().run_in_executor(
                None, self.sim.pod_executor, copy.deepcopy(pod)
            )
        except Exception:  # noqa: BLE001
            log.exception("pod executor failed for %s/%s", ns, name)
            final = "Failed"
        finally:
            self._executing.discard((ns, name))
        self._set_pod_phase(pod_store, ns, name, final)

    def _set_pod_phase(
        self, pod_store: Store, ns: str, name: str, phase: str, restart_count: int = 0
    ) -> None:
        try:
            pod = pod_store.get(ns, name)
        except ApiException:
            return
        patched = copy.deepcopy(pod)
        containers = deep_get(pod, "spec", "containers", default=[]) or [{"name": "main"}]
        patched["status"] = {
            "phase": phase,
            "conditions": [{"type": "Ready", "status": "True" if phase == "Running" else "False"}],
            "containerStatuses": [
                {
                    "name": c.get("name", "main"),
                    "ready": phase == "Running",
                    "restartCount": restart_count,
                }
                for c in containers
            ],
        }
        try:
            pod_store.update(patched, ns, name, status_only=True)
        except ApiException:
            pass

    def _maybe_advertise_tpu(self, pod: dict) -> None:
        """When a device-plugin DS pod goes Ready on a TPU node, simulate the
        kubelet picking up the plugin registration: node advertises
        google.com/tpu capacity/allocatable."""
        labels = pod["metadata"].get("labels", {})
        if labels.get("app") != "tpu-device-plugin":
            return
        node_name = deep_get(pod, "spec", "nodeName")
        if not node_name:
            return
        node_store = self.store("", "nodes")
        try:
            node = node_store.get(None, node_name)
        except ApiException:
            return
        chips = node["metadata"].get("annotations", {}).get("tpu.google.com/sim.chips", "4")
        patched = copy.deepcopy(node)
        patched["status"].setdefault("capacity", {})[consts.TPU_RESOURCE] = chips
        patched["status"].setdefault("allocatable", {})[consts.TPU_RESOURCE] = chips
        if patched["status"] != node["status"]:
            try:
                node_store.update(patched, None, node_name, status_only=True)
            except ApiException:
                pass
