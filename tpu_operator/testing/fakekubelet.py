"""Fake kubelet: the Registration gRPC server + device-plugin client.

Lets tests drive the full device-plugin protocol — registration over the
kubelet socket, ListAndWatch streaming, Allocate — without a real kubelet.
"""

from __future__ import annotations

import asyncio
import os
from typing import Optional

import grpc.aio

from tpu_operator.deviceplugin import api_pb2, rpc


class FakeKubelet:
    def __init__(self, plugin_dir: str):
        self.plugin_dir = plugin_dir
        self.registrations: list[api_pb2.RegisterRequest] = []
        self.registered = asyncio.Event()
        self._server: Optional[grpc.aio.Server] = None

    @property
    def socket_path(self) -> str:
        return os.path.join(self.plugin_dir, "kubelet.sock")

    async def Register(self, request: api_pb2.RegisterRequest, context) -> api_pb2.Empty:
        self.registrations.append(request)
        self.registered.set()
        return api_pb2.Empty()

    async def start(self) -> None:
        os.makedirs(self.plugin_dir, exist_ok=True)
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((rpc.registration_handler(self),))
        self._server.add_insecure_port(f"unix://{self.socket_path}")
        await self._server.start()

    async def stop(self) -> None:
        if self._server:
            await self._server.stop(grace=0.5)

    def plugin_channel(self, endpoint: str) -> grpc.aio.Channel:
        return grpc.aio.insecure_channel(f"unix://{os.path.join(self.plugin_dir, endpoint)}")

    async def __aenter__(self) -> "FakeKubelet":
        await self.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.stop()
