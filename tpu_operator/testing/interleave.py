"""Seeded deterministic event-loop shim: `go test -race` for asyncio.

The operator's concurrency bugs live in *scheduling order*: two coroutines
both ready, and the loop's FIFO happens to run them in the order that
hides the lost update.  Production hits the other order at 3am.  This
module makes that order an *input*: :class:`InterleavingEventLoop` is a
standard selector loop whose ready queue is shuffled by a seeded RNG
before every batch, so one test body runs under hundreds of distinct —
but perfectly reproducible — task interleavings.

Static twin: the ``async-race`` and ``fence-coverage`` analysis rules
(docs/STATIC_ANALYSIS.md) prove the *shape* of the code; this harness
executes the schedules those shapes are vulnerable to.  ``make race``
drives the workqueue dirty-set, plane-handoff, and migration-coordinator
invariant suites (tests/test_race.py) across ≥200 seeds.

Usage::

    async def scenario():
        ...build objects, spawn coroutines, assert invariants...

    run_interleaved(scenario, seed=7)           # one schedule
    report = sweep(scenario, seeds=range(200))  # the acceptance sweep
    assert not report.failures, report.summary()

Determinism contract: the scenario must not branch on wall-clock time or
its own ``random`` module state (use the loop's seed); timer *deadlines*
are honored normally — only the order of same-batch ready callbacks is
permuted, which is exactly the freedom a production loop has.
"""

from __future__ import annotations

import asyncio
import random
import selectors
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Iterable, Optional

Scenario = Callable[[], Awaitable]


class InterleavingEventLoop(asyncio.SelectorEventLoop):
    """Selector loop that permutes the ready-callback batch per iteration.

    ``_run_once`` drains ``self._ready`` FIFO; shuffling the deque right
    before each drain explores a different legal schedule while keeping
    every callback exactly-once.  ``permutations`` counts the batches that
    actually had >1 runnable callback — a scenario that never exceeds one
    runnable at a time has no schedule freedom to explore, and its sweep
    proves nothing (assert on ``permutations`` in the test)."""

    def __init__(self, seed: int):
        super().__init__(selectors.DefaultSelector())
        self.seed = seed
        self._rng = random.Random(seed)
        self.permutations = 0

    def _run_once(self) -> None:  # noqa: D401 — BaseEventLoop hook
        ready = getattr(self, "_ready", None)
        if ready is not None and len(ready) > 1:
            batch = list(ready)
            ready.clear()
            self._rng.shuffle(batch)
            ready.extend(batch)
            self.permutations += 1
        super()._run_once()


@dataclass
class Failure:
    seed: int
    error: BaseException


@dataclass
class SweepReport:
    seeds_run: int = 0
    total_permutations: int = 0
    failures: list[Failure] = field(default_factory=list)

    def summary(self) -> str:
        lines = [
            f"{self.seeds_run} seeds, {self.total_permutations} permuted "
            f"batches, {len(self.failures)} failing schedule(s)"
        ]
        for f in self.failures[:10]:
            lines.append(f"  seed {f.seed}: {type(f.error).__name__}: {f.error}")
        return "\n".join(lines)


def run_interleaved(
    scenario: Scenario, seed: int, timeout: float = 30.0
) -> tuple[object, int]:
    """Run one scenario under one seeded schedule.  Returns
    ``(result, permutations)``; re-raises whatever the scenario raises
    (an invariant violation surfaces as its assertion)."""
    loop = InterleavingEventLoop(seed)
    try:
        asyncio.set_event_loop(loop)
        result = loop.run_until_complete(
            asyncio.wait_for(scenario(), timeout)
        )
        return result, loop.permutations
    finally:
        asyncio.set_event_loop(None)
        # drain cancellations so nothing leaks across seeds
        try:
            pending = asyncio.all_tasks(loop)
            for task in pending:
                task.cancel()
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
        finally:
            loop.close()


def sweep(
    scenario: Scenario,
    seeds: Iterable[int],
    timeout: float = 30.0,
    stop_after: Optional[int] = None,
) -> SweepReport:
    """Run the scenario across many seeds, collecting failures instead of
    stopping at the first (a race that fires on 3 of 200 schedules should
    report all three seeds for replay)."""
    report = SweepReport()
    for seed in seeds:
        report.seeds_run += 1
        try:
            _, permutations = run_interleaved(scenario, seed, timeout=timeout)
            report.total_permutations += permutations
        except (KeyboardInterrupt, SystemExit):
            raise  # an operator interrupt is not a racing schedule
        except BaseException as e:  # noqa: BLE001 — collected, not hidden
            report.failures.append(Failure(seed, e))
            if stop_after is not None and len(report.failures) >= stop_after:
                break
    return report
