"""Small shared helpers.

Reference analogue: internal/utils/utils.go (GetObjectHash :66-78 — FNV-1a over
a deterministic dump; GetFilesWithSuffix :33-58).
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Any, Awaitable, Iterable, Iterator

FNV1A_64_OFFSET = 0xCBF29CE484222325
FNV1A_64_PRIME = 0x100000001B3


def fnv1a_64(data: bytes) -> int:
    h = FNV1A_64_OFFSET
    for b in data:
        h ^= b
        h = (h * FNV1A_64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def object_hash(obj: Any) -> str:
    """Deterministic content hash of a JSON-serialisable object.

    Used for the last-applied-hash annotation that lets states skip no-op
    updates (getDaemonsetHash, controllers/object_controls.go:4173).
    """
    dumped = json.dumps(obj, sort_keys=True, separators=(",", ":"), default=str)
    return format(fnv1a_64(dumped.encode()), "x")


async def bounded_gather(aws: Iterable[Awaitable], limit: int = 8) -> list:
    """``asyncio.gather`` under a concurrency bound, results in input order.

    Unlike bare gather with ``return_exceptions=False``, every task is
    awaited to completion even when one fails (no orphaned coroutines
    racing teardown); the first exception is re-raised afterwards.
    """
    sem = asyncio.Semaphore(max(1, limit))

    async def _run(aw: Awaitable):
        async with sem:
            return await aw

    aws = list(aws)
    try:
        results = await asyncio.gather(*(_run(aw) for aw in aws), return_exceptions=True)
    finally:
        # a hard cancel can kill wrapper tasks before they ever run; close
        # any coroutine that never started or it warns at GC (no-op for
        # finished ones, RuntimeError for the mid-await ones we must skip)
        for aw in aws:
            close = getattr(aw, "close", None)
            if close is not None:
                try:
                    close()
                except RuntimeError:
                    pass
    for r in results:
        if isinstance(r, BaseException):
            raise r
    return results


def files_with_suffix(root: str, *suffixes: str) -> list[str]:
    """Sorted file paths under ``root`` ending with any suffix (recursive)."""
    out: list[str] = []
    for dirpath, _, filenames in os.walk(root):
        for name in filenames:
            if name.endswith(tuple(suffixes)):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def deep_get(obj: Any, *path: str | int, default: Any = None) -> Any:
    """Traverse nested dicts/lists; return ``default`` on any miss."""
    cur = obj
    for key in path:
        try:
            if isinstance(key, int):
                cur = cur[key]
            else:
                cur = cur.get(key)  # type: ignore[union-attr]
        except (TypeError, AttributeError, IndexError, KeyError):
            return default
        if cur is None:
            return default
    return cur


def deep_set(obj: dict, value: Any, *path: str) -> None:
    """Set a nested dict value, creating intermediate dicts."""
    cur = obj
    for key in path[:-1]:
        cur = cur.setdefault(key, {})
    cur[path[-1]] = value


def merge_env(env_list: list[dict], name: str, value: str) -> None:
    """Set/replace an entry in a k8s container ``env`` list in place.

    Reference analogue: setContainerEnv (controllers/object_controls.go:2170).
    """
    for item in env_list:
        if item.get("name") == name:
            item["value"] = value
            return
    env_list.append({"name": name, "value": value})


def chunked(it: Iterable, n: int) -> Iterator[list]:
    buf: list = []
    for x in it:
        buf.append(x)
        if len(buf) == n:
            yield buf
            buf = []
    if buf:
        yield buf


def parse_topology(topology: str) -> tuple[int, ...]:
    """Parse an ICI topology string like ``2x4`` or ``4x4x4`` into dims."""
    try:
        dims = tuple(int(d) for d in topology.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"invalid topology {topology!r}") from e
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"invalid topology {topology!r}")
    return dims


def topology_chips(topology: str) -> int:
    n = 1
    for d in parse_topology(topology):
        n *= d
    return n
