"""tpu-validator: node-level validation harness.

Reference analogue: ``validator/`` (the nvidia-validator binary, 1,911 LoC)
— per-component validations writing status files under /run/tpu/validations
that operand init containers gate on, plus workload-pod spawning and a node
metrics mode.
"""
