"""tpu-validator CLI.

Reference analogue: validator/main.go:212-336 (urfave/cli flag surface) and
start() dispatch (:450-565).  Runs as operand init containers:

  python -m tpu_operator.validator.cli --component pjrt
  python -m tpu_operator.validator.cli --component runtime-prep --wait-only
  python -m tpu_operator.validator.cli --cleanup-all
  python -m tpu_operator.validator.cli --component metrics --metrics-port 8000
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import sys
from typing import Optional

from tpu_operator import consts
from tpu_operator.obs import events as obs_events
from tpu_operator.obs import logging as obs_logging
from tpu_operator.obs.trace import TraceContext, Tracer
from tpu_operator.validator import status
from tpu_operator.validator.components import ValidationError, Validator, ValidatorConfig


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser("tpu-validator")
    p.add_argument("--component", "-c", default="",
                   help="libtpu|pjrt|plugin|jax|perf|vfio-pci|metrics (or any name with --wait-only)")
    p.add_argument("--node-name", "-n", default=None)
    p.add_argument("--namespace", default=None)
    p.add_argument("--wait-only", action="store_true",
                   help="wait for <component>-ready instead of validating")
    p.add_argument("--with-workload", action="store_true", default=None)
    p.add_argument("--cleanup-all", action="store_true")
    p.add_argument("--sleep-interval-seconds", type=float, default=consts.VALIDATOR_SLEEP_SECONDS)
    p.add_argument("--workload-retries", type=int, default=consts.VALIDATOR_WORKLOAD_RETRIES)
    p.add_argument("--resource-retries", type=int, default=consts.VALIDATOR_RESOURCE_RETRIES)
    p.add_argument("--metrics-port", type=int, default=8000)
    p.add_argument("--oneshot", action="store_true", help="metrics: one scrape pass then exit")
    p.add_argument(
        "--log-format",
        choices=(obs_logging.FORMAT_TEXT, obs_logging.FORMAT_JSON),
        default=os.environ.get(consts.LOG_FORMAT_ENV, obs_logging.FORMAT_TEXT),
    )
    return p.parse_args(argv)


async def run(args: argparse.Namespace) -> int:
    obs_logging.setup(args.log_format)
    log = logging.getLogger("tpu-validator")

    if args.cleanup_all:
        removed = status.cleanup_all()
        log.info("removed %d status files", removed)
        return 0

    if not args.component:
        log.error("--component required")
        return 2

    config = ValidatorConfig(
        sleep_interval=args.sleep_interval_seconds,
        workload_retries=args.workload_retries,
        resource_retries=args.resource_retries,
    )
    if args.node_name is not None:
        config.node_name = args.node_name
    if args.namespace is not None:
        config.namespace = args.namespace
    if args.with_workload is not None:
        config.with_workload = args.with_workload

    if args.component == "metrics":
        from tpu_operator.validator.metrics import serve_metrics

        await serve_metrics(args.metrics_port, oneshot=args.oneshot,
                            interval=args.sleep_interval_seconds)
        return 0

    validator = Validator(config)
    # ambient tracer: component phases feed span durations even standalone.
    # The operator stamps TPU_TRACEPARENT into the validator DS env — the
    # adopted context makes these phase spans (and every flight sample
    # under them) part of the operator's rollout trace instead of an
    # unlinked local one; absent env degrades to a standalone trace.
    tracer = Tracer()
    try:
        with tracer.adopt(TraceContext.from_env()):
            if args.wait_only:
                await validator.wait_ready(args.component)
                log.info("%s-ready present", args.component)
            else:
                await validator.run(args.component)
                log.info("%s validation succeeded", args.component)
        return 0
    except ValidationError as e:
        log.error("%s validation failed: %s", args.component, e)
        # gate failure -> Warning Event on the node (best-effort: the
        # recorder never raises, and a client may not even exist for
        # node-local-only components)
        if validator._client is not None and config.node_name:
            recorder = obs_events.EventRecorder(
                validator._client, config.namespace, component="tpu-validator"
            )
            await recorder.warning(
                obs_events.node_ref(config.node_name),
                obs_events.REASON_VALIDATION_FAILED,
                f"{args.component} validation failed: {e}",
            )
        return 1
    finally:
        if validator._client is not None:
            await validator._client.close()


def main(argv: Optional[list] = None) -> int:
    return asyncio.run(run(parse_args(argv)))


if __name__ == "__main__":
    sys.exit(main())
