"""Validation component implementations.

Reference analogue: validator/main.go:450-1302 — component dispatch (:450-565),
driver chroot probe → status file (:606-689), plugin resource polling
(:1115-1135), workload-pod spawning with ownerRef/toleration copying
(:941-1052), CUDA workload (:1189-1302).

TPU chain (re-derived, SURVEY §7 hard part 3):
  libtpu   — wait for the runtime container marker, probe libtpu.so + /dev/accel*
  pjrt     — initialize a PJRT client (the nvidia-smi analogue: no smi tool on
             TPU hosts; a live XLA client is the root health proof)
  plugin   — poll node allocatable google.com/tpu; optionally run a 1-chip
             vector-add workload pod through the scheduler
  jax      — the collective gate: allreduce + sharded burn-in over all local
             chips, in-process or as a spawned pod (WITH_WORKLOAD)
  vfio-pci — passthrough chain: vfio group device nodes present
"""

from __future__ import annotations

import asyncio
import copy
import json
import logging
import os
from dataclasses import dataclass, field
from typing import Optional

from tpu_operator import consts, hw
from tpu_operator.k8s.client import ApiClient, ApiError
from tpu_operator.utils import deep_get
from tpu_operator.validator import status

log = logging.getLogger("tpu_operator.validator")

LIBTPU_CTR_MARKER = ".libtpu-ctr-ready"
COORDINATOR_PORT = 8476  # jax.distributed coordinator (worker 0's pod)


@dataclass
class ValidatorConfig:
    node_name: str = field(default_factory=lambda: os.environ.get("NODE_NAME", ""))
    namespace: str = field(
        default_factory=lambda: os.environ.get(consts.OPERATOR_NAMESPACE_ENV, "tpu-operator")
    )
    sleep_interval: float = consts.VALIDATOR_SLEEP_SECONDS
    workload_retries: int = consts.VALIDATOR_WORKLOAD_RETRIES
    resource_retries: int = consts.VALIDATOR_RESOURCE_RETRIES
    with_workload: bool = field(
        default_factory=lambda: os.environ.get("WITH_WORKLOAD", "").lower() in ("1", "true")
    )
    workload_image: str = field(default_factory=lambda: os.environ.get("WORKLOAD_IMAGE", ""))
    # jax platform the PJRT probe asks for; cpu in tests
    platform: str = field(default_factory=lambda: os.environ.get("TPU_VALIDATOR_PLATFORM", "tpu"))


class ValidationError(Exception):
    pass


class Validator:
    COMPONENTS = ("libtpu", "pjrt", "plugin", "jax", "vfio-pci")

    def __init__(self, config: Optional[ValidatorConfig] = None, client: Optional[ApiClient] = None):
        self.config = config or ValidatorConfig()
        self._client = client

    def client(self) -> ApiClient:
        if self._client is None:
            from tpu_operator.k8s.client import Config

            self._client = ApiClient(Config.from_env())
        return self._client

    # ------------------------------------------------------------------
    async def run(self, component: str) -> None:
        """Run one validation; raises ValidationError on failure."""
        handler = {
            "libtpu": self.validate_libtpu,
            "pjrt": self.validate_pjrt,
            "plugin": self.validate_plugin,
            "jax": self.validate_jax,
            "vfio-pci": self.validate_vfio,
        }.get(component)
        if handler is None:
            raise ValidationError(f"invalid component {component!r}; one of {self.COMPONENTS}")
        status.clear(component)
        await handler()

    async def wait_ready(self, component: str, retries: Optional[int] = None) -> None:
        """--wait-only: block until another pod's validation wrote the file
        (device-plugin init gate pattern)."""
        retries = retries if retries is not None else self.config.workload_retries
        for _ in range(retries):
            if status.is_ready(component):
                return
            await asyncio.sleep(self.config.sleep_interval)
        raise ValidationError(f"timed out waiting for {component}-ready")

    # ------------------------------------------------------------------
    async def validate_libtpu(self) -> None:
        """Wait for the runtime container, then probe host truth."""
        host_managed = False
        for _ in range(self.config.resource_retries):
            if status.marker_exists(LIBTPU_CTR_MARKER):
                break
            if hw.libtpu_path():
                # no operator-managed runtime container but libtpu is on the
                # host → host-managed runtime (host-driver-ready analogue)
                host_managed = True
                break
            await asyncio.sleep(self.config.sleep_interval)
        else:
            raise ValidationError("tpu runtime container never became ready")
        libtpu = hw.libtpu_path()
        if not libtpu:
            raise ValidationError("libtpu.so not found on host")
        chips = hw.chip_count()
        if chips <= 0:
            raise ValidationError("no /dev/accel* TPU device nodes")
        status.write_ready(
            "libtpu", {"libtpu_path": libtpu, "chips": chips, "host_managed": host_managed}
        )

    async def validate_pjrt(self) -> None:
        """PJRT client init — the nvidia-smi analogue."""
        await self.wait_ready("libtpu", retries=self.config.resource_retries)

        def probe() -> dict:
            import jax

            devices = jax.devices(self.config.platform)
            if not devices:
                raise ValidationError(f"PJRT reports no {self.config.platform} devices")
            return {
                "platform": self.config.platform,
                "device_count": len(devices),
                "device_kind": getattr(devices[0], "device_kind", ""),
            }

        payload = await asyncio.get_event_loop().run_in_executor(None, probe)
        status.write_ready("pjrt", payload)

    async def validate_plugin(self) -> None:
        """Node advertises google.com/tpu (validateGPUResource analogue)."""
        if not self.config.node_name:
            raise ValidationError("NODE_NAME required for plugin validation")
        client = self.client()
        for _ in range(self.config.resource_retries):
            node = await client.get("", "Node", self.config.node_name)
            alloc = deep_get(node, "status", "allocatable", default={}) or {}
            try:
                count = int(alloc.get(consts.TPU_RESOURCE, "0"))
            except ValueError:
                count = 0
            if count > 0:
                if self.config.with_workload:
                    await self.spawn_workload(
                        "tpu-plugin-workload-validation", checks="vector-add", tpu_request=1
                    )
                status.write_ready("plugin", {"allocatable": count})
                return
            await asyncio.sleep(self.config.sleep_interval)
        raise ValidationError(f"node {self.config.node_name} never advertised {consts.TPU_RESOURCE}")

    async def validate_jax(self) -> None:
        """The collective gate: allreduce + burn-in over all local chips —
        or, on a multi-host slice, ONE jax.distributed program across every
        host of the slice (SURVEY §7 hard parts 1 & 3: slice health is a set
        property; no reference analogue, GPU validation is node-local)."""
        await self.wait_ready("plugin", retries=self.config.resource_retries)
        if self.config.with_workload:
            group = await self._slice_group()
            if group is not None:
                await self.validate_jax_multihost(*group)
                return
            chips = await self._node_chip_count()
            await self.spawn_workload(
                "tpu-jax-workload-validation",
                checks="vector-add,allreduce,burn-in",
                tpu_request=chips,
            )
            status.write_ready("jax", {"mode": "workload-pod", "chips": chips})
            return

        def run_checks() -> dict:
            from tpu_operator.workloads import collectives

            results = {
                "vector-add": collectives.vector_add(1 << 16),
                "allreduce": collectives.allreduce_benchmark(size_mb=4, iters=3, warmup=1),
            }
            for name, r in results.items():
                if not r.get("ok"):
                    raise ValidationError(f"jax check {name} failed: {r}")
            return {
                "mode": "in-process",
                "devices": results["allreduce"]["devices"],
                "algbw_gbps": results["allreduce"]["algbw_gbps"],
            }

        payload = await asyncio.get_event_loop().run_in_executor(None, run_checks)
        status.write_ready("jax", payload)

    # ------------------------------------------------------------------
    # Multi-host slice validation (jax.distributed-coordinated worker pods).

    async def _slice_group(self) -> Optional[tuple[str, list[dict]]]:
        """(group_key, ordered member nodes) when this node belongs to a
        multi-host slice; None on single-host nodes.  Membership = same GKE
        nodepool (one multi-host slice per node pool); ordering = worker id
        (TFD / GKE label)."""
        from tpu_operator.controllers.labels import slice_group_key
        from tpu_operator.k8s import nodeinfo

        if not self.config.node_name:
            return None
        client = self.client()
        node = await client.get("", "Node", self.config.node_name)
        key = slice_group_key(node)
        if key is None:
            return None
        members = (
            nodeinfo.NodeFilter()
            .tpu()
            .eq(consts.GKE_NODEPOOL_LABEL, key)
            .apply(await client.list_items("", "Node"))
        )
        members.sort(key=lambda n: int(nodeinfo.attributes(n).worker_id or "0"))
        expected = max(nodeinfo.slice_hosts(m) for m in members)
        if len(members) < expected:
            raise ValidationError(
                f"slice {key}: only {len(members)}/{expected} hosts present"
            )
        return key, members

    def _group_pod_name(self, key: str, worker_id: int) -> str:
        from tpu_operator.state.nodepool import hashed_name

        return hashed_name("tpu-jax-validation", f"{key}-w{worker_id}")

    def _group_service_name(self, key: str) -> str:
        from tpu_operator.state.nodepool import hashed_name

        return hashed_name("tpu-jax-validation", key)

    async def validate_jax_multihost(self, key: str, members: list[dict]) -> None:
        """One global collective across every host of the slice.

        Worker 0's validator creates the coordination resources — a headless
        Service plus one workload pod per slice host, each pinned to its
        node and running ``workloads.distributed`` with
        jax.distributed.initialize(coordinator=worker-0-pod DNS) — then every
        host's validator (including 0) gates its own ``jax-ready`` on ITS
        pod succeeding, which can only happen if the GLOBAL psum + burn-in
        passed on all hosts (any missing worker fails the whole rendezvous).
        Reference pattern: workload-pod spawning of validator/main.go:941-1052,
        lifted from one pod to a coordinated set."""
        from tpu_operator.k8s import nodeinfo

        my_attrs = next(
            nodeinfo.attributes(m)
            for m in members
            if m["metadata"]["name"] == self.config.node_name
        )
        my_id = int(my_attrs.worker_id or "0")
        svc = self._group_service_name(key)
        coordinator = (
            f"{self._group_pod_name(key, 0)}.{svc}."
            f"{self.config.namespace}.svc:{COORDINATOR_PORT}"
        )
        if my_id == 0:
            await self._create_group_workloads(key, members, svc, coordinator)

        # gate on THIS host's pod (per-host evidence; global success implied)
        name = self._group_pod_name(key, my_id)
        client = self.client()
        phase = None
        for _ in range(self.config.workload_retries):
            try:
                live = await client.get("", "Pod", name, self.config.namespace)
            except ApiError as e:
                if not e.not_found:
                    raise
                # worker 0 may not have created the set yet
                await asyncio.sleep(self.config.sleep_interval)
                continue
            phase = deep_get(live, "status", "phase")
            if phase == "Succeeded":
                status.write_ready(
                    "jax",
                    {
                        "mode": "multi-host",
                        "group": key,
                        "workers": len(members),
                        "worker_id": my_id,
                    },
                )
                return
            if phase == "Failed":
                raise ValidationError(
                    f"distributed validation pod {name} failed (slice {key})"
                )
            await asyncio.sleep(self.config.sleep_interval)
        raise ValidationError(
            f"distributed validation pod {name} did not complete (phase={phase})"
        )

    async def _create_group_workloads(
        self, key: str, members: list[dict], svc: str, coordinator: str
    ) -> None:
        """Worker 0 only: headless Service + one pinned pod per slice host."""
        from tpu_operator.k8s import nodeinfo

        client = self.client()
        owner = await self._owner_daemonset()
        service = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": svc,
                "namespace": self.config.namespace,
                "labels": {"app": "tpu-jax-validation", "tpu.google.com/slice-group": svc},
            },
            "spec": {
                "clusterIP": "None",  # headless: per-pod DNS for the rendezvous
                "selector": {"tpu.google.com/slice-group": svc},
                "ports": [{"port": COORDINATOR_PORT, "name": "coordinator"}],
            },
        }
        if owner is not None:
            from tpu_operator.k8s import objects as obj_api

            obj_api.set_owner_reference(service, owner)
        try:
            await client.create(service)
        except ApiError as e:
            if not e.conflict:
                raise
        for member in members:
            attrs = nodeinfo.attributes(member)
            wid = int(attrs.worker_id or "0")
            name = self._group_pod_name(key, wid)
            pod = self._workload_pod(
                name, checks="", tpu_request=max(1, attrs.chips_per_host), owner=owner
            )
            pod["metadata"]["labels"]["tpu.google.com/slice-group"] = svc
            spec = pod["spec"]
            spec["nodeName"] = attrs.name
            # per-pod DNS record under the headless Service
            spec["hostname"] = name
            spec["subdomain"] = svc
            container = spec["containers"][0]
            container["command"] = ["python", "-m", "tpu_operator.workloads.distributed"]
            container["env"] = [
                {"name": "COORDINATOR_ADDRESS", "value": coordinator},
                {"name": "NUM_PROCESSES", "value": str(len(members))},
                {"name": "PROCESS_ID", "value": str(wid)},
            ]
            await client.delete("", "Pod", name, self.config.namespace)
            await client.create(pod)

    async def validate_vfio(self) -> None:
        devices = hw.vfio_device_paths()
        if not devices:
            raise ValidationError("no /dev/vfio group devices bound")
        status.write_ready("vfio-pci", {"devices": devices})

    # ------------------------------------------------------------------
    async def _node_chip_count(self) -> int:
        node = await self.client().get("", "Node", self.config.node_name)
        alloc = deep_get(node, "status", "allocatable", default={}) or {}
        try:
            return max(1, int(alloc.get(consts.TPU_RESOURCE, "1")))
        except ValueError:
            return 1

    async def _owner_daemonset(self) -> Optional[dict]:
        try:
            return await self.client().get(
                "apps", "DaemonSet", "tpu-operator-validator", self.config.namespace
            )
        except ApiError:
            return None

    def _workload_pod(self, name: str, checks: str, tpu_request: int, owner: Optional[dict]) -> dict:
        """Build the workload pod (plugin-workload-validation.yaml analogue,
        validator/main.go:984-1052: node pinning, resource request, ownerRef
        + tolerations copied from the validator DaemonSet)."""
        image = self.config.workload_image or "ghcr.io/tpu-operator/tpu-validator:latest"
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "namespace": self.config.namespace,
                "labels": {"app": name},
            },
            "spec": {
                "nodeName": self.config.node_name,
                "restartPolicy": "Never",
                "containers": [
                    {
                        "name": "workload",
                        "image": image,
                        "command": ["python", "-m", "tpu_operator.workloads.run_validation"],
                        "env": [
                            {"name": "WORKLOAD_CHECKS", "value": checks},
                            {
                                "name": "ALLREDUCE_MIN_GBPS",
                                "value": os.environ.get("ALLREDUCE_MIN_GBPS", "0"),
                            },
                        ],
                        "resources": {
                            "limits": {consts.TPU_RESOURCE: str(tpu_request)},
                            "requests": {consts.TPU_RESOURCE: str(tpu_request)},
                        },
                    }
                ],
            },
        }
        if owner is not None:
            from tpu_operator.k8s import objects as obj_api

            obj_api.set_owner_reference(pod, owner)
            tolerations = deep_get(owner, "spec", "template", "spec", "tolerations")
            if tolerations:
                pod["spec"]["tolerations"] = copy.deepcopy(tolerations)
        return pod

    async def spawn_workload(self, name: str, checks: str, tpu_request: int) -> None:
        client = self.client()
        owner = await self._owner_daemonset()
        pod = self._workload_pod(name, checks, tpu_request, owner)
        await client.delete("", "Pod", name, self.config.namespace)
        await client.create(pod)
        for _ in range(self.config.workload_retries):
            live = await client.get("", "Pod", name, self.config.namespace)
            phase = deep_get(live, "status", "phase")
            if phase == "Succeeded":
                return
            if phase == "Failed":
                raise ValidationError(f"workload pod {name} failed")
            await asyncio.sleep(self.config.sleep_interval)
        raise ValidationError(f"workload pod {name} did not complete (phase={phase})")
