"""Validation component implementations.

Reference analogue: validator/main.go:450-1302 — component dispatch (:450-565),
driver chroot probe → status file (:606-689), plugin resource polling
(:1115-1135), workload-pod spawning with ownerRef/toleration copying
(:941-1052), CUDA workload (:1189-1302).

TPU chain (re-derived, SURVEY §7 hard part 3):
  libtpu   — wait for the runtime container marker, probe libtpu.so + /dev/accel*
  pjrt     — initialize a PJRT client (the nvidia-smi analogue: no smi tool on
             TPU hosts; a live XLA client is the root health proof)
  plugin   — poll node allocatable google.com/tpu; optionally run a 1-chip
             vector-add workload pod through the scheduler
  jax      — the collective gate: allreduce + sharded burn-in over all local
             chips, in-process or as a spawned pod (WITH_WORKLOAD)
  vfio-pci — passthrough chain: vfio group device nodes present
"""

from __future__ import annotations

import asyncio
import copy
import functools
import hashlib
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Optional

from tpu_operator import consts, hw
from tpu_operator.k8s.client import ApiClient, ApiError
from tpu_operator.obs import trace
from tpu_operator.utils import deep_get
from tpu_operator.validator import status

log = logging.getLogger("tpu_operator.validator")

LIBTPU_CTR_MARKER = ".libtpu-ctr-ready"
COORDINATOR_PORT = 8476  # jax.distributed coordinator (worker 0's pod)
EPOCH_LABEL = "tpu.google.com/validation-epoch"
# node-local persistent XLA compilation cache shared by all validation
# workload pods on a host (see workloads/compile_cache.py)
COMPILE_CACHE_HOST_PATH = consts.COMPILE_CACHE_DIR
# distinct name base for cross-slice rendezvous resources: a nodepool whose
# name happens to match a prefixed group key must never share Service/pod
# names (and thus epoch tombstones) with the multislice rendezvous
MULTISLICE_BASE = "tpu-ms-validation"
VALIDATED_EPOCH_ANNOTATION = "tpu.google.com/validated-epoch"

# Fraction of the generation's published per-chip ICI bandwidth
# (k8s/nodeinfo.py ACCELERATORS.ici_gbps) a validation allreduce's busbw
# must reach: conservative enough for small validation buffers and mixed
# topologies, tight enough that a degraded link (which halves or worse the
# ring's steady-state rate) fails the slice instead of passing at any speed.
ALLREDUCE_GATE_FRACTION = 0.25

# Fraction of the generation's per-LINK ICI bandwidth the ring diagnostic's
# slowest hop must reach.  Deliberately derived from ici_link_gbps
# (aggregate / torus degree), NEVER the aggregate: a single healthy link
# runs at aggregate/links, which can sit at or below the multi-link
# allreduce floor (ADVICE r03 — the old alert compared per-link rates to
# the aggregate-derived floor and would fire chronically on v4).
RING_GATE_FRACTION = 0.25


def _env_floor(env_var: str, fallback) -> float:
    """The one bandwidth-floor resolution rule: an explicit env override
    (operator-injected) wins — including an explicit 0, which keeps the
    gate report-only; malformed values log and fall through to the
    ``fallback`` derivation rather than crash the validation loop."""
    env = os.environ.get(env_var, "")
    if env != "":
        try:
            return max(0.0, float(env))
        except ValueError:
            log.warning("ignoring malformed %s=%r", env_var, env)
    return fallback()


def _ring_min_gbps(generation: str) -> float:
    """The per-link ring floor for this chip generation, from the
    catalogue's per-link bandwidth (aggregate / torus degree)."""
    from tpu_operator.k8s.nodeinfo import generation_info

    return _env_floor(
        "RING_MIN_GBPS",
        lambda: round(generation_info(generation).ici_link_gbps * RING_GATE_FRACTION, 1),
    )


def _allreduce_min_gbps(generation: str) -> float:
    """The armed ICI gate for this chip generation — the BASELINE
    'expected ICI GB/s for slice shape' metric, from the accelerator
    catalogue (it previously defaulted to 0 and gated nothing)."""
    from tpu_operator.k8s.nodeinfo import generation_info

    return _env_floor(
        "ALLREDUCE_MIN_GBPS",
        lambda: round(generation_info(generation).ici_gbps * ALLREDUCE_GATE_FRACTION, 1),
    )


# Fraction of the generation's host NIC line rate a cross-slice allreduce's
# busbw must reach.  Deliberately low: DCN efficiency for collectives is far
# below line rate (protocol overhead, cross-rack routing, sharing), and
# validation buffers are small — but a slice pair talking at a tenth of a
# NIC (mis-routed through WAN, a 1 Gbps link in the path, broken ECMP) must
# fail instead of passing at any speed.  The same armed-by-default shape as
# the ICI allreduce gate got in r03 (VERDICT r02 critique: unarmed = decorative).
DCN_GATE_FRACTION = 0.1


def _multislice_min_gbps(generation: str = "") -> float:
    """The cross-slice (DCN) allreduce floor for the slice's generation,
    from the catalogue's host NIC rate (0 / unknown generations keep it
    report-only; MULTISLICE_MIN_GBPS overrides either way)."""
    from tpu_operator.k8s.nodeinfo import generation_info

    return _env_floor(
        "MULTISLICE_MIN_GBPS",
        lambda: round(generation_info(generation).dcn_gbps * DCN_GATE_FRACTION, 1),
    )


def _measured_from_results(results: Optional[dict]) -> dict:
    """Map the workload drop-box (status.read_workload_results — either a
    run_validation {'checks': {...}} or a distributed {'distributed': {...}}
    shape) to the jax-payload keys the node-status exporter serves
    (metrics.NodeMetrics.PERF_KEYS).  Best-effort: absent file or keys
    contribute nothing.

    MEASUREMENTS from overhead-dominated runs are dropped: the shared
    timing rule (workloads/timing.py) says a flagged number can't be
    trusted in either direction, and these values feed the
    TPUNodeComputeDegraded / TPUNodeInterconnectDegraded alerts — r03's
    own BENCH showed a healthy chip at a flagged 0.37 "MFU" that would
    have paged the operator.  Gate FLOORS (min_gbps) are configuration,
    not measurements, and always pass through."""
    out: dict = {}
    if not isinstance(results, dict):
        return out
    checks = results.get("checks") or {}
    dist = results.get("distributed") or {}
    allreduce = checks.get("allreduce") or dist.get("allreduce") or {}
    ring = checks.get("ring") or dist.get("ring") or {}
    matmul = checks.get("matmul") or {}
    hbm = checks.get("hbm") or {}
    hbm_dma = checks.get("hbm-dma") or {}

    def _num(value):
        return (
            value
            if isinstance(value, (int, float)) and not isinstance(value, bool)
            else None
        )

    def _measured(source: dict, key: str):
        return None if source.get("overhead_dominated") else _num(source.get(key))

    algbw = _measured(allreduce, "algbw_gbps")
    if algbw is None and not allreduce.get("overhead_dominated"):
        # explicit None check, not `or`: a measured 0.0 is the most
        # alert-worthy value and must survive into the payload
        algbw = _num(allreduce.get("busbw_gbps"))
    for key, value in (
        ("algbw_gbps", algbw),
        ("allreduce_min_gbps", _num(allreduce.get("min_gbps"))),
        ("ring_link_gbps", _measured(ring, "link_gbps")),
        ("ring_min_gbps", _num(ring.get("min_gbps"))),
        ("matmul_tflops", _measured(matmul, "tflops")),
        ("mfu", _measured(matmul, "mfu")),
        ("hbm_gbps", _measured(hbm, "gbps")),
        ("hbm_fraction_of_peak", _measured(hbm, "fraction_of_peak")),
        # the DMA-pipeline cross-check: same units as hbm_gbps, VPU-free
        # path — divergence between the two isolates memory-system vs
        # compute-pipeline degradation (workloads/hbm_pallas.py)
        ("hbm_dma_gbps", _measured(hbm_dma, "gbps")),
    ):
        if value is not None:
            out[key] = value
    return out


# measured (gate-relevant) metric keys compared round-over-round; the gate
# FLOORS (min_gbps keys) are configuration and never "regress"
_REGRESSION_KEYS = (
    "algbw_gbps",
    "ring_link_gbps",
    "matmul_tflops",
    "mfu",
    "hbm_gbps",
    "hbm_dma_gbps",
)


def _regression_threshold() -> float:
    """Relative drop that counts as a regression (shared verdict rule,
    workloads/timing.regression_verdict); PERF_REGRESSION_THRESHOLD
    overrides the 7% default — including an explicit 0, which flags
    every drop (the _env_floor explicit-zero rule)."""
    return _env_floor("PERF_REGRESSION_THRESHOLD", lambda: 0.07)


def _regressions_vs_prior(payload: dict, prior: dict) -> list[dict]:
    """Gated metrics that regressed against the previous round's payload
    (the one run() stashed before clearing the status file)."""
    from tpu_operator.workloads import timing

    threshold = _regression_threshold()
    out = []
    for key in _REGRESSION_KEYS:
        verdict = timing.regression_verdict(
            payload.get(key), prior.get(key), threshold=threshold
        )
        if verdict is not None and verdict["verdict"] == "regressed":
            out.append({"metric": key, **verdict})
    return out


def _worker_id_of(node: dict) -> int:
    """The node's slice worker id; raises ValidationError on a malformed or
    missing label (silently collapsing to 0 would collide with the real
    worker 0: duplicate pod names, wrong PROCESS_ID in the rendezvous)."""
    from tpu_operator.k8s import nodeinfo

    attrs = nodeinfo.attributes(node)
    raw = attrs.worker_id
    if raw == "":
        raise ValidationError(
            f"node {attrs.name} is in a multi-host slice but has no worker-id label"
        )
    try:
        wid = int(raw)
    except ValueError:
        raise ValidationError(
            f"node {attrs.name} has a non-numeric worker-id label {raw!r}"
        ) from None
    if wid < 0:
        raise ValidationError(f"node {attrs.name} has negative worker id {wid}")
    return wid


@dataclass
class ValidatorConfig:
    node_name: str = field(default_factory=lambda: os.environ.get("NODE_NAME", ""))
    namespace: str = field(
        default_factory=lambda: os.environ.get(consts.OPERATOR_NAMESPACE_ENV, "tpu-operator")
    )
    sleep_interval: float = consts.VALIDATOR_SLEEP_SECONDS
    workload_retries: int = consts.VALIDATOR_WORKLOAD_RETRIES
    resource_retries: int = consts.VALIDATOR_RESOURCE_RETRIES
    with_workload: bool = field(
        default_factory=lambda: os.environ.get("WITH_WORKLOAD", "").lower() in ("1", "true")
    )
    workload_image: str = field(default_factory=lambda: os.environ.get("WORKLOAD_IMAGE", ""))
    # jax platform the PJRT probe asks for; cpu in tests
    platform: str = field(default_factory=lambda: os.environ.get("TPU_VALIDATOR_PLATFORM", "tpu"))


class ValidationError(Exception):
    pass


class Validator:
    COMPONENTS = ("libtpu", "pjrt", "plugin", "jax", "perf", "vfio-pci")

    def __init__(self, config: Optional[ValidatorConfig] = None, client: Optional[ApiClient] = None):
        self.config = config or ValidatorConfig()
        self._client = client
        self._events = None
        # per-component payload of the PREVIOUS validation round, stashed
        # by run() before it clears the status file — the LHS of the
        # round-over-round regression comparison
        self._prior: dict[str, dict] = {}

    def client(self) -> ApiClient:
        if self._client is None:
            from tpu_operator.k8s.client import Config

            self._client = ApiClient(Config.from_env())
        return self._client

    def events(self):
        """Lazy EventRecorder (Events are evidence; posting never gates)."""
        if self._events is None:
            from tpu_operator.obs.events import EventRecorder

            self._events = EventRecorder(
                self.client(), self.config.namespace, component="tpu-validator"
            )
        return self._events

    async def _finish_measured(
        self, component: str, payload: dict, scope: str = ""
    ) -> None:
        """Shared evidence-finishing rule for the measured components
        (jax, perf): attach the run's flight record (per-step samples with
        span ids, joinable against /debug/traces) to the ready payload,
        and when a gated metric regressed past the threshold vs the
        previous round's payload, record it and post a Warning Event —
        evidence and alerting, never a gate."""
        evidence = status.flight_evidence(scope=scope)
        if evidence is not None:
            payload["flight"] = evidence
        prior = self._prior.get(component)
        if not prior:
            return
        regressions = _regressions_vs_prior(payload, prior)
        if not regressions:
            return
        payload["regressions"] = regressions
        if not self.config.node_name:
            return
        from tpu_operator.obs import events as obs_events

        msg = "; ".join(
            f"{r['metric']} {r['prior']:.4g}→{r['current']:.4g}"
            f" ({r['delta_pct']:+.1f}%)"
            for r in regressions
        )
        await self.events().warning(
            obs_events.node_ref(self.config.node_name),
            obs_events.REASON_PERF_REGRESSED,
            f"{component} validation: {msg}",
        )

    # ------------------------------------------------------------------
    async def run(self, component: str) -> None:
        """Run one validation; raises ValidationError on failure."""
        handler = {
            "libtpu": self.validate_libtpu,
            "pjrt": self.validate_pjrt,
            "plugin": self.validate_plugin,
            "jax": self.validate_jax,
            "perf": self.validate_perf,
            "vfio-pci": self.validate_vfio,
        }.get(component)
        if handler is None:
            raise ValidationError(f"invalid component {component!r}; one of {self.COMPONENTS}")
        prior = status.read_status(component)
        if prior is not None:
            self._prior[component] = prior
        status.clear(component)
        # feeds workload_phase_duration_seconds{phase} when a tracer is ambient
        with trace.span(f"validate/{component}", kind=trace.KIND_PHASE, phase=component):
            await handler()
        if component == "jax":
            # jax-ready just landed: report the join critical-path
            # segments (status-file timestamps + flight compile samples)
            # through the agent push hop, tagged with the propagated trace
            # id — the fleet turns them into join_phase_seconds rollups
            # and /debug/explain's blocking verdict.  Strictly after the
            # gate, strictly best-effort.
            await self._push_join_phases()

    async def _push_join_phases(self) -> None:
        """One POST of this node's join-phase segments to the metrics
        agent (TPU_METRICS_PUSH_URL), carrying the adopted trace id so the
        fleet exemplar joins back to the operator's rollout trace.  Never
        raises — the join is already proven; this is its breakdown."""
        if not self.config.node_name or not os.environ.get("TPU_METRICS_PUSH_URL"):
            return
        try:
            created: Optional[float] = None
            node = await self.client().get("", "Node", self.config.node_name)
            raw = deep_get(node, "metadata", "creationTimestamp", default="")
            if raw:
                from tpu_operator.obs.fleet import _parse_k8s_ts

                created = _parse_k8s_ts(raw)
            segments = status.join_phase_segments(created)
            if not segments:
                return
            env_ctx = trace.TraceContext.from_env()
            tid = trace.trace_id() or (env_ctx.trace_id if env_ctx else "")
            from tpu_operator.obs import flight

            await asyncio.get_event_loop().run_in_executor(
                None,
                functools.partial(
                    flight.push_join_phases,
                    self.config.node_name,
                    segments,
                    trace_id=tid,
                ),
            )
        except Exception as e:  # noqa: BLE001 — telemetry must never fail a gate
            log.debug("join-phase push failed: %s", e)

    async def wait_ready(self, component: str, retries: Optional[int] = None) -> None:
        """--wait-only: block until another pod's validation wrote the file
        (device-plugin init gate pattern)."""
        retries = retries if retries is not None else self.config.workload_retries
        for _ in range(retries):
            if status.is_ready(component):
                return
            await asyncio.sleep(self.config.sleep_interval)
        raise ValidationError(f"timed out waiting for {component}-ready")

    # ------------------------------------------------------------------
    async def validate_libtpu(self) -> None:
        """Wait for the runtime container, then probe host truth."""
        host_managed = False
        for _ in range(self.config.resource_retries):
            if status.marker_exists(LIBTPU_CTR_MARKER):
                break
            if hw.libtpu_path():
                # no operator-managed runtime container but libtpu is on the
                # host → host-managed runtime (host-driver-ready analogue)
                host_managed = True
                break
            await asyncio.sleep(self.config.sleep_interval)
        else:
            raise ValidationError("tpu runtime container never became ready")
        libtpu = hw.libtpu_path()
        if not libtpu:
            raise ValidationError("libtpu.so not found on host")
        chips = hw.chip_count()
        if chips <= 0:
            raise ValidationError("no /dev/accel* TPU device nodes")
        status.write_ready(
            "libtpu", {"libtpu_path": libtpu, "chips": chips, "host_managed": host_managed}
        )

    async def validate_pjrt(self) -> None:
        """PJRT client init — the nvidia-smi analogue.  Beyond "a client
        initializes", the device COUNT must match the host's chip truth
        (libtpu-ready's /dev/accel* count): libtpu excludes dead chips at
        init, so 4 device nodes with 1 PJRT device is a half-dead host that
        must fail validation here, not pass on the survivors."""
        await self.wait_ready("libtpu", retries=self.config.resource_retries)

        def probe() -> dict:
            import jax

            devices = jax.devices(self.config.platform)
            if not devices:
                raise ValidationError(f"PJRT reports no {self.config.platform} devices")
            return {
                "platform": self.config.platform,
                "device_count": len(devices),
                "device_kind": getattr(devices[0], "device_kind", ""),
            }

        payload = await asyncio.get_event_loop().run_in_executor(None, probe)
        from tpu_operator.workloads.timing import gate_backends

        chips = (status.read_status("libtpu") or {}).get("chips")
        if (
            self.config.platform in gate_backends("DEVICE_COUNT_GATE_BACKENDS")
            and isinstance(chips, int)
            and chips > 0
            and payload["device_count"] != chips
        ):
            raise ValidationError(
                f"PJRT initialized {payload['device_count']} devices but the "
                f"host has {chips} chip device nodes — dead or missing chips"
            )
        payload["host_chips"] = chips
        status.write_ready("pjrt", payload)

    async def validate_plugin(self) -> None:
        """Node advertises google.com/tpu (validateGPUResource analogue)."""
        if not self.config.node_name:
            raise ValidationError("NODE_NAME required for plugin validation")
        client = self.client()
        for _ in range(self.config.resource_retries):
            node = await client.get("", "Node", self.config.node_name)
            alloc = deep_get(node, "status", "allocatable", default={}) or {}
            try:
                count = int(alloc.get(consts.TPU_RESOURCE, "0"))
            except ValueError:
                count = 0
            if count > 0:
                if self.config.with_workload:
                    await self.spawn_workload(
                        "tpu-plugin-workload-validation", checks="vector-add", tpu_request=1
                    )
                status.write_ready("plugin", {"allocatable": count})
                return
            await asyncio.sleep(self.config.sleep_interval)
        raise ValidationError(f"node {self.config.node_name} never advertised {consts.TPU_RESOURCE}")

    async def validate_jax(self) -> None:
        """The collective gate: allreduce + burn-in over all local chips —
        or, on a multi-host slice, ONE jax.distributed program across every
        host of the slice (SURVEY §7 hard parts 1 & 3: slice health is a set
        property; no reference analogue, GPU validation is node-local)."""
        await self.wait_ready("plugin", retries=self.config.resource_retries)
        # fresh flight record for this round: recorders APPEND (concurrent
        # local writers must never truncate each other), so the one
        # per-node coordinator — this validator — clears stale samples
        # here, before any writer starts
        status.clear_flight_record()
        if self.config.with_workload:
            group = await self._slice_group()
            if group is not None:
                await self.validate_jax_multihost(*group)
                return
            chips = await self._node_chip_count()
            # multi-chip: the local allreduce rides ICI — arm the busbw gate
            # from the accelerator catalogue (single chip stays report-only)
            min_gbps = 0.0
            if chips > 1:
                from tpu_operator.k8s import nodeinfo

                node = await self.client().get("", "Node", self.config.node_name)
                min_gbps = _allreduce_min_gbps(nodeinfo.attributes(node).generation)
            # the readiness gate is the MINIMAL workload only (reference
            # bar: validator/main.go:1189-1302 gates on vectorAdd, not a
            # benchmark suite) — matmul/hbm/ring perf probes run POST-ready
            # via the perf component; putting them here cost r03 a 37%
            # join-to-validated regression.  burn-in gates only where it is
            # a real slice-acceptance test (multi-chip collectives); on a
            # single chip it is an MXU exercise that belongs with the
            # post-ready probes, not on the critical path
            checks = "vector-add,allreduce" + (",burn-in" if chips > 1 else "")
            await self.spawn_workload(
                "tpu-jax-workload-validation",
                checks=checks,
                tpu_request=chips,
                min_gbps=min_gbps,
            )
            payload = {
                "mode": "workload-pod", "chips": chips,
                "allreduce_min_gbps": min_gbps,
            }
            payload.update(_measured_from_results(status.read_workload_results()))
            await self._finish_measured("jax", payload)
            status.write_ready("jax", payload)
            return

        def run_checks() -> dict:
            import jax

            from tpu_operator.obs import flight
            from tpu_operator.workloads import collectives, compile_cache

            compile_cache.enable()
            # the in-process run leaves the same flight record a workload
            # pod would — samples under per-check phase spans so they carry
            # span ids exactly like run_validation's (explicit activation:
            # executor threads don't inherit the event loop's contextvars)
            recorder = flight.recorder_for(status.flight_record_path())
            local_tracer = trace.Tracer()
            with local_tracer.adopt(trace.TraceContext.from_env()), flight.activate(recorder):
                # minimal gate only — matmul/hbm/ring run post-ready via the
                # perf component, and burn-in gates only where it is a real
                # multi-chip acceptance test: the same split as the
                # workload-pod path (single-chip burn-in runs post-ready)
                checks = [
                    ("vector-add", lambda: collectives.vector_add(1 << 16)),
                    (
                        "allreduce",
                        lambda: collectives.allreduce_benchmark(
                            size_mb=4, iters=3, warmup=1
                        ),
                    ),
                ]
                if len(jax.devices()) > 1:
                    checks.append(("burn-in", lambda: collectives.burn_in(steps=2)))
                results = {}
                for name, fn in checks:
                    with trace.span(
                        f"check/{name}", kind=trace.KIND_PHASE, phase=name
                    ):
                        results[name] = fn()
                        flight.record_result(name, results[name])
                for name, r in results.items():
                    if not r.get("ok"):
                        raise ValidationError(f"jax check {name} failed: {r}")
            # measured figures go through the SAME flag filter as the
            # workload path: the small in-process buffer is routinely
            # overhead-dominated on tunneled backends (a real run reported
            # 0.16 GB/s for a healthy chip), and a flagged number must
            # never reach the exporter
            return {
                "mode": "in-process",
                "devices": results["allreduce"]["devices"],
                **_measured_from_results({"checks": results}),
            }

        payload = await asyncio.get_event_loop().run_in_executor(None, run_checks)
        await self._finish_measured("jax", payload)
        status.write_ready("jax", payload)

    async def validate_perf(self) -> None:
        """Post-ready perf probes: matmul MFU, HBM streaming, and (on
        multi-chip hosts) the per-link ring diagnostic — the measured
        evidence behind the TPUNodeComputeDegraded /
        TPUNodeInterconnectDegraded alerts.

        Runs strictly AFTER jax-ready: readiness gates on the minimal
        workload only (reference bar: the CUDA workload of
        validator/main.go:1189-1302, not a benchmark suite), and the
        probes' chip time must never sit on the join→validated critical
        path — r03 put matmul there and regressed the headline 37%.
        Probe failures are recorded in perf-ready (ok=false + error), not
        raised: a slow chip is the alerts' business, not a reason to mark
        the node unvalidated.  Workload-pod results land in their own
        drop-box scope so they never clobber the gating run's figures."""
        await self.wait_ready("jax", retries=self.config.resource_retries)
        if self.config.with_workload:
            from tpu_operator.k8s import nodeinfo

            group = await self._slice_group()
            if group is not None:
                # a slice member's chips only initialize inside the
                # coordinated jax.distributed program — a node-local
                # single-process probe pod would request every host chip and
                # hang in slice init (the same reason validate_jax branches
                # to validate_jax_multihost).  Per-link ICI and allreduce
                # busbw for the slice are measured by that coordinated run;
                # chip-local matmul/HBM probes have no valid node-local
                # execution here, so record the skip honestly instead of
                # chronically failing perf-ready on healthy slices.  Clear
                # the node-local drop-box too: a node that ran standalone
                # perf probes and later joined a slice must not keep
                # exporting stale matmul/hbm figures to the alerts.
                status.clear_workload_results(scope="perf")
                status.clear_flight_record(scope="perf")
                status.write_ready("perf", {
                    "ok": True,
                    "skipped": "multi-host slice member: node-local PJRT "
                               "init is invalid; slice perf is measured by "
                               "the coordinated multi-host validation",
                    "slice": group[0],
                })
                return
            chips = await self._node_chip_count()
            node = await self.client().get("", "Node", self.config.node_name)
            generation = nodeinfo.attributes(node).generation
            ring_min = _ring_min_gbps(generation) if chips > 1 else 0.0
            # multi-chip: ring per-link diagnostic + the parallelism
            # census (ring attention, Ulysses all-to-all, expert-parallel
            # MoE — whose dispatch crosses EVERY chip pair, a full-
            # bisection check the neighbour ring can't give — and the
            # GPipe pipeline); single chip: the burn-in train-step moves
            # here from the gate (still proven, just not on the readiness
            # critical path).  hbm-dma is the pallas DMA-pipeline
            # cross-check paired with hbm
            checks = "matmul,hbm,hbm-dma,longctx,decode" + (
                ",ring,ring-attention,ulysses,moe,pipeline"
                if chips > 1 else ",burn-in"
            )
            # the CR-level probe budget (validator.perfProbes → template
            # env): check selection override + a time budget forwarded to
            # the probe pod, which stops STARTING checks past it — the
            # ~80 s of chip occupancy per round is an operator decision
            checks = os.environ.get("PERF_PROBE_CHECKS", "") or checks
            budget = _env_floor("PERF_PROBE_BUDGET_S", lambda: 0.0)
            # clear the previous run's drop-box FIRST: a failed probe run
            # must surface as "no current measurements", never republish
            # last round's healthy figures to the degradation alerts (the
            # flight record clears with it — same staleness rule)
            status.clear_workload_results(scope="perf")
            status.clear_flight_record(scope="perf")
            ok, error = True, None
            try:
                await self.spawn_workload(
                    "tpu-perf-probes",
                    checks=checks,
                    tpu_request=chips,
                    ring_min_gbps=ring_min,
                    results_scope="perf",
                    budget_seconds=budget,
                )
            except ValidationError as e:
                ok, error = False, str(e)
                # best-effort: a pod left Pending/Running would later grab
                # the chips it never got and collide with user workloads
                # (post-ready, the node is schedulable — probes are
                # opportunistic and re-run on the next validation round)
                await self.client().delete(
                    "", "Pod", "tpu-perf-probes", self.config.namespace
                )
            dropbox = status.read_workload_results(scope="perf") or {}
            results = dropbox.get("checks") or {}
            measured = _measured_from_results(dropbox)
        else:

            def run_probes() -> dict:
                import jax

                from tpu_operator.obs import flight
                from tpu_operator.workloads import (
                    collectives,
                    compile_cache,
                    hbm_bench,
                    hbm_pallas,
                    matmul_bench,
                )

                compile_cache.enable()
                multi = len(jax.devices()) > 1
                # the per-link floor must be recorded here too (the alert
                # needs its ring_min_gbps RHS on in-process nodes as much as
                # on workload-pod ones); generation comes from the PJRT
                # device kind — no apiserver needed in-process
                ring_min = (
                    _ring_min_gbps(matmul_bench.detect_generation()) if multi else 0.0
                )
                probes = {
                    "matmul": matmul_bench.quick_benchmark,
                    "hbm": hbm_bench.quick_benchmark,
                    "hbm-dma": hbm_pallas.quick_benchmark,
                    "ring": lambda: collectives.apply_ring_gate(
                        collectives.ring_benchmark(size_mb=2, iters=2, best_of=2),
                        ring_min,
                    ),
                }
                if multi:
                    from tpu_operator.workloads import ring_attention

                    # sequence-parallel exact attention over the local ring
                    probes["ring-attention"] = ring_attention.quick_check
                if not multi:
                    # mirror the workload split: single-chip burn-in runs
                    # here, post-ready, instead of on the gate
                    probes["burn-in"] = lambda: collectives.burn_in(steps=2)
                # the CR-level budget applies in-process exactly as in the
                # probe pod: selection override + stop STARTING probes past
                # the budget (skipped = evidence, not failure)
                selected = os.environ.get("PERF_PROBE_CHECKS", "")
                if selected:
                    from tpu_operator.workloads import run_validation

                    valid = run_validation.known_checks()
                    names = [c.strip() for c in selected.split(",") if c.strip()]

                    def _unavailable(n):
                        # the CR selection is cluster-wide but in-process
                        # nodes implement a probe subset — a VALID name
                        # this node can't run is SKIPPED evidence (the
                        # workload-pod nodes still run it), never a
                        # hardware-looking failure; a typo'd name fails
                        # here exactly as the probe pod would fail it
                        if n in valid:
                            return {
                                "ok": True,
                                "skipped": f"probe {n} not available in-process",
                            }
                        return {"ok": False, "error": f"unknown check {n}"}

                    probes = {
                        n: probes.get(n, functools.partial(_unavailable, n))
                        for n in names
                    }
                budget = _env_floor("PERF_PROBE_BUDGET_S", lambda: 0.0)
                t_start = time.monotonic()
                out = {}
                # in-process probes leave the same scoped flight record a
                # probe pod would, samples under per-probe phase spans for
                # span ids (explicit activation: executor threads don't
                # inherit the loop's contextvars)
                recorder = flight.recorder_for(status.flight_record_path("perf"))
                local_tracer = trace.Tracer()
                with local_tracer.adopt(trace.TraceContext.from_env()), flight.activate(recorder):
                    for probe_name, fn in probes.items():
                        if budget and time.monotonic() - t_start > budget:
                            out[probe_name] = {
                                "ok": True,
                                "skipped": f"budget ({budget}s) exhausted",
                            }
                            continue
                        with trace.span(
                            f"check/{probe_name}",
                            kind=trace.KIND_PHASE,
                            phase=probe_name,
                        ):
                            try:
                                out[probe_name] = fn()
                            except Exception as e:  # noqa: BLE001
                                # post-ready, the chip is schedulable: a user
                                # pod may own it and PJRT init can fail
                                # device-busy — probes are opportunistic,
                                # record and move on
                                out[probe_name] = {"ok": False, "error": str(e)}
                            flight.record_result(probe_name, out[probe_name])
                return out

            results = await asyncio.get_event_loop().run_in_executor(None, run_probes)
            ok = all(bool(r.get("ok")) for r in results.values())
            error = None if ok else "; ".join(
                f"{name}: {r.get('error', 'failed')}"
                for name, r in results.items()
                if not r.get("ok")
            )
            measured = _measured_from_results({"checks": results})
        # top level: the filtered measurements the exporter serves (flagged
        # overhead-dominated figures already dropped); "checks": the raw
        # probe results, flags and all, as the human-debuggable evidence
        payload = {"ok": ok, **measured, "checks": results}
        if error:
            payload["error"] = error
        await self._finish_measured("perf", payload, scope="perf")
        status.write_ready("perf", payload)

    # ------------------------------------------------------------------
    # Multi-host slice validation (jax.distributed-coordinated worker pods).

    async def _slice_group(self) -> Optional[tuple[str, list[dict]]]:
        """(group_key, ordered member nodes) when this node belongs to a
        multi-host slice; None on single-host nodes.  Membership = same GKE
        nodepool (one multi-host slice per node pool); ordering = worker id
        (TFD / GKE label)."""
        from tpu_operator.controllers.labels import slice_group_key
        from tpu_operator.k8s import nodeinfo

        if not self.config.node_name:
            return None
        client = self.client()
        node = await client.get("", "Node", self.config.node_name)
        key = slice_group_key(node)
        if key is None:
            return None
        members = (
            nodeinfo.NodeFilter()
            .tpu()
            .eq(consts.GKE_NODEPOOL_LABEL, key)
            .apply(await client.list_items("", "Node"))
        )
        self._checked_worker_ids(key, members)  # sorts members in place
        return key, members

    @staticmethod
    def _checked_worker_ids(key: str, members: list[dict]) -> dict[str, int]:
        """Validate one slice's worker-id labels (numeric, unique, covering
        0..N-1, all hosts present), sort ``members`` by id in place, and
        return {node name: worker id}."""
        from tpu_operator.k8s import nodeinfo

        ids = {m["metadata"]["name"]: _worker_id_of(m) for m in members}
        dupes = {i for i in ids.values() if list(ids.values()).count(i) > 1}
        if dupes:
            raise ValidationError(
                f"slice {key}: duplicate worker ids {sorted(dupes)} across hosts "
                f"{sorted(n for n, i in ids.items() if i in dupes)}"
            )
        members.sort(key=lambda m: ids[m["metadata"]["name"]])
        expected = max(nodeinfo.slice_hosts(m) for m in members)
        if len(members) < expected:
            raise ValidationError(
                f"slice {key}: only {len(members)}/{expected} hosts present"
            )
        if sorted(ids.values()) != list(range(len(members))):
            raise ValidationError(
                f"slice {key}: worker ids {sorted(ids.values())} do not cover "
                f"0..{len(members) - 1}; check the worker-id labels"
            )
        return ids

    async def _multislice_group(
        self,
    ) -> Optional[tuple[str, list[dict], dict[str, int], dict[str, list[dict]]]]:
        """(group key, globally-ordered members, {node: global process id},
        {slice key: slice members}) when this node's slice belongs to a
        DCN-connected multislice group spanning >1 slice; None otherwise.

        Membership = the admin/TFD-applied ``tpu.google.com/multislice-group``
        label (GKE creates one node pool per slice; which slices form a
        multislice is a deployment decision the cluster must declare).
        Global process ids order slices lexicographically by slice key, hosts
        by worker id within each — every member derives the same order from
        cluster state alone."""
        from tpu_operator.controllers.labels import slice_group_key
        from tpu_operator.k8s import nodeinfo

        client = self.client()
        node = await client.get("", "Node", self.config.node_name)
        ms_key = (deep_get(node, "metadata", "labels", default={}) or {}).get(
            consts.MULTISLICE_GROUP_LABEL
        )
        if not ms_key:
            return None
        members = (
            nodeinfo.NodeFilter()
            .tpu()
            .eq(consts.MULTISLICE_GROUP_LABEL, ms_key)
            .apply(await client.list_items("", "Node"))
        )
        slices: dict[str, list[dict]] = {}
        for m in members:
            sk = slice_group_key(m)
            if sk is None:
                raise ValidationError(
                    f"multislice {ms_key}: member {m['metadata']['name']} has no "
                    "slice identity (single-host or missing nodepool label)"
                )
            slices.setdefault(sk, []).append(m)
        declared = (deep_get(node, "metadata", "labels", default={}) or {}).get(
            consts.MULTISLICE_SLICES_LABEL
        )
        if declared:
            try:
                expected_slices = int(declared)
            except ValueError:
                raise ValidationError(
                    f"multislice {ms_key}: malformed "
                    f"{consts.MULTISLICE_SLICES_LABEL}={declared!r}"
                )
            if len(slices) != expected_slices:
                # a wholly-absent member slice must FAIL, not silently
                # degrade to single-slice validation (set-property
                # semantics, same as a partially-present slice)
                raise ValidationError(
                    f"multislice {ms_key}: {len(slices)}/{expected_slices} "
                    f"member slices visible ({sorted(slices)})"
                )
        elif len(slices) < 2:
            log.warning(
                "multislice %s: only one member slice visible and no %s "
                "declaration; skipping cross-slice validation (set the label "
                "to make absence a failure)",
                ms_key, consts.MULTISLICE_SLICES_LABEL,
            )
            return None
        ordered: list[dict] = []
        for sk in sorted(slices):
            self._checked_worker_ids(sk, slices[sk])  # sorts by worker id
            ordered.extend(slices[sk])
        ids = {m["metadata"]["name"]: i for i, m in enumerate(ordered)}
        return ms_key, ordered, ids, slices

    async def _await_member_slices_proven(
        self, ms_key: str, slices: dict[str, list[dict]]
    ) -> None:
        """Block the cross-slice phase until every member slice's own
        rendezvous is proven AND garbage-collected (Service tombstone at the
        slice's current epoch).  Ordering matters on real kubelets: a
        nodeName-pinned pod that doesn't fit the node's free chips is
        REJECTED (OutOf<resource>), not queued — cross-slice pods must not
        race member slices' validation pods for the same chips."""
        for _ in range(self.config.workload_retries):
            pending = None
            for sk, mems in slices.items():
                svc = self._group_service_name(sk)
                epoch = await self._validation_epoch(mems)
                if await self._group_tombstone(svc) != epoch:
                    pending = sk
                    break
            if pending is None:
                return
            await asyncio.sleep(self.config.sleep_interval)
        raise ValidationError(
            f"multislice {ms_key}: member slice {pending} never proved its own "
            "rendezvous; cannot start the cross-slice phase"
        )

    def _group_pod_name(
        self, key: str, worker_id: int, base: str = "tpu-jax-validation"
    ) -> str:
        from tpu_operator.state.nodepool import hashed_name

        return hashed_name(base, f"{key}-w{worker_id}")

    def _group_service_name(self, key: str, base: str = "tpu-jax-validation") -> str:
        from tpu_operator.state.nodepool import hashed_name

        return hashed_name(base, key)

    async def _validation_epoch(self, members: list[dict]) -> str:
        """Identity of the runtime the slice is being proven against.

        A workload pod's Succeeded phase is only evidence for the runtime it
        ran on; after an upgrade swaps libtpu on any member host the old
        evidence must not re-gate jax-ready.  The epoch hashes, per member,
        the live runtime pod's UID (changes on every swap, even same-version
        reinstalls) with the TFD-reported version label as the host-managed
        fallback — so all hosts derive the same value from cluster state."""
        from tpu_operator.k8s import nodeinfo

        runtime_uid: dict[str, str] = {}
        for pod in await self.client().list_items(
            "", "Pod", self.config.namespace, label_selector="app=tpu-runtime"
        ):
            if deep_get(pod, "metadata", "deletionTimestamp"):
                continue
            node = deep_get(pod, "spec", "nodeName")
            if node:
                runtime_uid[node] = deep_get(pod, "metadata", "uid", default="")
        ident = sorted(
            (a.name, runtime_uid.get(a.name, ""), a.runtime_version)
            for a in (nodeinfo.attributes(m) for m in members)
        )
        return hashlib.sha1(json.dumps(ident).encode()).hexdigest()[:12]

    async def validate_jax_multihost(self, key: str, members: list[dict]) -> None:
        """One global collective across every host of the slice.

        The validator converges the coordination resources — a headless
        Service plus one workload pod per slice host, each pinned to its
        node and running ``workloads.distributed`` with
        jax.distributed.initialize(coordinator=worker-0-pod DNS) — then every
        host's validator gates its own ``jax-ready`` on ITS pod succeeding,
        which can only happen if the GLOBAL psum + burn-in passed on all
        hosts (any missing worker fails the whole rendezvous).

        Evidence is keyed to a validation EPOCH (runtime identity across the
        slice): a Succeeded pod from an older epoch is stale — whichever
        host's validator notices (worker 0 up front; any other worker after
        a grace period, covering post-swap re-validation where worker 0's
        validator isn't re-running) deletes and recreates the out-of-date
        pods.  After success, worker 0 records the proven epoch on the
        Service and garbage-collects the Succeeded pods, so re-validating
        validators accept the Service tombstone instead of re-proving.
        Reference pattern: workload-pod spawning of validator/main.go:941-1052,
        lifted from one pod to a coordinated, epoch-keyed set.

        When the slice belongs to a declared MULTISLICE group, jax-ready
        additionally requires the CROSS-SLICE rendezvous — the same
        machinery over every host of every member slice, with global
        process ids and the collective riding DCN between slices (SURVEY
        §5.8's "DCN across slices later", now).  The ICI-derived allreduce
        floor is NOT applied there (DCN is a different fabric); the
        cross-slice busbw gates against the generation's NIC-rate-derived
        DCN floor (_multislice_min_gbps; MULTISLICE_MIN_GBPS overrides)."""
        import functools

        ids = {m["metadata"]["name"]: _worker_id_of(m) for m in members}
        payload = await self._validate_group_rendezvous(
            key, members, ids, mode="multi-host"
        )
        ms = await self._multislice_group()
        if ms is not None:
            ms_key, ms_members, ms_ids, ms_slices = ms
            ms_payload = await self._validate_group_rendezvous(
                ms_key, ms_members, ms_ids, mode="multislice",
                gate_ici=False,
                base=MULTISLICE_BASE,
                # re-awaited before EVERY pod-set convergence, not just the
                # first: a mid-flight epoch change re-triggers member-slice
                # validations, and cross-slice pods must never race them
                # for the same chips
                before_ensure=functools.partial(
                    self._await_member_slices_proven, ms_key, ms_slices
                ),
            )
            payload["multislice"] = {
                k: ms_payload[k]
                for k in ("group", "workers", "worker_id", "epoch", "proven_by")
            }
            # the cross-slice pod's DCN figures, from their own scope
            payload["multislice"].update(
                _measured_from_results(
                    status.read_workload_results(scope="multislice")
                )
            )
        # THIS host's slice pod dropped its ICI figures into the node-local
        # drop-box it mounts — surface them (exporter → alerts); on the
        # tombstone path the drop-box holds the last run's figures, which is
        # exactly the gauge family's "last measured" semantics
        payload.update(_measured_from_results(status.read_workload_results()))
        await self._finish_measured("jax", payload)
        status.write_ready("jax", payload)

    async def _validate_group_rendezvous(
        self,
        key: str,
        members: list[dict],
        ids: dict[str, int],
        mode: str,
        gate_ici: bool = True,
        base: str = "tpu-jax-validation",
        before_ensure=None,
    ) -> dict:
        """Converge + gate on one coordinated rendezvous over ``members``
        with the given process-id assignment; returns the proof payload
        (the caller owns writing status).  ``base`` namespaces the
        Service/pod names so distinct rendezvous kinds can never collide
        (a nodepool literally named like a prefixed group key must not share
        evidence with the cross-slice rendezvous)."""
        my_id = ids[self.config.node_name]
        svc = self._group_service_name(key, base)
        coordinator = (
            f"{self._group_pod_name(key, 0, base)}.{svc}."
            f"{self.config.namespace}.svc:{COORDINATOR_PORT}"
        )
        client = self.client()
        epoch = await self._validation_epoch(members)
        if my_id == 0:
            if before_ensure is not None:
                await before_ensure()
            await self._ensure_group_workloads(
                key, members, svc, coordinator, epoch, ids, gate_ici, base
            )

        def ready_payload(proven_by: str) -> dict:
            return {
                "mode": mode,
                "group": key,
                "workers": len(members),
                "worker_id": my_id,
                "epoch": epoch,
                "proven_by": proven_by,
            }

        # non-zero workers give worker 0 this many polls before converging
        # the pod set themselves (idempotent: the epoch check skips current
        # pods, so concurrent converging workers agree)
        patience = 10 if my_id != 0 else 0
        name = self._group_pod_name(key, my_id, base)
        phase = None
        ensured = my_id == 0  # whoever converged the pod set also GCs it
        for attempt in range(self.config.workload_retries):
            # re-derive the epoch every poll: a runtime pod restarting on any
            # member mid-validation would otherwise leave validators that
            # snapshotted different epochs deleting each other's pod sets
            # until retries exhaust — recomputing makes them all converge on
            # the latest cluster state
            epoch = await self._validation_epoch(members)
            tombstone = await self._group_tombstone(svc)
            if tombstone == epoch:
                return ready_payload("service-tombstone")
            try:
                live = await client.get("", "Pod", name, self.config.namespace)
            except ApiError as e:
                if not e.not_found:
                    raise
                live = None
            pod_epoch = (
                deep_get(live, "metadata", "labels", default={}).get(EPOCH_LABEL)
                if live is not None
                else None
            )
            if live is None or pod_epoch != epoch:
                if attempt >= patience:
                    if before_ensure is not None:
                        await before_ensure()
                    await self._ensure_group_workloads(
                        key, members, svc, coordinator, epoch, ids, gate_ici, base
                    )
                    ensured = True
                await asyncio.sleep(self.config.sleep_interval)
                continue
            phase = deep_get(live, "status", "phase")
            if phase == "Succeeded":
                if ensured:
                    # the worker that converged the pod set also records the
                    # tombstone + GCs — covering re-proofs driven by a
                    # non-zero worker while worker 0's validator is asleep
                    await self._cleanup_group_workloads(
                        key, members, svc, epoch, ids, base
                    )
                return ready_payload("workload-pod")
            if phase == "Failed":
                raise ValidationError(
                    f"distributed validation pod {name} failed (slice {key})"
                )
            await asyncio.sleep(self.config.sleep_interval)
        raise ValidationError(
            f"distributed validation pod {name} did not complete (phase={phase})"
        )

    async def _group_tombstone(self, svc: str) -> Optional[str]:
        """The epoch already proven for this slice group, recorded on the
        headless Service after worker 0 garbage-collected the pods."""
        try:
            service = await self.client().get(
                "", "Service", svc, self.config.namespace
            )
        except ApiError as e:
            if e.not_found:
                return None
            raise
        return deep_get(service, "metadata", "annotations", default={}).get(
            VALIDATED_EPOCH_ANNOTATION
        )

    async def _ensure_group_workloads(
        self,
        key: str,
        members: list[dict],
        svc: str,
        coordinator: str,
        epoch: str,
        ids: dict[str, int],
        gate_ici: bool = True,
        base: str = "tpu-jax-validation",
    ) -> None:
        """Converge the headless Service + one pinned pod per group host to
        the current epoch.  Pods already at this epoch (and not Failed) are
        left untouched — no group-wide churn when evidence is current.
        ``ids`` assigns each host its process id (per-slice worker ids for a
        slice group; global ids for a multislice group); ``gate_ici`` arms
        the catalogue ICI floor (off for cross-slice DCN, where the
        NIC-rate-derived DCN floor applies instead)."""
        from tpu_operator.k8s import nodeinfo

        if await self._group_tombstone(svc) == epoch:
            # already proven and garbage-collected (worker 0's cleanup can
            # land between a peer's tombstone check and its pod poll);
            # recreating pods here would start an unjoinable rendezvous
            return
        client = self.client()
        owner = await self._owner_daemonset()
        service = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": svc,
                "namespace": self.config.namespace,
                "labels": {"app": "tpu-jax-validation", "tpu.google.com/slice-group": svc},
            },
            "spec": {
                "clusterIP": "None",  # headless: per-pod DNS for the rendezvous
                "selector": {"tpu.google.com/slice-group": svc},
                "ports": [{"port": COORDINATOR_PORT, "name": "coordinator"}],
            },
        }
        if owner is not None:
            from tpu_operator.k8s import objects as obj_api

            obj_api.set_owner_reference(service, owner)
        try:
            await client.create(service)
        except ApiError as e:
            if not e.already_exists:
                raise
        for member in members:
            attrs = nodeinfo.attributes(member)
            wid = ids[member["metadata"]["name"]]
            name = self._group_pod_name(key, wid, base)
            try:
                live = await client.get("", "Pod", name, self.config.namespace)
            except ApiError as e:
                if not e.not_found:
                    raise
                live = None
            if live is not None:
                current = deep_get(live, "metadata", "labels", default={}).get(
                    EPOCH_LABEL
                )
                if current == epoch and deep_get(live, "status", "phase") != "Failed":
                    continue
                await client.delete("", "Pod", name, self.config.namespace)
            if gate_ici:
                # the armed ICI gate: the distributed program measures the
                # global allreduce and fails the rendezvous below this
                # busbw.  The RING stays report-only on multi-host slices:
                # its enumeration-order hops are only a LOWER BOUND on
                # per-link rate there (collectives.ring_benchmark note), so
                # a per-link floor would chronically fail healthy slices —
                # operators can still arm it explicitly via RING_MIN_GBPS
                min_gbps = _allreduce_min_gbps(attrs.generation)
                ring_min = _env_floor("RING_MIN_GBPS", lambda: 0.0)
            else:
                # cross-slice traffic rides DCN, not ICI — the armed floor
                # derives from the generation's host NIC line rate (the
                # same catalogue-armed shape the ICI gate got in r03; a
                # wholly unarmed DCN gate was decorative, VERDICT r03 #6)
                min_gbps = _multislice_min_gbps(attrs.generation)
                ring_min = 0.0
            pod = self._workload_pod(
                name,
                checks="",
                tpu_request=max(1, attrs.chips_per_host),
                owner=owner,
                min_gbps=min_gbps,
                ring_min_gbps=ring_min,
            )
            pod["metadata"]["labels"]["tpu.google.com/slice-group"] = svc
            pod["metadata"]["labels"][EPOCH_LABEL] = epoch
            spec = pod["spec"]
            spec["nodeName"] = attrs.name
            # per-pod DNS record under the headless Service
            spec["hostname"] = name
            spec["subdomain"] = svc
            container = spec["containers"][0]
            container["command"] = ["python", "-m", "tpu_operator.workloads.distributed"]
            container["env"] += [
                {"name": "COORDINATOR_ADDRESS", "value": coordinator},
                {"name": "NUM_PROCESSES", "value": str(len(members))},
                {"name": "PROCESS_ID", "value": str(wid)},
            ]
            if not gate_ici:
                # cross-slice results land in their own drop-box scope so
                # DCN figures never overwrite the slice's ICI figures
                container["env"].append(
                    {"name": "RESULTS_SCOPE", "value": "multislice"}
                )
            try:
                await client.create(pod)
            except ApiError as e:
                # another worker converged this name concurrently, or the old
                # pod is still terminating; the next poll's epoch check decides
                if not e.already_exists:
                    raise

    async def _cleanup_group_workloads(
        self,
        key: str,
        members: list[dict],
        svc: str,
        epoch: str,
        ids: dict[str, int],
        base: str = "tpu-jax-validation",
    ) -> None:
        """Worker 0, post-success: once every member pod of this epoch has
        Succeeded, record the proven epoch on the Service and delete the
        pods (a 64-host slice must not leave 64 completed pods per round).
        Best-effort and bounded — evidence is only deleted after the
        tombstone is durably written, so a crash mid-cleanup at worst causes
        one re-proof, never a false pass."""
        client = self.client()
        names = [
            self._group_pod_name(key, ids[m["metadata"]["name"]], base)
            for m in members
        ]
        for _ in range(min(60, self.config.workload_retries)):
            done = 0
            for name in names:
                try:
                    pod = await client.get("", "Pod", name, self.config.namespace)
                except ApiError as e:
                    if not e.not_found:
                        raise
                    # already gone (completed-pod GC, eviction, or a
                    # concurrent cleanup) — absence must not block the
                    # tombstone the remaining Succeeded pods have earned
                    done += 1
                    continue
                if (
                    deep_get(pod, "metadata", "labels", default={}).get(EPOCH_LABEL)
                    == epoch
                    and deep_get(pod, "status", "phase") == "Succeeded"
                ):
                    done += 1
            if done == len(names):
                break
            await asyncio.sleep(self.config.sleep_interval)
        else:
            log.info(
                "slice %s: not all validation pods finished; leaving them in place",
                key,
            )
            return
        await client.patch(
            "", "Service", svc,
            {"metadata": {"annotations": {VALIDATED_EPOCH_ANNOTATION: epoch}}},
            self.config.namespace,
        )
        for name in names:
            await client.delete("", "Pod", name, self.config.namespace)

    async def validate_vfio(self) -> None:
        devices = hw.vfio_device_paths()
        if not devices:
            raise ValidationError("no /dev/vfio group devices bound")
        status.write_ready("vfio-pci", {"devices": devices})

    # ------------------------------------------------------------------
    async def _node_chip_count(self) -> int:
        node = await self.client().get("", "Node", self.config.node_name)
        alloc = deep_get(node, "status", "allocatable", default={}) or {}
        try:
            return max(1, int(alloc.get(consts.TPU_RESOURCE, "1")))
        except ValueError:
            return 1

    async def _owner_daemonset(self) -> Optional[dict]:
        try:
            return await self.client().get(
                "apps", "DaemonSet", "tpu-operator-validator", self.config.namespace
            )
        except ApiError:
            return None

    def _workload_pod(
        self,
        name: str,
        checks: str,
        tpu_request: int,
        owner: Optional[dict],
        min_gbps: float = 0.0,
        ring_min_gbps: float = 0.0,
        results_scope: str = "",
        budget_seconds: float = 0.0,
        cache_key_env: Optional[dict] = None,
    ) -> dict:
        """Build the workload pod (plugin-workload-validation.yaml analogue,
        validator/main.go:984-1052: node pinning, resource request, ownerRef
        + tolerations copied from the validator DaemonSet).  ``min_gbps``
        arms the allreduce busbw gate and ``ring_min_gbps`` the per-link
        ring gate (catalogue-derived for multi-chip workloads; 0 keeps them
        report-only).  ``results_scope`` namespaces the measured-results
        drop-box (the perf probes must not clobber the gating run's
        figures)."""
        image = self.config.workload_image or "ghcr.io/tpu-operator/tpu-validator:latest"
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "namespace": self.config.namespace,
                "labels": {"app": name},
            },
            "spec": {
                "nodeName": self.config.node_name,
                "restartPolicy": "Never",
                "containers": [
                    {
                        "name": "workload",
                        "image": image,
                        "command": ["python", "-m", "tpu_operator.workloads.run_validation"],
                        "env": [
                            {"name": "WORKLOAD_CHECKS", "value": checks},
                            {"name": "ALLREDUCE_MIN_GBPS", "value": str(min_gbps)},
                            {"name": "RING_MIN_GBPS", "value": str(ring_min_gbps)},
                            # device-count truth: the pod requested this many
                            # chips; PJRT inside it must initialize exactly
                            # that many (collectives.device_count_check)
                            {"name": "EXPECTED_DEVICES", "value": str(tpu_request)},
                            # node-local persistent XLA cache: re-validations
                            # (preStop re-gating, upgrade re-proof) skip the
                            # ~2s/program recompiles (workloads/compile_cache.py)
                            {"name": "TPU_COMPILE_CACHE", "value": COMPILE_CACHE_HOST_PATH},
                            # compile-ARTIFACT store beside it: serialized
                            # executables keyed on (generation, topology,
                            # versions, program), prewarmed from the fleet
                            # cache before the first jit trace
                            {
                                "name": "TPU_COMPILE_CACHE_ARTIFACTS",
                                "value": COMPILE_CACHE_HOST_PATH + "/artifacts",
                            },
                            # the seeding-plane contract: fleet cache URL
                            # (DS-rendered into the validator's own env)
                            # plus the cache-key fields — an explicit env
                            # wins, else the node's own labels (computed
                            # by spawn_workload) fill them in
                            *(
                                [{"name": name, "value": value}
                                 for name in ("TPU_FLEET_CACHE_URL",
                                              "TPU_CACHE_GENERATION",
                                              "TPU_CACHE_TOPOLOGY",
                                              "TPU_LIBTPU_VERSION")
                                 for value in (
                                     os.environ.get(name)
                                     or (cache_key_env or {}).get(name, ""),
                                 )
                                 if value]
                            ),
                            *(
                                [{"name": "RESULTS_SCOPE", "value": results_scope}]
                                if results_scope
                                else []
                            ),
                            # live telemetry: the pod's flight recorder
                            # pushes to the node metrics agent when the
                            # validator knows its address (DS-injected)
                            *(
                                [{
                                    "name": "TPU_METRICS_PUSH_URL",
                                    "value": os.environ["TPU_METRICS_PUSH_URL"],
                                }]
                                if os.environ.get("TPU_METRICS_PUSH_URL")
                                else []
                            ),
                            # cross-process trace propagation: the spawned
                            # pod continues the validator's ACTIVE span
                            # when one is live (its samples link under the
                            # validate/<component> phase), else relays the
                            # DS-injected rollout context verbatim
                            *(
                                [{
                                    "name": trace.TRACEPARENT_ENV,
                                    "value": (
                                        trace.current_traceparent()
                                        or os.environ[trace.TRACEPARENT_ENV]
                                    ),
                                }]
                                if trace.current_traceparent()
                                or os.environ.get(trace.TRACEPARENT_ENV)
                                else []
                            ),
                            # the probe pod stops STARTING checks past this
                            # budget (run_validation; skipped, not failed)
                            *(
                                [{
                                    "name": "WORKLOAD_BUDGET_S",
                                    "value": str(budget_seconds),
                                }]
                                if budget_seconds
                                else []
                            ),
                        ],
                        "resources": {
                            "limits": {consts.TPU_RESOURCE: str(tpu_request)},
                            "requests": {consts.TPU_RESOURCE: str(tpu_request)},
                        },
                        "volumeMounts": [
                            # exactly two narrow identity mounts: the cache
                            # and the measured-results drop-box — NOT the
                            # validations ready markers or the worker-id/
                            # slice-config handoff files a misbehaving
                            # workload could forge or corrupt
                            {
                                "name": "compile-cache",
                                "mountPath": COMPILE_CACHE_HOST_PATH,
                            },
                            {
                                "name": "workload-results",
                                "mountPath": consts.WORKLOAD_RESULTS_DIR,
                            },
                        ],
                    }
                ],
                "volumes": [
                    {
                        "name": "compile-cache",
                        "hostPath": {
                            "path": COMPILE_CACHE_HOST_PATH,
                            "type": "DirectoryOrCreate",
                        },
                    },
                    {
                        "name": "workload-results",
                        "hostPath": {
                            "path": consts.WORKLOAD_RESULTS_DIR,
                            "type": "DirectoryOrCreate",
                        },
                    },
                ],
            },
        }
        if owner is not None:
            from tpu_operator.k8s import objects as obj_api

            obj_api.set_owner_reference(pod, owner)
            tolerations = deep_get(owner, "spec", "template", "spec", "tolerations")
            if tolerations:
                pod["spec"]["tolerations"] = copy.deepcopy(tolerations)
        return pod

    async def spawn_workload(
        self,
        name: str,
        checks: str,
        tpu_request: int,
        min_gbps: float = 0.0,
        ring_min_gbps: float = 0.0,
        results_scope: str = "",
        budget_seconds: float = 0.0,
    ) -> None:
        client = self.client()
        owner = await self._owner_daemonset()
        # cache-key fields for the compile-artifact plane, from the node's
        # own labels (raw values — the same vocabulary the revalidation
        # coordinator's node_kind uses); best-effort: a node without TPU
        # labels just leaves the fields empty and keying stays node-local
        cache_key_env: dict = {}
        if self.config.node_name:
            try:
                node = await client.get("", "Node", self.config.node_name)
                labels = deep_get(node, "metadata", "labels", default={}) or {}
                cache_key_env = {
                    "TPU_CACHE_GENERATION": labels.get(
                        consts.GKE_TPU_ACCELERATOR_LABEL, ""
                    ),
                    "TPU_CACHE_TOPOLOGY": labels.get(
                        consts.GKE_TPU_TOPOLOGY_LABEL, ""
                    ),
                    "TPU_LIBTPU_VERSION": labels.get(
                        consts.TFD_RUNTIME_VERSION_LABEL, ""
                    ),
                }
            except ApiError:
                pass
        pod = self._workload_pod(
            name, checks, tpu_request, owner, min_gbps=min_gbps,
            ring_min_gbps=ring_min_gbps, results_scope=results_scope,
            budget_seconds=budget_seconds, cache_key_env=cache_key_env,
        )
        await client.delete("", "Pod", name, self.config.namespace)
        await client.create(pod)
        for _ in range(self.config.workload_retries):
            live = await client.get("", "Pod", name, self.config.namespace)
            phase = deep_get(live, "status", "phase")
            if phase == "Succeeded":
                return
            if phase == "Failed":
                raise ValidationError(f"workload pod {name} failed")
            await asyncio.sleep(self.config.sleep_interval)
        raise ValidationError(f"workload pod {name} did not complete (phase={phase})")
