"""Validator node-metrics mode.

Reference analogue: validator/metrics.go:39-300 — Prometheus gauges mirroring
the status files plus a host device count (their lspci, our /dev/accel*).
Also the implementation behind the node-status-exporter operand.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from aiohttp import web
from prometheus_client import CollectorRegistry, Gauge, generate_latest

from tpu_operator import consts, hw
from tpu_operator.validator import status

log = logging.getLogger("tpu_operator.validator.metrics")


class NodeMetrics:
    def __init__(
        self,
        registry: Optional[CollectorRegistry] = None,
        node_name: Optional[str] = None,
    ):
        import os

        self.registry = registry or CollectorRegistry()
        # every series carries the NODE name: Prometheus's `instance` is
        # the scrape endpoint (podIP:port) — alert runbooks and the
        # remediation channel (`kubectl label node ...`) need the real
        # node, which the DS injects via the downward API (NODE_NAME)
        self.node_name = node_name or os.environ.get("NODE_NAME", "unknown")
        self.validation_status = Gauge(
            "tpu_validator_validation_status",
            "1 when the component's validation status file is present",
            ["node", "component"],
            registry=self.registry,
        )
        self.device_count = Gauge(
            "tpu_validator_tpu_device_count",
            "TPU chip device nodes visible on the host",
            ["node"],
            registry=self.registry,
        )
        # measured perf from the jax validation payload (the numbers the
        # reference never had: MFU, HBM-local allreduce, per-link ring) —
        # a label family so series only materialize for measured metrics
        self.perf = Gauge(
            "tpu_validator_measured",
            "Perf numbers measured by the last jax validation",
            ["node", "metric"],
            registry=self.registry,
        )

    # jax-payload key → exported metric label (set only when present)
    PERF_KEYS = {
        "algbw_gbps": "allreduce_gbps",
        "matmul_tflops": "matmul_tflops",
        "mfu": "mfu",
        "ring_link_gbps": "ring_link_gbps",
        "workers": "slice_workers",
        "allreduce_min_gbps": "allreduce_min_gbps",
        # the ring alert's floor: per-LINK (catalogue aggregate / link
        # count), NEVER the multi-link allreduce busbw floor — a single
        # link legitimately runs at aggregate/links, which can sit at or
        # below the allreduce floor on healthy hardware (ADVICE r03)
        "ring_min_gbps": "ring_min_gbps",
        "hbm_gbps": "hbm_gbps",
        # pallas DMA-pipeline cross-check (VPU-free): compare against
        # hbm_gbps to isolate memory-system vs compute-pipeline faults
        "hbm_dma_gbps": "hbm_dma_gbps",
        "hbm_fraction_of_peak": "hbm_fraction_of_peak",
    }

    def scrape(self) -> None:
        for component in consts.STATUS_FILES:
            self.validation_status.labels(
                node=self.node_name, component=component
            ).set(1 if status.is_ready(component) else 0)
        self.device_count.labels(node=self.node_name).set(hw.chip_count())
        payload = status.read_status("jax") or {}
        # the post-ready perf probes carry the matmul/hbm/ring figures in
        # their own status file; merge ONLY the measurement keys over the
        # jax payload (never its ok/error bookkeeping)
        perf = status.read_status("perf") or {}
        payload = {
            **payload,
            **{k: v for k, v in perf.items() if k in self.PERF_KEYS},
        }
        # re-derive the whole family each scrape: a metric absent from the
        # CURRENT payload must stop being served, not linger from an older
        # validation round (serve mode scrapes repeatedly)
        self.perf.clear()

        def _set(metric: str, value) -> None:
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                self.perf.labels(node=self.node_name, metric=metric).set(value)

        for key, metric in self.PERF_KEYS.items():
            _set(metric, payload.get(key))
        ms = payload.get("multislice")
        if isinstance(ms, dict):
            _set("multislice_workers", ms.get("workers"))
            # DCN figures under their own names — never conflated with ICI
            _set("multislice_allreduce_gbps", ms.get("algbw_gbps"))
            _set("multislice_ring_link_gbps", ms.get("ring_link_gbps"))

    def render(self) -> bytes:
        return generate_latest(self.registry)


async def serve_metrics(port: int, oneshot: bool = False, interval: float = 5.0) -> None:
    metrics = NodeMetrics()
    metrics.scrape()
    if oneshot:
        print(metrics.render().decode())
        return

    async def handler(request: web.Request) -> web.Response:
        metrics.scrape()
        return web.Response(body=metrics.render(), content_type="text/plain")

    app = web.Application()
    app.router.add_get("/metrics", handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "0.0.0.0", port)
    await site.start()
    log.info("validator metrics serving on :%d", port)
    try:
        while True:
            await asyncio.sleep(interval)
    finally:
        await runner.cleanup()
