"""Validation status files.

Reference analogue: validator/main.go:131-166 — files like ``driver-ready``
under /run/nvidia/validations; here ``libtpu-ready``/``pjrt-ready``/
``plugin-ready``/``jax-ready`` under /run/tpu/validations, relocatable via
``TPU_VALIDATION_ROOT`` (UNIT_TEST seam analogue).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from tpu_operator import consts


def validation_dir() -> str:
    root = os.environ.get(consts.VALIDATION_ROOT_ENV)
    if root:
        return os.path.join(root, "validations")
    return consts.VALIDATION_DIR


def slice_config_path() -> str:
    """/run/tpu/slice_config.json — the applied partition layout, written by
    the slice manager and read by the device plugin for mixed-strategy
    resource naming."""
    return os.path.join(os.path.dirname(validation_dir()), "slice_config.json")


def worker_id_path() -> str:
    """/run/tpu/worker_id — the handoff file between tpu-feature-discovery
    (writer) and node-local daemons without apiserver access, e.g. the device
    plugin's Allocate env (reader)."""
    return os.path.join(os.path.dirname(validation_dir()), "worker_id")


def status_path(component: str) -> str:
    name = consts.STATUS_FILES.get(component, f"{component}-ready")
    return os.path.join(validation_dir(), name)


def write_ready(component: str, payload: Optional[dict] = None) -> str:
    path = status_path(component)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    data = {"component": component, "ts": time.time(), **(payload or {})}
    # tmp+replace: the ready markers gate the whole init chain — a reader
    # (validator, exporter, upgrade controller) must never parse a torn one
    tmp = path + f".{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(data, f)
    os.replace(tmp, path)
    return path


def is_ready(component: str) -> bool:
    return os.path.exists(status_path(component))


def read_status(component: str) -> Optional[dict]:
    try:
        with open(status_path(component)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def clear(component: str) -> None:
    try:
        os.remove(status_path(component))
    except OSError:
        pass


def cleanup_all() -> int:
    """--cleanup-all: remove every *-ready file (validator preStop pattern,
    assets/state-operator-validation/0500_daemonset.yaml:150-153)."""
    d = validation_dir()
    removed = 0
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    for name in names:
        if name.endswith("-ready"):
            try:
                os.remove(os.path.join(d, name))
                removed += 1
            except OSError:
                pass
    return removed


def write_marker(name: str) -> str:
    """Dot-file markers for intra-chain handoff (.libtpu-ctr-ready analogue
    of .driver-ctr-ready, validator/main.go:606-635); tmp+replace so a
    handoff reader never sees a half-written timestamp."""
    path = os.path.join(validation_dir(), name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f".{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(str(time.time()))
    os.replace(tmp, path)
    return path


def marker_exists(name: str) -> bool:
    return os.path.exists(os.path.join(validation_dir(), name))


def workload_results_path(scope: str = "") -> str:
    """Node-local drop-box for the measured numbers of the LAST validation
    workload run on this host (workload pods mount exactly this subdir, so
    the validator — and through it the node-status exporter — can surface
    busbw/MFU/ring figures the pod measured; pod logs would need an extra
    API round trip and log-parsing).  ``scope`` separates rendezvous kinds:
    the cross-slice (DCN) run must not overwrite the slice's ICI figures."""
    root = os.path.dirname(validation_dir())
    suffix = f"-{scope}" if scope else ""
    return os.path.join(root, "workload-results", f"results{suffix}.json")


def write_workload_results(results: dict, scope: str = "") -> None:
    """Best-effort: measurement evidence must never fail a validation —
    including a non-serializable value (stray numpy scalar) raising
    TypeError, which would flip a PASSED validation pod to Failed if it
    escaped (callers invoke this outside their check try/except)."""
    try:
        path = workload_results_path(scope)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # per-process tmp name: local workers sharing one validation root
        # (spawn_local_workers, single-host multislice dryrun) must not
        # interleave writes inside one shared tmp file; os.replace keeps
        # the publish itself atomic, last writer wins whole-file
        tmp = path + f".{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"ts": time.time(), **results}, f)
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 — evidence is optional, the verdict is not
        pass


def clear_workload_results(scope: str = "") -> None:
    """Drop a scope's measured evidence (the perf component clears before
    each probe run so a failed run can never republish stale figures)."""
    try:
        os.remove(workload_results_path(scope))
    except OSError:
        pass


def read_workload_results(scope: str = "") -> Optional[dict]:
    try:
        with open(workload_results_path(scope)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def flight_record_path(scope: str = "") -> str:
    """JSONL flight record (obs.flight per-step samples) of the LAST
    validation/bench workload run, beside the results drop-box so workload
    pods reach it through the same mount.  Scoped like the results file."""
    root = os.path.dirname(validation_dir())
    suffix = f"-{scope}" if scope else ""
    return os.path.join(root, "workload-results", f"flight{suffix}.jsonl")


def read_flight_record(scope: str = "") -> list[dict]:
    """Parsed flight samples; a torn or missing record reads as fewer
    samples, never an error (evidence is best-effort)."""
    samples: list[dict] = []
    try:
        with open(flight_record_path(scope)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    samples.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        return []
    return samples


def clear_flight_record(scope: str = "") -> None:
    try:
        os.remove(flight_record_path(scope))
    except OSError:
        pass


def join_phase_segments(node_created_ts: Optional[float] = None) -> dict:
    """Derive this node's join→validated critical-path segments
    (obs/fleet.py ``JOIN_PHASES``) from evidence already on disk: the
    ``ts`` stamps the status files carry and the flight record's compile
    samples.  The segments telescope — their sum is jax-ready minus node
    creation — so the fleet's per-phase rollups reconcile against
    ``join_to_validated_seconds`` instead of being a separate estimate.

    Absent files contribute nothing (a partially-joined node reports the
    segments it has; ``/debug/explain`` turns the first missing one into
    the blocking verdict).  Best-effort like all evidence."""

    def ts(component: str) -> Optional[float]:
        st = read_status(component)
        value = st.get("ts") if st else None
        return float(value) if isinstance(value, (int, float)) else None

    libtpu, pjrt, plugin, jax_ready = (
        ts("libtpu"), ts("pjrt"), ts("plugin"), ts("jax")
    )
    phases: dict = {}
    if libtpu is not None and node_created_ts is not None:
        phases["runtime-ready"] = max(0.0, libtpu - node_created_ts)
    if pjrt is not None and libtpu is not None:
        phases["validator-scheduled"] = max(0.0, pjrt - libtpu)
    if plugin is not None and pjrt is not None:
        phases["plugin-advertised"] = max(0.0, plugin - pjrt)
    if jax_ready is not None and plugin is not None:
        tail_s = max(0.0, jax_ready - plugin)
        # compile time from the flight record: per check, the largest
        # compile_s sample (re-records of the same check must not double
        # count), summed across checks, clamped into the gate tail
        compile_s = 0.0
        per_check: dict = {}
        for sample in read_flight_record():
            value = (sample.get("metrics") or {}).get("compile_s")
            if isinstance(value, (int, float)) and value >= 0:
                check = sample.get("check", "")
                per_check[check] = max(per_check.get(check, 0.0), float(value))
        compile_s = min(tail_s, sum(per_check.values()))
        phases["compile"] = compile_s
        phases["collective"] = max(0.0, tail_s - compile_s)
    return {k: round(v, 6) for k, v in phases.items()}


def flight_evidence(scope: str = "", tail: int = 50) -> Optional[dict]:
    """The flight record as ready-payload evidence: record path, sample
    count, the span ids the samples carry (joinable against
    ``/debug/traces``), and the newest ``tail`` samples — bounded so a
    long bench cannot balloon a status file."""
    samples = read_flight_record(scope)
    if not samples:
        return None
    span_ids = sorted({s["span_id"] for s in samples if s.get("span_id")})
    return {
        "path": flight_record_path(scope),
        "samples": len(samples),
        "checks": sorted({s.get("check", "") for s in samples}),
        "span_ids": span_ids,
        "tail": samples[-tail:],
    }
