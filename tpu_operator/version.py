"""Version info, stamped at build time.

Reference analogue: internal/info/version.go + ldflags stamping (Makefile:91-94).
"""

__version__ = "0.1.0"
GIT_COMMIT = "unknown"


def version_string() -> str:
    return f"tpu-operator {__version__} (commit {GIT_COMMIT})"
