"""JAX validation/burn-in workloads — the operator's TPU compute payloads.

Reference analogue: the CUDA vectorAdd image the validator spawns
(validator/main.go:1189-1302) and the plugin workload pod (:941-1028).  The
TPU replacements are real XLA programs: a pallas vector-add for single-chip
sanity, a psum allreduce over ICI with achieved-bandwidth reporting, and a
sharded burn-in step exercising the MXU + collectives across a device mesh.
"""
