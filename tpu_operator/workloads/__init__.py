"""JAX validation/burn-in workloads — the operator's TPU compute payloads.

Reference analogue: the CUDA vectorAdd image the validator spawns
(validator/main.go:1189-1302) and the plugin workload pod (:941-1028).  The
TPU replacements are real XLA programs: a pallas vector-add for single-chip
sanity, a psum allreduce over ICI with achieved-bandwidth reporting, and a
sharded burn-in step exercising the MXU + collectives across a device mesh.
Beyond validation, the package carries the migratable-checkpoint layer
(checkpoint.py, docs/ROBUSTNESS.md "Live migration") and the sustained-
serving engine (serving.py: continuous batching over a paged KV cache,
docs/SERVING.md) — the payloads the chaos soaks drain and restore.
"""

import os


def subprocess_pythonpath() -> str:
    """PYTHONPATH value for a spawned worker that re-imports this package
    via ``python -m``: the parent's package root prepended to the existing
    PYTHONPATH.  Covers the ImportError case (worker launched from a cwd
    without the package — e.g. the dryrun invoked outside the repo).  It
    does NOT pin the worker to the parent's copy: ``-m`` still puts the
    child's cwd at sys.path[0], ahead of PYTHONPATH — don't launch from a
    directory containing a different checkout.  One home for the contract,
    used by every subprocess-spawning workload harness."""
    import tpu_operator

    root = os.path.dirname(os.path.dirname(os.path.abspath(tpu_operator.__file__)))
    existing = os.environ.get("PYTHONPATH", "")
    return root + os.pathsep + existing if existing else root


def honor_cpu_platform_request() -> None:
    """Apply a caller's JAX_PLATFORMS=cpu request decisively.

    A TPU-plugin sitecustomize may rewrite the env var at interpreter start
    (before any entry point runs); the pre-backend-init config update wins
    regardless.  Must be called before the first backend use.  One home for
    the guard every workload entry point needs."""
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        import jax

        jax.config.update("jax_platforms", "cpu")
