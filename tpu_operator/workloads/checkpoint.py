"""Migratable training state: atomic sharded checkpoints + reshard-on-restore.

The drain paths used to end a training job with ``client.delete`` — the
job's progress died with the node.  This module is the workload half of the
live-migration story (CRIUgpu: checkpoint/restore is the production answer
to planned disruption; Tenplex: a checkpoint is a *parallelizable tensor
collection* — taken under one slice shape, restorable under another):

- :func:`save_checkpoint` writes an atomic snapshot: every array is dumped
  as its device shards (raw bytes + the shard's *global index ranges* +
  a content hash), the manifest (step, mesh shape, partition specs, hashes)
  is written last inside a temp directory, and the whole directory is
  published with ``os.replace`` — a ``LATEST`` pointer (itself tmp+replace)
  names the newest complete snapshot.  A crash at ANY byte leaves the
  previous snapshot authoritative; a torn snapshot is never observable.
- :func:`load_checkpoint` verifies the manifest (version, shard presence,
  sizes, content hashes) and falls back to the next-newest *valid* snapshot
  on any corruption.  Because shards carry global index ranges rather than
  device ranks, restore reassembles the global tensors and re-places them
  under ANY target mesh — a job checkpointed on a 4x4 mesh resumes on 2x4
  bitwise-identically.
- :class:`Checkpointer` serializes snapshot requests (concurrent requests
  coalesce onto the in-flight snapshot) and owns retention.
- :class:`MigrationSignal` watches the drain signal: the pod annotation
  ``tpu.google.com/migrate=requested`` via a downward-API annotations file
  (``TPU_MIGRATE_SIGNAL_FILE``), with SIGTERM as the fallback for clusters
  that deliver nothing richer.
- :func:`main` is a reference migratable training job (the chaos-migrate
  soak's payload): a real sharded SGD loop over the ``TPU_JOB_TOPOLOGY``
  mesh that checkpoints every ``TPU_CKPT_EVERY`` steps and on the drain
  signal, then exits 0 — the "checkpoint complete" status the migration
  coordinator awaits — and restores (resharding) on the next launch.

Every phase is recorded on the ambient flight recorder (obs.flight), so a
migration shows up in the job's flight record as checkpoint/restore phases
joinable against the operator's trace ids.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import re
import signal
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from tpu_operator import consts
from tpu_operator.obs import flight
from tpu_operator.obs import profile as obs_profile

MANIFEST_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
LATEST_NAME = "LATEST"
# highest step the training loop ever COMPLETED on this checkpoint dir
# (vs LATEST, the newest durable snapshot): the gap between the two at
# restore time IS the lost-step delta — derived from stamps on disk, not
# inferred from timings (obs/accounting.py busy_wasted evidence)
HIGHWATER_NAME = "HIGHWATER"
_STEP_DIR_RE = re.compile(r"^step-(\d{8})$")

# fault-injection env (testing/chaos.py checkpoint faults): applied to
# signal-triggered (final) snapshots only, so periodic snapshots stay good
# and the soak can prove a torn final snapshot never shadows them.
#   kill      SIGKILL self after the shard files, before the manifest
#   slow:<s>  sleep <s> seconds mid-snapshot (drives the timeout->evict path)
FAULT_ENV = "TPU_CKPT_FAULT"


class CheckpointError(Exception):
    """A snapshot that must not be trusted (torn manifest, hash mismatch)."""


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _np_dtype(name: str):
    """numpy dtype for a manifest dtype name; bfloat16 etc. resolve through
    ml_dtypes (always present beside jax)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _shards_of(value) -> list[tuple[tuple, np.ndarray]]:
    """(global index ranges, host data) per distinct shard of ``value``.

    jax arrays contribute their addressable shards deduplicated by global
    index (replicated dims put the same shard on many devices); anything
    else is one full-coverage shard.  Index ranges — not device ranks — are
    what make the collection restorable under a different mesh."""
    shards = getattr(value, "addressable_shards", None)
    if shards is None:
        arr = np.asarray(value)
        index = tuple((0, d) for d in arr.shape)
        return [(index, arr)]
    seen: dict[tuple, np.ndarray] = {}
    shape = value.shape
    for shard in shards:
        index = tuple(
            (sl.start or 0, sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(shard.index, shape)
        )
        if index not in seen:
            seen[index] = np.asarray(shard.data)
    return sorted(seen.items())


def save_checkpoint(
    ckpt_dir: str,
    step: int,
    arrays: dict,
    mesh_shape: Optional[tuple] = None,
    specs: Optional[dict] = None,
    extra: Optional[dict] = None,
    keep: int = 2,
    fault: Optional[Callable[[], None]] = None,
) -> str:
    """Write one atomic snapshot; returns the published snapshot dir.

    ``specs`` maps array name -> partition spec as a list (e.g.
    ``["dp", None]``: dim 0 sharded over the mesh's dp axis) recorded for
    restore-time placement.  ``fault`` is the test seam invoked after the
    shard files exist but before the manifest — the canonical torn point.
    """
    final = os.path.join(ckpt_dir, f"step-{step:08d}")
    tmp = final + f".tmp-{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    t0 = time.perf_counter()
    manifest: dict = {
        "version": MANIFEST_VERSION,
        "step": int(step),
        "mesh": list(mesh_shape) if mesh_shape else None,
        "ts": time.time(),
        "arrays": {},
        "extra": extra or {},
    }
    for name, value in arrays.items():
        dtype = getattr(value, "dtype", None) or np.asarray(value).dtype
        entry: dict = {
            "shape": list(np.shape(value)),
            "dtype": str(dtype.name),
            "spec": list((specs or {}).get(name) or []),
            "shards": [],
        }
        for i, (index, data) in enumerate(_shards_of(value)):
            fname = f"{name}-{i:05d}.bin"
            blob = np.ascontiguousarray(data).tobytes()
            with open(os.path.join(tmp, fname), "wb") as f:
                f.write(blob)
            entry["shards"].append({
                "file": fname,
                "index": [list(r) for r in index],
                "bytes": len(blob),
                "sha256": _sha256(blob),
            })
        manifest["arrays"][name] = entry
    if fault is not None:
        fault()  # torn point: shards on disk, no manifest yet
    # manifest last, inside the tmp dir, itself via tmp+replace; then the
    # directory rename publishes the snapshot as one atomic unit
    mtmp = os.path.join(tmp, MANIFEST_NAME + ".tmp")
    with open(mtmp, "w") as f:
        json.dump(manifest, f)
    os.replace(mtmp, os.path.join(tmp, MANIFEST_NAME))
    if os.path.isdir(final):
        _rmtree(final)  # a re-snapshot of the same step replaces it whole
    os.replace(tmp, final)
    _publish_latest(ckpt_dir, os.path.basename(final))
    _gc(ckpt_dir, keep=keep)
    flight.record(
        "migration", "checkpoint", step=step,
        checkpoint_s=time.perf_counter() - t0,
        arrays=float(len(arrays)),
    )
    return final


def _publish_latest(ckpt_dir: str, name: str) -> None:
    tmp = os.path.join(ckpt_dir, LATEST_NAME + f".tmp-{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(name)
    os.replace(tmp, os.path.join(ckpt_dir, LATEST_NAME))


def publish_highwater(ckpt_dir: str, step: int) -> None:
    """Stamp the highest completed step (same tmp+replace publish as
    LATEST: a torn write can only leave the previous stamp)."""
    tmp = os.path.join(ckpt_dir, HIGHWATER_NAME + f".tmp-{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(str(int(step)))
    os.replace(tmp, os.path.join(ckpt_dir, HIGHWATER_NAME))


def read_highwater(ckpt_dir: str) -> int:
    """The step the job had reached when it last ran, or -1 when no stamp
    (fresh dir / pre-upgrade layout)."""
    try:
        with open(os.path.join(ckpt_dir, HIGHWATER_NAME)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return -1


def _rmtree(path: str) -> None:
    import shutil

    shutil.rmtree(path, ignore_errors=True)


def _snapshot_dirs(ckpt_dir: str) -> list[str]:
    """Complete snapshot dir names, newest first (tmp debris excluded)."""
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return []
    return sorted((n for n in names if _STEP_DIR_RE.match(n)), reverse=True)


def _gc(ckpt_dir: str, keep: int) -> None:
    for name in _snapshot_dirs(ckpt_dir)[keep:]:
        _rmtree(os.path.join(ckpt_dir, name))
    # stale tmp dirs from crashed snapshots are debris, not evidence
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return
    for name in names:
        if ".tmp-" in name and not os.path.isfile(os.path.join(ckpt_dir, name)):
            _rmtree(os.path.join(ckpt_dir, name))


def _read_manifest(snap_dir: str) -> dict:
    """Parse one snapshot's manifest and validate its STRUCTURE; raises
    CheckpointError on a missing/truncated/malformed manifest.  Shard
    content (presence, size, hash) is verified by :func:`_assemble` on the
    single read that also reconstructs the tensors — multi-GB checkpoints
    must not pay restore I/O twice."""
    path = os.path.join(snap_dir, MANIFEST_NAME)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointError(f"unreadable manifest at {path}: {e}") from e
    if manifest.get("version") != MANIFEST_VERSION:
        raise CheckpointError(
            f"manifest version {manifest.get('version')!r} != {MANIFEST_VERSION}"
        )
    if not isinstance(manifest.get("arrays"), dict) or "step" not in manifest:
        raise CheckpointError(f"malformed manifest at {path}")
    return manifest


@dataclass
class Checkpoint:
    """One verified snapshot, reassembled: global numpy arrays (or, when a
    target mesh was given, jax arrays placed under the recorded specs)."""

    step: int
    arrays: dict
    mesh_shape: Optional[tuple]
    specs: dict
    path: str
    extra: dict = field(default_factory=dict)


def _assemble(snap_dir: str, entry: dict) -> np.ndarray:
    """Reconstruct one global array, verifying every shard (presence, size,
    content hash) on the same single read; raises CheckpointError on any
    tear so the caller falls back to an older complete snapshot."""
    shape = tuple(entry["shape"])
    out = np.empty(shape, dtype=_np_dtype(entry["dtype"]))
    for shard in entry["shards"]:
        spath = os.path.join(snap_dir, shard.get("file", ""))
        try:
            with open(spath, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise CheckpointError(f"missing shard {spath}: {e}") from e
        if len(blob) != shard.get("bytes"):
            raise CheckpointError(
                f"shard {spath} truncated: {len(blob)} != {shard.get('bytes')}"
            )
        if _sha256(blob) != shard.get("sha256"):
            raise CheckpointError(f"shard {spath} content hash mismatch")
        index = tuple(slice(a, b) for a, b in shard["index"])
        piece_shape = tuple(b - a for a, b in shard["index"])
        out[index] = np.frombuffer(blob, dtype=out.dtype).reshape(piece_shape)
    return out


def load_checkpoint(ckpt_dir: str, mesh=None) -> Optional[Checkpoint]:
    """Newest *valid* snapshot, or None.  Corrupt snapshots (torn manifest,
    hash mismatch) are skipped — never restored — and the scan falls back
    to older complete ones; the LATEST pointer is an optimization, the
    manifest verification is the authority."""
    order = _snapshot_dirs(ckpt_dir)
    try:
        with open(os.path.join(ckpt_dir, LATEST_NAME)) as f:
            latest = f.read().strip()
        if latest in order:  # try the pointer first
            order = [latest] + [n for n in order if n != latest]
    except OSError:
        pass
    t0 = time.perf_counter()
    for name in order:
        snap_dir = os.path.join(ckpt_dir, name)
        try:
            manifest = _read_manifest(snap_dir)
            arrays = {
                aname: _assemble(snap_dir, entry)
                for aname, entry in manifest["arrays"].items()
            }
        except CheckpointError:
            continue
        specs = {
            aname: tuple(entry.get("spec") or ())
            for aname, entry in manifest["arrays"].items()
        }
        if mesh is not None:
            arrays = {
                aname: _place(mesh, arrays[aname], specs[aname])
                for aname in arrays
            }
        ckpt = Checkpoint(
            step=int(manifest["step"]),
            arrays=arrays,
            mesh_shape=tuple(manifest["mesh"]) if manifest.get("mesh") else None,
            specs=specs,
            path=snap_dir,
            extra=manifest.get("extra") or {},
        )
        # lost-step delta derived from on-disk stamps: HIGHWATER is where
        # the killed process stood, the manifest step is where this one
        # resumes — everything between is recompute (busy_wasted)
        step_at_kill = read_highwater(ckpt_dir)
        flight.record(
            "migration", "restore", step=ckpt.step,
            restore_s=time.perf_counter() - t0,
            arrays=float(len(arrays)),
            step_at_kill=float(step_at_kill),
            step_at_restore=float(ckpt.step),
            lost_steps=float(max(0, step_at_kill - ckpt.step)),
        )
        return ckpt
    return None


def _place(mesh, array: np.ndarray, spec: tuple):
    """Device-place a reassembled global array under ``mesh`` with its
    recorded partition spec — the Tenplex reshard: the collection carries
    global index ranges, so ANY mesh shape reconstructs bitwise-equal
    tensors, just cut along different lines."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    names = tuple(spec[i] if i < len(spec) else None for i in range(array.ndim))
    sharding = NamedSharding(mesh, P(*names))
    return jax.make_array_from_callback(
        array.shape, sharding, lambda idx: array[idx]
    )


class Checkpointer:
    """Snapshot coordinator: serializes writes, coalesces concurrent
    requests, applies the seeded chaos faults to *final* snapshots only."""

    def __init__(self, ckpt_dir: str, keep: int = 2):
        self.ckpt_dir = ckpt_dir
        self.keep = keep
        self._lock = threading.Lock()
        self._saving = False
        self._last_step: Optional[int] = None
        self._last_path: Optional[str] = None

    def save(
        self,
        step: int,
        arrays: dict,
        mesh_shape: Optional[tuple] = None,
        specs: Optional[dict] = None,
        extra: Optional[dict] = None,
        final: bool = False,
    ) -> Optional[str]:
        """Snapshot ``step``; concurrent callers coalesce — while a snapshot
        is being written, other requests return the in-flight/previous path
        instead of racing a second writer into the same directory.  A
        re-request of an already-persisted step is a no-op — EXCEPT for
        ``final`` (signal-triggered) snapshots, which ALWAYS write: the
        drain signal can land exactly on a periodic-checkpoint step, and
        the migration snapshot is the authoritative one (it may carry
        state the periodic pass did not, and skipping it would also skip
        the chaos fault seam the torn-snapshot soak drives through it).
        A final request that races an in-flight periodic save therefore
        WAITS for the writer to finish and then writes its own snapshot,
        instead of returning the stale path — exiting 0 on a snapshot that
        never ran would hand the coordinator a false checkpoint-complete."""
        while True:
            with self._lock:
                if not self._saving:
                    if self._last_step == step and not final:
                        return self._last_path
                    self._saving = True
                    break
                if not final:
                    return self._last_path
            time.sleep(0.01)  # final: outwait the in-flight writer
        try:
            path = save_checkpoint(
                self.ckpt_dir, step, arrays, mesh_shape=mesh_shape,
                specs=specs, extra=extra, keep=self.keep,
                fault=_env_fault() if final else None,
            )
            with self._lock:
                self._last_step, self._last_path = step, path
            return path
        finally:
            with self._lock:
                self._saving = False


def _env_fault() -> Optional[Callable[[], None]]:
    """The chaos checkpoint fault as a callable, from TPU_CKPT_FAULT."""
    spec = os.environ.get(FAULT_ENV, "")
    if not spec:
        return None
    kind, _, arg = spec.partition(":")

    def fault() -> None:
        if kind == "kill":
            print(json.dumps({"fault_injected": "kill-during-checkpoint"}),
                  flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        elif kind == "slow":
            try:
                time.sleep(float(arg or 0))
            except ValueError:
                pass

    return fault


class MigrationSignal:
    """The drain signal, from either channel:

    - downward-API annotations file (``TPU_MIGRATE_SIGNAL_FILE``): the pod
      mounts ``metadata.annotations`` and the kubelet rewrites the file when
      the migration coordinator stamps ``tpu.google.com/migrate=requested``
      — the rich channel, no API access needed in the workload;
    - SIGTERM: the fallback every Kubernetes eviction already delivers.
    """

    def __init__(self, annotations_file: Optional[str] = None,
                 install_sigterm: bool = True):
        self.annotations_file = (
            annotations_file
            if annotations_file is not None
            else os.environ.get(consts.MIGRATE_SIGNAL_FILE_ENV, "")
        )
        self._sigterm = threading.Event()
        if install_sigterm:
            try:
                signal.signal(signal.SIGTERM, self._on_sigterm)
            except ValueError:
                pass  # non-main thread (tests): file channel only

    def _on_sigterm(self, signum, frame) -> None:
        self._sigterm.set()

    @property
    def sigterm(self) -> bool:
        return self._sigterm.is_set()

    def requested(self) -> bool:
        if self._sigterm.is_set():
            return True
        if not self.annotations_file:
            return False
        try:
            with open(self.annotations_file) as f:
                text = f.read()
        except OSError:
            return False
        return self._parse(text)

    @staticmethod
    def _parse(text: str) -> bool:
        """Downward-API format (``key="value"`` lines, values Go-quoted);
        plain ``key=value`` accepted for hand-written test files."""
        for line in text.splitlines():
            key, sep, value = line.partition("=")
            if not sep or key.strip() != consts.MIGRATE_ANNOTATION:
                continue
            if value.strip().strip('"') == consts.MIGRATE_REQUESTED:
                return True
        return False


# ---------------------------------------------------------------------------
# Reference migratable training job (the chaos-migrate soak's payload).


def _mesh_from_topology(topology: str):
    """(dp, mp) Mesh over exactly topology-many devices: the first topology
    dim is dp, the rest collapse into mp — "4x4" → 4x4, "2x4" → 2x4.

    When FEWER devices exist than the topology names, the mesh degrades to
    (1, all-devices) instead of crashing: a restore pod created unpinned
    (no healthy capacity at migration time) keeps the env of its OLD slice
    shape, and the scheduler may later bind it to a smaller one — the
    checkpoint reshards under any mesh, so training on the shape actually
    present beats dying with a valid snapshot in hand."""
    import jax

    from tpu_operator.utils import parse_topology, topology_chips

    dims = parse_topology(topology)
    chips = topology_chips(topology)
    devices = jax.devices()
    if len(devices) < chips:
        print(json.dumps({
            "event": "topology-degraded", "declared": topology,
            "devices": len(devices),
        }), flush=True)
        dp, mp = 1, len(devices)
    else:
        dp = dims[0]
        mp = chips // dp
    from jax.sharding import Mesh

    return Mesh(np.array(devices[:dp * mp]).reshape(dp, mp), ("dp", "mp"))


def run_migratable_training(
    ckpt_dir: str,
    topology: str,
    steps: int = 50,
    ckpt_every: int = 10,
    step_sleep_s: float = 0.0,
    d_model: int = 32,
    d_hidden: int = 64,
    signal_source: Optional[MigrationSignal] = None,
    progress: Optional[Callable[[dict], None]] = None,
) -> dict:
    """The migratable train loop: restore → step → periodic checkpoint →
    (on drain signal) final checkpoint + clean exit.

    Returns a result dict with ``ok``, ``steps_done``,
    ``resumed_from_step`` (0 when cold), ``checkpointed_step`` (the step
    the final snapshot holds, -1 when the run finished without one) and the
    mesh actually used — the evidence the chaos-migrate soak asserts its
    step bound over.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    sig = signal_source or MigrationSignal()
    mesh = _mesh_from_topology(topology)
    dp, mp = mesh.shape["dp"], mesh.shape["mp"]
    specs = {"w1": (None, "mp"), "w2": ("mp", None)}

    start_step = 0
    resumed_from = 0
    # chip-time accounting evidence: what the dir's stamps say the job had
    # already reached (steps at-or-below this are replayed recompute), and
    # cumulative useful/wasted busy seconds pushed as counters so the
    # operator-side ledger deltas them (obs/accounting.py)
    highwater_prior = read_highwater(ckpt_dir)
    acct_useful_s = 0.0
    acct_wasted_s = 0.0
    replayed_steps = 0
    t_restore0 = time.perf_counter()
    ckpt = load_checkpoint(ckpt_dir, mesh=mesh)
    acct_wasted_s += time.perf_counter() - t_restore0  # restore overhead
    if ckpt is not None:
        params = {"w1": ckpt.arrays["w1"], "w2": ckpt.arrays["w2"]}
        start_step = resumed_from = ckpt.step
        if progress is not None:
            progress({"event": "restored", "resumed_from_step": ckpt.step,
                      "from_mesh": list(ckpt.mesh_shape or ()),
                      "mesh": [dp, mp]})
    else:
        params = {
            k: _place(mesh, np.asarray(v), specs[k])
            for k, v in _init_params(d_model, d_hidden).items()
        }
        if progress is not None:
            progress({"event": "started", "mesh": [dp, mp]})

    global_batch = 8 * dp
    gx = np.random.default_rng(7).standard_normal(
        (global_batch, d_model), dtype=np.float32
    ).astype(jnp.bfloat16)
    x = jax.make_array_from_callback(
        (global_batch, d_model), NamedSharding(mesh, P("dp", None)),
        lambda idx: gx[idx],
    )

    # Plain-jit GSPMD step (no shard_map dependency): the dp-sharded batch
    # through the mp-sharded Megatron MLP; the partitioner inserts the mp
    # psum and dp gradient reduction from the shardings alone.
    def loss_fn(p, xs):
        h = jnp.maximum(xs.astype(jnp.bfloat16) @ p["w1"], 0)
        y = h @ p["w2"]
        return jnp.mean(jnp.square(y.astype(jnp.float32)))

    @jax.jit
    def step_fn(p, xs):
        loss, grads = jax.value_and_grad(loss_fn)(p, xs)
        new = {
            k: (p[k].astype(jnp.float32)
                - 0.05 * grads[k].astype(jnp.float32)).astype(p[k].dtype)
            for k in p
        }
        return loss, new

    ckpt_writer = Checkpointer(ckpt_dir)
    ckpt_writer._last_step = resumed_from or None

    def snapshot(step: int, final: bool) -> Optional[str]:
        nonlocal acct_wasted_s
        host = {k: np.asarray(v) for k, v in params.items()}
        t_ckpt0 = time.perf_counter()
        try:
            return ckpt_writer.save(
                step, host, mesh_shape=(dp, mp), specs=specs, final=final,
            )
        finally:
            acct_wasted_s += time.perf_counter() - t_ckpt0  # ckpt overhead

    checkpointed = resumed_from if ckpt is not None else -1
    step = start_step
    losses: list[float] = []
    # Step-phase attribution (obs/profile.py): each step's wall time is
    # split into compile / compute / collective-wait spans; the optional
    # file barrier (TPU_STEP_BARRIER_DIR + WORLD/RANK) makes a multi-host
    # slice lock-step per step, so the wait at the barrier IS the
    # collective-wait a slow peer inflicts on this host — the evidence the
    # straggler detector attributes from.
    barrier = obs_profile.FileStepBarrier.from_env()
    compiled = False
    timer = obs_profile.StepTimer()
    while step < steps:
        if sig.requested():
            snapshot(step, final=True)
            checkpointed = step
            if progress is not None:
                progress({"event": "checkpointed", "step": step,
                          "trigger": "migrate-signal"})
            if barrier is not None:
                # tell peers this rank left on purpose — a migrating
                # member must not wedge the survivors at the barrier
                barrier.leave()
            break
        timer.reset()
        t_step0 = time.perf_counter()
        # first executed step pays jit tracing+compilation; later steps
        # run the cached executable — classic compile-vs-compute split
        with timer.phase(obs_profile.PHASE_COMPUTE if compiled
                         else obs_profile.PHASE_COMPILE):
            loss, params = step_fn(params, x)
        compiled = True
        losses.append(float(loss))
        step += 1
        if step_sleep_s:
            # simulated per-step device work rides the compute span
            with timer.phase(obs_profile.PHASE_COMPUTE):
                time.sleep(step_sleep_s)
        if barrier is not None:
            timer.add(obs_profile.PHASE_COLLECTIVE_WAIT, barrier.wait(step))
        step_wall_s = time.perf_counter() - t_step0
        replayed = step <= highwater_prior
        if replayed:
            replayed_steps += 1
            acct_wasted_s += step_wall_s
        else:
            acct_useful_s += step_wall_s
            publish_highwater(ckpt_dir, step)
        flight.record(
            "migration", "step", step=step, step_s=step_sleep_s,
            replayed=1.0 if replayed else 0.0,
            replayed_steps=float(replayed_steps),
            acct_useful_s=acct_useful_s,
            acct_wasted_s=acct_wasted_s,
        )
        flight.record_step(
            "migration", step_seq=step, wall_s=step_wall_s,
            phases=timer.spans(),
        )
        if ckpt_every and step % ckpt_every == 0 and step < steps:
            snapshot(step, final=False)
            checkpointed = step
            if progress is not None:
                progress({"event": "progress", "step": step})

    finite = all(math.isfinite(l) for l in losses) if losses else True
    return {
        "ok": finite,
        "steps_done": step - start_step,
        "step": step,
        "resumed_from_step": resumed_from,
        "checkpointed_step": checkpointed,
        "migrated_out": bool(sig.requested()),
        "mesh": [dp, mp],
        "topology": topology,
        "losses_finite": finite,
        "backend": jax.default_backend(),
    }


def _init_params(d_model: int, d_hidden: int) -> dict:
    rng = np.random.default_rng(0)
    scale = 1.0 / np.sqrt(d_model)
    import jax.numpy as jnp

    return {
        "w1": (rng.standard_normal((d_model, d_hidden), dtype=np.float32)
               * scale).astype(jnp.bfloat16),
        "w2": (rng.standard_normal((d_hidden, d_model), dtype=np.float32)
               * scale).astype(jnp.bfloat16),
    }


def main() -> int:
    from tpu_operator import workloads
    from tpu_operator.validator import status as vstatus

    workloads.honor_cpu_platform_request()
    ckpt_dir = os.environ.get(consts.CKPT_DIR_ENV, "")
    if not ckpt_dir:
        print(json.dumps({"ok": False, "error": f"{consts.CKPT_DIR_ENV} required"}))
        return 1
    os.makedirs(ckpt_dir, exist_ok=True)
    topology = os.environ.get(consts.JOB_TOPOLOGY_ENV, "2x4")
    result_file = os.environ.get("TPU_JOB_RESULT_FILE", "")

    def progress(event: dict) -> None:
        line = json.dumps({"ts": round(time.time(), 3), **event})
        print(line, flush=True)
        if result_file:
            try:
                with open(result_file, "a") as f:
                    f.write(line + "\n")
            except OSError:
                pass

    recorder = flight.recorder_for(vstatus.flight_record_path("migration"))
    with flight.activate(recorder):
        result = run_migratable_training(
            ckpt_dir,
            topology,
            steps=int(os.environ.get("TRAIN_STEPS", "50")),
            ckpt_every=int(os.environ.get("TPU_CKPT_EVERY", "10")),
            step_sleep_s=float(os.environ.get("TRAIN_STEP_SLEEP_S", "0") or 0),
            progress=progress,
        )
        flight.record_result("migration", result)
    progress({"event": "result", **result})
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
