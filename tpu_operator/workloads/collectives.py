"""TPU validation workloads: vector-add, allreduce benchmark, sharded burn-in.

These replace the reference's CUDA workload images (cuda-workload-validation
vectorAdd, validator/main.go:1189-1302) with TPU-native XLA programs:

- ``vector_add``           — single-chip sanity via a Pallas kernel (MXU-free
                             VPU path; interpret mode off-TPU)
- ``allreduce_benchmark``  — psum over all local chips via shard_map on a 1-D
                             mesh; reports achieved algorithm bandwidth GB/s
                             (the BASELINE.json "ICI GB/s" metric)
- ``burn_in_step``         — jitted (dp, mp)-sharded matmul chain exercising
                             MXU + all_gather/reduce_scatter/psum over ICI;
                             the slice acceptance test run by the jax
                             validation component on multi-host slices
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_operator.obs import flight
from tpu_operator.obs import profile as obs_profile
from tpu_operator.workloads import timing


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _vary(v, axis: str = "x"):
    """Mark a replicated value as device-varying along ``axis`` inside
    shard_map (loop carries must have matching varying-manual-axes; pcast
    replaced pvary in newer jax — keep the fallback for older)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(v, axis, to="varying")
    return jax.lax.pvary(v, axis)  # pragma: no cover — older jax


# ---------------------------------------------------------------------------
# device-count truth


def device_count_check(expected_local: int, num_processes: int = 1) -> dict:
    """Assert the devices PJRT actually initialized match what the node (or
    the pod's resource request) promised.

    The reference's plugin validation counts the ADVERTISED resource
    (validator/main.go:1115-1135) and its CUDA workload then consumes one
    GPU — but nothing in that chain notices a runtime that silently
    initializes fewer devices than the node advertises.  On TPU that failure
    is real: libtpu can come up with dead chips excluded, PJRT reports the
    survivors, and every downstream collective quietly runs on the wrong
    mesh.  This check is the missing equality: visible-local must equal the
    promised chip count, and (multi-controller) the global count must equal
    processes x per-host chips.

    Enforced only on backends named in ``DEVICE_COUNT_GATE_BACKENDS``
    (default tpu — the virtual CPU device count is a test-harness knob, not
    hardware truth); unenforced runs still report the counts."""
    visible_local = jax.local_device_count()
    visible_global = jax.device_count()
    expected_global = expected_local * max(1, num_processes)
    backend = jax.default_backend()
    gated = backend in timing.gate_backends("DEVICE_COUNT_GATE_BACKENDS")
    matches = visible_local == expected_local and visible_global == expected_global
    result = {
        "ok": matches or not gated,
        "visible": visible_local,
        "expected": expected_local,
        "visible_global": visible_global,
        "expected_global": expected_global,
        "gated": gated,
        "backend": backend,
    }
    if not matches:
        result["error"] = (
            f"PJRT initialized {visible_local} local / {visible_global} global "
            f"devices but the node advertises {expected_local} local / "
            f"{expected_global} global — dead or missing chips"
        )
    return result


# ---------------------------------------------------------------------------
# vector add (pallas)


def _add_kernel(x_ref, y_ref, o_ref):
    o_ref[...] = x_ref[...] + y_ref[...]


def pallas_vector_add(x: jax.Array, y: jax.Array) -> jax.Array:
    """Tiled elementwise add; (8,128)-aligned blocks feed the VPU."""
    assert x.ndim == 2, "expects 2D (n, 128k) input"
    block = (min(x.shape[0], 256), min(x.shape[1], 512))
    grid = (pl.cdiv(x.shape[0], block[0]), pl.cdiv(x.shape[1], block[1]))
    return pl.pallas_call(
        _add_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(block, lambda i, j: (i, j)),
            pl.BlockSpec(block, lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec(block, lambda i, j: (i, j)),
        interpret=not _on_tpu(),
    )(x, y)


def vector_add(n: int = 1 << 20, seed: int = 0) -> dict:
    """CUDA vectorAdd analogue; returns {'ok', 'n', 'max_error'}.

    ONE compiled program — the pallas kernel, the XLA reference add, and
    the max-error reduction fused in a single jit with a single scalar
    readback.  Inputs are host-generated numpy randoms: on-device threefry
    RNG inside the program ballooned its compile from 0.7s to ~7s on the
    validation critical path, and runtime inputs (unlike an in-program
    iota) also guarantee XLA cannot constant-fold the whole check away."""
    cols = 512
    rows = max(8, n // cols)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((rows, cols), dtype=np.float32))
    y = jnp.asarray(rng.standard_normal((rows, cols), dtype=np.float32))

    @jax.jit
    def program(x, y):
        out = pallas_vector_add(x, y)
        return jnp.max(jnp.abs(out - (x + y)))

    err = float(program(x, y))
    return {"ok": err < 1e-5, "n": rows * cols, "max_error": err, "backend": jax.default_backend()}


# ---------------------------------------------------------------------------
# allreduce bandwidth


def allreduce_benchmark(
    size_mb: float = 64.0,
    iters: int = 10,
    warmup: int = 2,
    devices: Optional[list] = None,
    best_of: int = 3,
) -> dict:
    """psum a bf16 buffer across all chips; report achieved algbw GB/s.

    Ring-allreduce algorithm bandwidth: each chip moves 2*(n-1)/n * size
    bytes, so algbw = size / t and busbw = algbw * 2*(n-1)/n (NCCL-tests
    convention, reported the same way so numbers compare 1:1 with the
    reference's GPU fleet tooling).

    Methodology (r03): ``iters`` collectives run inside ONE compiled
    fori_loop with a single scalar readback — per-dispatch timing is
    untrustworthy on tunneled PJRT backends and host sync would serialize
    the ICI — and the dispatch+readback floor (a null program) is
    subtracted.  ``best_of`` repetitions with min/median reported: the r02
    round's 19% "regression" was single-shot noise nobody could see.
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("x",))
    elems_per_dev = max(1, int(size_mb * 1024 * 1024 / 2 / n))  # bf16 = 2 bytes
    # pad to lane width
    elems_per_dev = (elems_per_dev + 127) // 128 * 128
    global_elems = elems_per_dev * n

    sharding = NamedSharding(mesh, P("x"))
    if jax.process_count() > 1:
        # multi-controller (the distributed validation program): every
        # process contributes its local shards; device_put can't scatter a
        # host array across processes
        local = np.ones(
            (elems_per_dev * jax.local_device_count(),), np.float32
        ).astype(jnp.bfloat16)
        x = jax.make_array_from_process_local_data(sharding, local)
    else:
        x = jax.device_put(jnp.ones((global_elems,), jnp.bfloat16), sharding)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=P("x"), out_specs=P("x")
    )
    def chain(shard):
        if n > 1:
            # value stays exactly 1.0 every round: psum -> n, /n -> 1
            # (the replicated psum result must re-enter the loop as the
            # device-varying carry the fori_loop signature requires)
            body = lambda _, s: _vary(jax.lax.psum(s, "x") / n)  # noqa: E731
            expected = 1.0
        else:
            # single chip moves no ICI traffic; accumulate so the loop body
            # is a real HBM read+write per iteration instead of an identity
            # XLA would fold away (reported as hbm-local, never gated)
            body = lambda _, s: s + 1  # noqa: E731
            expected = 1.0 + iters
        out = jax.lax.fori_loop(0, iters, body, shard)
        return out - (expected - 1.0)  # normalize back to ones

    # ONE program per timed repetition (chain + error reduction fused, a
    # single scalar readback) and one baseline program for the floor: the
    # split chain/err pair cost an extra compile plus an extra tunneled
    # dispatch per repetition for identical semantics
    @jax.jit
    def chain_err(v):
        return jnp.max(jnp.abs(chain(v).astype(jnp.float32) - 1.0))

    @jax.jit
    def baseline(v):
        # dispatch + scalar-readback floor: same reduction, no collective
        return jnp.max(jnp.abs(v.astype(jnp.float32) - 1.0))

    # floor is min of 3: one noisy sample must not over-subtract and
    # inflate the reported bandwidth past the gate
    float(baseline(x))  # compile
    overheads = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(baseline(x))
        overheads.append(time.perf_counter() - t0)
    overhead = min(overheads)

    compile_s = timing.timed(lambda: float(chain_err(x)))  # compile + settle
    flight.record("allreduce", "compile", compile_s=compile_s)
    for _ in range(max(1, warmup) - 1):
        float(chain_err(x))
    raw = []
    max_err = 0.0
    size_bytes_per_rep = elems_per_dev * n * 2
    for rep in range(best_of):
        t0 = time.perf_counter()
        # worst error across ALL reps: a corrupt repetition must fail the
        # check even when a later one is clean
        max_err = max(max_err, float(chain_err(x)))
        raw.append(time.perf_counter() - t0)
        flight.record(
            "allreduce", "step", step=rep,
            step_s=raw[-1],
            # amortized per-collective rate, floor NOT subtracted: the live
            # per-step series is a monitoring signal, the verdict below
            # applies the shared floor rule
            gbps=size_bytes_per_rep * iters / raw[-1] / 1e9,
        )
        # phase attribution: a timed all-reduce chain IS collective time
        flight.record_step(
            "allreduce", step_seq=rep, wall_s=raw[-1],
            phases={obs_profile.PHASE_COLLECTIVE_WAIT: raw[-1]},
        )
    # shared rule (workloads/timing.py): when the floor rivals the compute
    # (tiny buffers or a huge dispatch RTT) subtraction is meaningless —
    # report the unsubtracted, deflated rate and flag it so gates skip
    # rather than trust either direction
    times, overhead_dominated = timing.subtract_floor(raw, overhead, per=iters)
    dt = times[0]
    dt_median = times[len(times) // 2]

    size_bytes = global_elems * 2
    algbw = size_bytes / dt / 1e9
    busbw = algbw * (2 * (n - 1) / n) if n > 1 else algbw
    ok = max_err < 0.1
    return {
        "ok": ok,
        "devices": n,
        "size_mb": size_bytes / 1e6,
        "time_ms": dt * 1e3,
        "time_ms_median": dt_median * 1e3,
        "overhead_ms": overhead * 1e3,
        "overhead_dominated": overhead_dominated,
        "best_of": best_of,
        "algbw_gbps": algbw,
        "algbw_gbps_median": size_bytes / dt_median / 1e9,
        "busbw_gbps": busbw,
        "max_error": max_err,
        # n=1 moves no inter-chip traffic: the number is an HBM copy rate,
        # not an ICI bandwidth, and must never be gated or reported as one
        "transport": "ici" if n > 1 else "hbm-local",
        "backend": jax.default_backend(),
    }


def apply_allreduce_gate(result: dict, min_gbps: float) -> dict:
    """The ICI allreduce gate (shared rule: timing.apply_min_gate): gates
    busbw, the link-rate-comparable NCCL-tests number, over real ICI only.
    The workload-pod and distributed multi-host paths both call this."""
    return timing.apply_min_gate(
        result, metric="busbw_gbps", minimum=min_gbps,
        backends_env="ALLREDUCE_GATE_BACKENDS", label="busbw",
        require_ici=True,
    )


# ---------------------------------------------------------------------------
# ring exchange (per-link ICI diagnostic)


def ring_benchmark(
    size_mb: float = 16.0,
    iters: int = 4,
    best_of: int = 3,
    devices: Optional[list] = None,
) -> dict:
    """ppermute the chips' buffers around the full ring and verify every
    hop's payload — the per-LINK diagnostic the global psum can't give.

    An allreduce proves the slice as a whole (a wrong sum says "something
    is broken", not where), and its tree/ring schedule is the compiler's
    choice.  This check forces n-1 explicit neighbor hops: device i's
    buffer visits every other device in order, and the accumulated sum at
    each device is exact only if EVERY individual link carried its payload
    uncorrupted.  The reported bandwidth is per-hop and bottlenecked by the
    slowest link (ring pipelines all links each step) — the substrate
    pattern of ring attention, where k/v blocks stream neighbor-to-neighbor
    over ICI exactly like this.

    Methodology: the r03 chained recipe (workloads/timing.py) — ``iters``
    full ring revolutions inside one compiled program, scalar-readback
    sync, dispatch floor subtracted, best-of-N."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if n == 1:
        return {
            "ok": True,
            "devices": 1,
            "skipped": "single chip: no ring",
            "transport": "hbm-local",
            "backend": jax.default_backend(),
        }
    mesh = Mesh(np.array(devices), ("x",))
    elems_per_dev = max(128, int(size_mb * 1024 * 1024 / 2 / n))
    elems_per_dev = (elems_per_dev + 127) // 128 * 128
    perm = [(j, (j + 1) % n) for j in range(n)]

    sharding = NamedSharding(mesh, P("x"))
    ranks = np.repeat(np.arange(1, n + 1, dtype=np.float32), elems_per_dev)
    # the payload AS IT RIDES THE RING: bf16-rounded ranks (integers above
    # 256 are not bf16-exact, so the expected values must be computed from
    # the rounded payload or big slices would fail spuriously)
    payload = np.asarray(ranks.astype(jnp.bfloat16), dtype=np.float32)
    if jax.process_count() > 1:
        # rank = 1 + mesh POSITION, never device id — multi-process device
        # ids are not contiguous (process 1's CPU devices start at 2048)
        index_of = {d: i for i, d in enumerate(devices)}
        local = np.repeat(
            np.array(
                sorted(1.0 + index_of[d] for d in mesh.local_devices),
                dtype=np.float32,
            ),
            elems_per_dev,
        ).astype(jnp.bfloat16)
        x = jax.make_array_from_process_local_data(sharding, local)
    else:
        x = jax.device_put(ranks.astype(jnp.bfloat16), sharding)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=P("x"), out_specs=P("x")
    )
    def ring(shard):
        # the ring payload stays bf16 (the bandwidth under test); the
        # accumulator is f32 so rank sums stay exact on big slices (bf16
        # integers are exact only to 256)
        def hop(_, carry):
            buf, acc = carry
            buf = jax.lax.ppermute(buf, "x", perm)
            return buf, acc + buf.astype(jnp.float32)

        def revolution(_, carry):
            # n-1 accumulating hops: my accumulator sums every other
            # device's buffer, one hop at a time; the completing n-th hop
            # brings my buffer home so the next revolution starts clean
            buf, acc = jax.lax.fori_loop(
                0, n - 1, hop,
                (carry[0], _vary(jnp.zeros(carry[0].shape, jnp.float32))),
            )
            buf = jax.lax.ppermute(buf, "x", perm)
            return buf, acc

        buf, acc = jax.lax.fori_loop(
            0, iters, revolution,
            (shard, _vary(jnp.zeros(shard.shape, jnp.float32))),
        )
        return acc

    distinct_total = float(payload[::elems_per_dev].sum())

    @jax.jit
    def err(acc, x_in):
        # after a full revolution my buffer is back home (iters revolutions
        # are idempotent on buf), and acc = sum of all OTHER devices'
        # payloads: distinct-total minus own, derived ON DEVICE from the
        # unchanged input (not baked in as a global-size HLO constant) and
        # exact at any slice size — bf16 integer payloads accumulate in f32
        # exactly to 2^24.  One corrupted hop breaks the equality.
        expected = distinct_total - x_in.astype(jnp.float32)
        return jnp.max(jnp.abs(acc - expected))

    t_compile = time.perf_counter()
    acc0 = ring(x)  # compile + warm the timed program
    float(err(acc0, x))  # compile err for its real input types
    flight.record("ring", "compile", compile_s=time.perf_counter() - t_compile)
    # floor: dispatch + readback of the SAME compiled err on a materialized
    # array — no recompile in the first sample, no ring execution
    floor = min(
        timing.timed(lambda: float(err(acc0, x))) for _ in range(max(2, best_of))
    )
    raw = []
    max_err = 0.0
    for rep in range(best_of):
        t0 = time.perf_counter()
        max_err = max(max_err, float(err(ring(x), x)))
        raw.append(time.perf_counter() - t0)
        flight.record(
            "ring", "step", step=rep, step_s=raw[-1],
            gbps=elems_per_dev * 2 * iters * n / raw[-1] / 1e9,
        )
        flight.record_step(
            "ring", step_seq=rep, wall_s=raw[-1],
            phases={obs_profile.PHASE_COLLECTIVE_WAIT: raw[-1]},
        )
    # per-hop time: iters revolutions x n pipelined hops each (n-1
    # accumulating + 1 completing)
    times, overhead_dominated = timing.subtract_floor(
        raw, floor, per=iters * n
    )
    hop_bytes = elems_per_dev * 2  # bf16 per device per hop
    gbps = hop_bytes / times[0] / 1e9
    # the ring follows jax.devices() ENUMERATION order; within one host
    # that tracks the physical chip ring, but across hosts / higher-D tori
    # consecutive indices are not guaranteed ICI-adjacent — some hops then
    # traverse multiple links (or DCN) and the reported per-link rate is a
    # LOWER BOUND.  Flag it so floors calibrated to a single link are read
    # accordingly (correctness of the hop payloads is unaffected).
    note = (
        "multi-host enumeration-order ring: some hops may span multiple "
        "links; link_gbps is a lower bound"
        if jax.process_count() > 1
        else None
    )
    return {
        **({"note": note} if note else {}),
        # the equality is exact by construction (integer payloads, f32
        # accumulation): ANY deviation is a corrupted hop, no tolerance
        "ok": max_err == 0.0,
        "devices": n,
        "size_mb": hop_bytes * n / 1e6,
        "hops": iters * n,
        "hop_ms": times[0] * 1e3,
        "overhead_ms": floor * 1e3,
        "overhead_dominated": overhead_dominated,
        "link_gbps": gbps,
        "link_gbps_median": hop_bytes / times[len(times) // 2] / 1e9,
        "max_error": max_err,
        "transport": "ici",
        "backend": jax.default_backend(),
    }


def apply_ring_gate(result: dict, min_gbps: float) -> dict:
    """RING_MIN_GBPS gate on the per-link rate (shared rule:
    timing.apply_min_gate; never on skipped/single-chip measurements)."""
    return timing.apply_min_gate(
        result, metric="link_gbps", minimum=min_gbps,
        backends_env="RING_GATE_BACKENDS", label="ring link",
        require_ici=True,
    )


# ---------------------------------------------------------------------------
# sharded burn-in (slice acceptance test)


def _split_dp_mp(n: int) -> tuple:
    """(dp, mp) factorization of n chips — both axes populated when
    possible so dp and mp collectives both flow; mp gets the larger
    factor (it carries the sequence/TP collectives)."""
    if n == 1:
        mp = 1
    elif n % 4 == 0 and n > 4:
        mp = 4
    elif n % 2 == 0 and n > 2:
        mp = 2
    else:
        mp = n
    return n // mp, mp


def make_mesh(n_devices: Optional[int] = None, devices: Optional[list] = None) -> Mesh:
    """2-D (dp, mp) mesh over the available chips; mp rides the fastest ICI
    dimension (innermost), dp the outer."""
    devices = devices if devices is not None else jax.devices()
    if n_devices:
        devices = devices[:n_devices]
    n = len(devices)
    dp, mp = _split_dp_mp(n)
    return Mesh(np.array(devices).reshape(dp, mp), ("dp", "mp"))


def burn_in_params(mesh: Mesh, d_model: int = 512, d_hidden: int = 2048, seed: int = 0):
    """Two-layer MLP block params, mp-sharded (Megatron layout: W1 column-,
    W2 row-parallel so the block needs exactly one psum)."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    scale = 1.0 / np.sqrt(d_model)
    w1 = jax.device_put(
        (jax.random.normal(k1, (d_model, d_hidden), jnp.bfloat16) * scale),
        NamedSharding(mesh, P(None, "mp")),
    )
    w2 = jax.device_put(
        (jax.random.normal(k2, (d_hidden, d_model), jnp.bfloat16) * scale),
        NamedSharding(mesh, P("mp", None)),
    )
    return {"w1": w1, "w2": w2}


def burn_in_step(
    mesh: Mesh, params: dict, x: jax.Array, lr: float = 0.05
) -> tuple[jax.Array, dict]:
    """One real SGD train step: dp-sharded batch through an mp-sharded MLP,
    gradients pmean'd over dp, parameters updated in place — exercises MXU
    matmuls plus ICI collectives (implicit all_gather via sharding, the mp
    psum of row-parallel outputs, dp grad reduction).  Returns
    ``(loss, new_params)`` so repeated steps move the loss, making the
    acceptance test's trajectory a real signal instead of a re-run of one
    cached forward."""

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(None, "mp"), P("mp", None), P("dp", None)),
        out_specs=(P(), P(None, "mp"), P("mp", None)),
    )
    def step(w1, w2, xs):
        def loss_fn(w1, w2):
            h = jnp.maximum(xs.astype(jnp.bfloat16) @ w1, 0)  # [b, hidden/mp]
            y = h @ w2  # partial sum over mp shards
            y = jax.lax.psum(y, "mp")  # row-parallel reduce
            return jnp.mean(jnp.square(y.astype(jnp.float32)))

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(w1, w2)
        # data-parallel gradient reduction; each mp shard updates its own
        # parameter slice (grads are per-shard already — Megatron layout)
        g1 = jax.lax.pmean(grads[0], "dp")
        g2 = jax.lax.pmean(grads[1], "dp")
        new_w1 = (w1.astype(jnp.float32) - lr * g1.astype(jnp.float32)).astype(w1.dtype)
        new_w2 = (w2.astype(jnp.float32) - lr * g2.astype(jnp.float32)).astype(w2.dtype)
        return jax.lax.pmean(loss, "dp"), new_w1, new_w2

    loss, w1, w2 = step(params["w1"], params["w2"], x)
    return loss, {"w1": w1, "w2": w2}



def _acceptance_run(
    mesh: Mesh, step, params, x, steps: int, name: str = "burn-in"
) -> dict:
    """Shared acceptance-loop contract (burn_in and transformer_burn_in):
    run ``steps`` jitted SGD steps, require finite and strictly-moving
    losses (a flat line means the step silently stopped training — the r1
    failure mode).  Every SGD step leaves a flight-recorder sample (step
    wall time; the first one carries the compile)."""
    losses = []
    t0 = time.perf_counter()
    t_step = t0
    for i in range(steps):
        loss, params = step(params, x)
        losses.append(float(loss))
        now = time.perf_counter()
        flight.record(
            name, "compile" if i == 0 else "step", step=i,
            step_s=now - t_step, loss=losses[-1],
        )
        flight.record_step(
            name, step_seq=i, wall_s=now - t_step,
            phases={(obs_profile.PHASE_COMPILE if i == 0
                     else obs_profile.PHASE_COMPUTE): now - t_step},
        )
        t_step = now
    dt = time.perf_counter() - t0
    finite = all(np.isfinite(l) for l in losses)
    decreasing = len(losses) < 2 or losses[-1] < losses[0]
    return {
        "ok": finite and decreasing,
        "devices": mesh.size,
        "mesh": dict(zip(mesh.axis_names, mesh.devices.shape)),
        "steps": steps,
        "losses": losses,
        "time_s": dt,
        "backend": jax.default_backend(),
    }


def burn_in(
    mesh: Optional[Mesh] = None,
    steps: int = 3,
    batch: int = 64,
    d_model: int = 512,
    seed: int = 0,
) -> dict:
    """Run the acceptance test; returns loss trajectory + timing.

    ``seed`` varies params AND data (defaults reproduce the historical
    trajectory) — the concurrent partition acceptance gives each partition
    its own seed so the two trajectories are INDEPENDENT pinned signals:
    identical losses from disjoint partitions would mean the isolation
    boundary leaked one unit's computation into the other."""
    mesh = mesh or make_mesh()
    params = burn_in_params(mesh, d_model=d_model, seed=seed)
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(seed + 1), (batch, d_model), jnp.bfloat16),
        NamedSharding(mesh, P("dp", None)),
    )
    return _acceptance_run(
        mesh, jax.jit(functools.partial(burn_in_step, mesh)), params, x, steps
    )


# ---------------------------------------------------------------------------
# Transformer-layer flagship step: SP attention + TP MLP + DP grads.
#
# The full sharding portfolio in ONE training step over the (dp, mp) mesh —
# the shape the driver's dryrun_multichip compiles:
#   - batch over dp (data parallel; gradients pmean'd across dp)
#   - SEQUENCE over mp for attention: blockwise ring attention
#     (workloads/ring_attention.py) — KV blocks ppermute the mp ring,
#     peak attention memory one block per chip (sequence parallelism)
#   - Megatron tensor parallel over mp for the MLP, in the Megatron-SP
#     arrangement: all_gather the sequence shards into the TP region,
#     column/row-split matmuls, reduce_scatter (psum_scatter) back to
#     sequence shards — the collective sandwich of Korthikanti et al.
# Attention projections are replicated (ring attention keeps heads whole);
# their gradients therefore reduce over BOTH mesh axes, while the
# mp-sharded MLP weights reduce over dp alone.


def _rmsnorm(x, eps: float = 1e-6):
    scale = jax.lax.rsqrt(jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True) + eps)
    return (x.astype(jnp.float32) * scale).astype(x.dtype)


def transformer_params(
    mesh: Mesh,
    d_model: int = 256,
    d_hidden: int = 1024,
    seed: int = 0,
) -> dict:
    """One pre-norm transformer layer's weights: replicated attention
    projections, Megatron-split MLP."""
    ks = jax.random.split(jax.random.PRNGKey(seed), 6)
    scale = 1.0 / np.sqrt(d_model)

    def mk(k, shape, spec):
        return jax.device_put(
            jax.random.normal(k, shape, jnp.bfloat16) * scale,
            NamedSharding(mesh, spec),
        )

    return {
        "wq": mk(ks[0], (d_model, d_model), P(None, None)),
        "wk": mk(ks[1], (d_model, d_model), P(None, None)),
        "wv": mk(ks[2], (d_model, d_model), P(None, None)),
        "wo": mk(ks[3], (d_model, d_model), P(None, None)),
        "w1": mk(ks[4], (d_model, d_hidden), P(None, "mp")),
        "w2": mk(ks[5], (d_hidden, d_model), P("mp", None)),
    }


def _layer_fwd(xs, wq, wk, wv, wo, w1, w2, heads: int, axes: tuple,
               use_pallas: bool = False):
    """The flagship per-shard transformer layer on [b, s_loc, d] — the ONE
    definition both the flat (dp, mp) step and the pp-pipelined stages
    run: sequence-parallel ring attention over mp, then the Megatron-SP
    MLP sandwich.  ``axes``: every manual mesh axis the activations vary
    over (the ring's loop carries must match); ``use_pallas`` routes the
    attention FORWARD through the fused flash kernel (training-safe: the
    remat backward consumes only layout-identical residuals)."""
    from tpu_operator.workloads import ring_attention

    b, s_loc, d = xs.shape
    hd = d // heads
    xf = xs.astype(jnp.bfloat16)
    # -- attention, sequence-parallel over the mp ring
    h = _rmsnorm(xf)
    q = (h @ wq).reshape(b, s_loc, heads, hd)
    k = (h @ wk).reshape(b, s_loc, heads, hd)
    v = (h @ wv).reshape(b, s_loc, heads, hd)
    # the memory-efficient path: custom VJP recomputes each hop's
    # scores in a second ring pass instead of letting AD save every
    # hop's residuals — O(1) blocks per layer, the property that
    # makes long sequences trainable at all
    attn = ring_attention.ring_attention_remat(
        q, k, v, "mp", True, axes, use_pallas
    )
    xa = xf + attn.reshape(b, s_loc, d) @ wo
    # -- MLP, Megatron-SP: sequence shards gather into the TP
    # region, column/row-split matmuls, reduce-scatter back out
    g = jax.lax.all_gather(_rmsnorm(xa), "mp", axis=1, tiled=True)
    mid = jnp.maximum(g @ w1, 0)            # [b, S, hidden/mp]
    y_part = mid @ w2                        # partial over mp
    y = jax.lax.psum_scatter(y_part, "mp", scatter_dimension=1, tiled=True)
    return xa + y


def transformer_step(
    mesh: Mesh, heads: int, params: dict, x: jax.Array, lr: float = 0.05,
    use_pallas: bool = False, check_vma: bool = True,
) -> tuple[jax.Array, dict]:
    """One SGD step of the transformer layer on x [B, S, D] sharded
    P("dp", "mp", None) — batch over dp, sequence over mp.  ``heads`` is
    static (it shapes the trace); partial it in before jit.  Returns
    (loss, new_params).

    ``check_vma`` is TEST-ONLY (interpret-mode kernel pinning on CPU,
    where the pallas interpreter's internal index ops can't satisfy the
    checker).  NEVER disable it in real training: check_vma=False
    changes the MLP collectives' gradient transposes — it inflated w1/w2
    gradients by axis-size factors until r04 caught it by comparing
    updated weights across the flag."""
    dp, mp = mesh.shape["dp"], mesh.shape["mp"]

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(None, None), P(None, None), P(None, None), P(None, None),
            P(None, "mp"), P("mp", None), P("dp", "mp", None),
        ),
        out_specs=(
            P(),
            P(None, None), P(None, None), P(None, None), P(None, None),
            P(None, "mp"), P("mp", None),
        ),
        check_vma=check_vma,
    )
    def step(wq, wk, wv, wo, w1, w2, xs):
        b, s_loc, d = xs.shape

        def loss_fn(wq, wk, wv, wo, w1, w2):
            out = _layer_fwd(xs, wq, wk, wv, wo, w1, w2, heads, ("dp", "mp"),
                             use_pallas)
            # global mean-square loss: reduce over every shard's tokens
            total = jax.lax.psum(
                jax.lax.psum(jnp.sum(jnp.square(out.astype(jnp.float32))), "mp"),
                "dp",
            )
            count = b * dp * s_loc * mp * d
            return total / count

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2, 3, 4, 5))(
            wq, wk, wv, wo, w1, w2
        )

        def upd(w, grad, axes):
            for ax in axes:
                grad = jax.lax.pmean(grad, ax)
            return (w.astype(jnp.float32) - lr * grad.astype(jnp.float32)).astype(w.dtype)

        # replicated attention weights: every shard saw different tokens →
        # reduce over BOTH axes; mp-sharded MLP slices reduce over dp only
        new = (
            upd(wq, grads[0], ("dp", "mp")),
            upd(wk, grads[1], ("dp", "mp")),
            upd(wv, grads[2], ("dp", "mp")),
            upd(wo, grads[3], ("dp", "mp")),
            upd(w1, grads[4], ("dp",)),
            upd(w2, grads[5], ("dp",)),
        )
        return (loss, *new)

    loss, wq, wk, wv, wo, w1, w2 = step(
        params["wq"], params["wk"], params["wv"], params["wo"],
        params["w1"], params["w2"], x,
    )
    return loss, {
        "wq": wq, "wk": wk, "wv": wv, "wo": wo, "w1": w1, "w2": w2,
    }


def transformer_burn_in(
    mesh: Optional[Mesh] = None,
    steps: int = 3,
    batch_per_dp: int = 4,
    seq_per_mp: int = 16,
    d_model: int = 128,
    d_hidden: int = 256,
    heads: int = 4,
) -> dict:
    """Acceptance run of the transformer step; same contract as burn_in."""
    mesh = mesh or make_mesh()
    dp, mp = mesh.shape["dp"], mesh.shape["mp"]
    params = transformer_params(mesh, d_model=d_model, d_hidden=d_hidden)
    x = jax.device_put(
        jax.random.normal(
            jax.random.PRNGKey(1), (batch_per_dp * dp, seq_per_mp * mp, d_model),
            jnp.bfloat16,
        ),
        NamedSharding(mesh, P("dp", "mp", None)),
    )
    return _acceptance_run(
        mesh, jax.jit(functools.partial(transformer_step, mesh, heads)),
        params, x, steps, name="transformer",
    )


# ---------------------------------------------------------------------------
# The FULL composition: pipeline-parallel stack of transformer stages.
# Mesh (pp, dp, mp): each pp shard owns one transformer layer's weights
# (GPipe microbatch streaming, pipeline.py's tick/feed/land machinery),
# and INSIDE each stage the layer runs exactly like transformer_step —
# batch over dp, ring-attention sequence parallelism over mp, Megatron-SP
# MLP over mp.  One shard_map, one differentiable program: tp/pp/dp/sp in
# a single train step (ep has its own mesh in workloads/moe.py — routing
# wants the full axis for its all-to-all, not a leftover factor).


def make_mesh3(n_devices: Optional[int] = None, devices: Optional[list] = None) -> Mesh:
    """3-D (pp, dp, mp) mesh; mp innermost (fastest ICI), pp outermost —
    stage hops are the rarest collective (one ppermute per tick) so they
    take the slowest axis."""
    devices = devices if devices is not None else jax.devices()
    if n_devices:
        devices = devices[:n_devices]
    n = len(devices)
    pp = 2 if n % 2 == 0 and n >= 4 else 1
    dp, mp = _split_dp_mp(n // pp)
    return Mesh(np.array(devices).reshape(pp, dp, mp), ("pp", "dp", "mp"))


def transformer_pipeline_params(
    mesh: Mesh, d_model: int = 128, d_hidden: int = 256, seed: int = 0
):
    """Per-stage transformer weights, stage axis sharded over pp, MLP
    halves additionally Megatron-split over mp."""
    pp = mesh.shape["pp"]
    ks = jax.random.split(jax.random.PRNGKey(seed), 6)
    scale = 1.0 / np.sqrt(d_model)

    def mk(k, shape, spec):
        return jax.device_put(
            jax.random.normal(k, shape, jnp.bfloat16) * scale,
            NamedSharding(mesh, spec),
        )

    return {
        "wq": mk(ks[0], (pp, d_model, d_model), P("pp", None, None)),
        "wk": mk(ks[1], (pp, d_model, d_model), P("pp", None, None)),
        "wv": mk(ks[2], (pp, d_model, d_model), P("pp", None, None)),
        "wo": mk(ks[3], (pp, d_model, d_model), P("pp", None, None)),
        "w1": mk(ks[4], (pp, d_model, d_hidden), P("pp", None, "mp")),
        "w2": mk(ks[5], (pp, d_hidden, d_model), P("pp", "mp", None)),
    }


def transformer_pipeline_step(
    mesh: Mesh, heads: int, params: dict, x: jax.Array, lr: float = 0.05,
    use_pallas: bool = False, check_vma: bool = True,
) -> tuple[jax.Array, dict]:
    """One SGD step of the pp-stage pipelined transformer stack on x
    [M, B, S, D] microbatches sharded P(None, "dp", "mp", None).  Returns
    (loss, new_params).  ``use_pallas``: fused flash fwd + FA2 backward
    kernels inside each stage; ``check_vma``: TEST-ONLY, see
    transformer_step."""
    pp, dp, mp = mesh.shape["pp"], mesh.shape["dp"], mesh.shape["mp"]
    axes = ("pp", "dp", "mp")

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P("pp", None, None), P("pp", None, None), P("pp", None, None),
            P("pp", None, None), P("pp", None, "mp"), P("pp", "mp", None),
            P(None, "dp", "mp", None),
        ),
        out_specs=(
            P(),
            P("pp", None, None), P("pp", None, None), P("pp", None, None),
            P("pp", None, None), P("pp", None, "mp"), P("pp", "mp", None),
        ),
        check_vma=check_vma,
    )
    def step(wq, wk, wv, wo, w1, w2, xs):
        m, b, s_loc, d = xs.shape
        s_pp = jax.lax.axis_index("pp")
        fwd = [(i, i + 1) for i in range(pp - 1)]

        def layer(h_in, wq, wk, wv, wo, w1, w2):
            """transformer_step's stage body on [b, s_loc, d] (f32 carry
            for the scan; the layer math itself is bf16)."""
            return _layer_fwd(
                h_in, wq, wk, wv, wo, w1, w2, heads, axes, use_pallas
            ).astype(jnp.float32)

        def loss_fn(wq, wk, wv, wo, w1, w2):
            wq, wk, wv, wo, w1, w2 = (w[0] for w in (wq, wk, wv, wo, w1, w2))
            ticks = m + pp - 1

            def feed(t):
                mbi = jnp.clip(t, 0, m - 1)
                return jax.lax.dynamic_slice(
                    xs, (mbi, 0, 0, 0), (1, b, s_loc, d)
                )[0].astype(jnp.float32)

            x0 = jnp.where(s_pp == 0, feed(jnp.int32(0)),
                           jnp.zeros((b, s_loc, d), jnp.float32))
            # the carry accumulates a masked SCALAR, not the [m, b, s, d]
            # output buffer: under value_and_grad every tick's carry is an
            # AD residual, and a full buffer carry would cost
            # O(ticks · m · tokens) backward memory — defeating the O(1)
            # residual budget the ring-attention remat buys this step
            total0 = _vary(jnp.float32(0), axes)

            def tick(carry, t):
                x_cur, total = carry
                y = layer(x_cur, wq, wk, wv, wo, w1, w2)
                # the last stage lands microbatch j = t - (pp-1); drain
                # garbage never lands (j caps at m-1 on the final tick)
                j = t - (pp - 1)
                total = total + jnp.where(
                    (s_pp == pp - 1) & (j >= 0), jnp.sum(jnp.square(y)), 0.0
                )
                recv = jax.lax.ppermute(y, "pp", fwd)
                x_next = jnp.where(s_pp == 0, feed(t + 1), recv)
                return (x_next, total), None

            (_, total), _ = jax.lax.scan(
                tick, (x0, total0), jnp.arange(ticks, dtype=jnp.int32)
            )
            # loss lives on the last stage (zeros elsewhere): psum over pp
            # picks it up, dp/mp sum their token shards
            for ax in ("mp", "dp", "pp"):
                total = jax.lax.psum(total, ax)
            count = m * b * dp * s_loc * mp * d
            return total / count

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2, 3, 4, 5))(
            wq, wk, wv, wo, w1, w2
        )

        def upd(w, grad, reduce_axes):
            # w and grad both carry the [1, ...] per-shard stage axis
            for ax in reduce_axes:
                grad = jax.lax.pmean(grad, ax)
            return (w.astype(jnp.float32) - lr * grad.astype(jnp.float32)).astype(w.dtype)

        # stage weights are private to their pp shard (NO pp reduction);
        # every stage's weights are shared across its dp x mp region,
        # except the mp-split MLP halves which reduce over dp alone
        new = (
            upd(wq, grads[0], ("dp", "mp")),
            upd(wk, grads[1], ("dp", "mp")),
            upd(wv, grads[2], ("dp", "mp")),
            upd(wo, grads[3], ("dp", "mp")),
            upd(w1, grads[4], ("dp",)),
            upd(w2, grads[5], ("dp",)),
        )
        return (loss, *new)

    loss, wq, wk, wv, wo, w1, w2 = step(
        params["wq"], params["wk"], params["wv"], params["wo"],
        params["w1"], params["w2"], x,
    )
    return loss, {
        "wq": wq, "wk": wk, "wv": wv, "wo": wo, "w1": w1, "w2": w2,
    }


def transformer_pipeline_burn_in(
    mesh: Optional[Mesh] = None,
    steps: int = 3,
    microbatches: int = 4,
    batch_per_dp: int = 2,
    seq_per_mp: int = 16,
    d_model: int = 64,
    d_hidden: int = 128,
    heads: int = 4,
) -> dict:
    """Acceptance run of the full tp/pp/dp/sp composition; same contract
    as burn_in."""
    mesh = mesh or make_mesh3()
    dp, mp = mesh.shape["dp"], mesh.shape["mp"]
    params = transformer_pipeline_params(mesh, d_model=d_model, d_hidden=d_hidden)
    x = jax.device_put(
        jax.random.normal(
            jax.random.PRNGKey(1),
            (microbatches, batch_per_dp * dp, seq_per_mp * mp, d_model),
            jnp.float32,
        ),
        NamedSharding(mesh, P(None, "dp", "mp", None)),
    )
    result = _acceptance_run(
        mesh, jax.jit(functools.partial(transformer_pipeline_step, mesh, heads)),
        params, x, steps, name="transformer-pp",
    )
    if mesh.shape["pp"] == 1:
        # make_mesh3 degrades to pp=1 below 4 chips: the math still runs
        # but no stage boundary is crossed — say so rather than let a
        # dead pp ICI path read as exercised
        result["pp_degenerate"] = True
    return result
