"""TPU validation workloads: vector-add, allreduce benchmark, sharded burn-in.

These replace the reference's CUDA workload images (cuda-workload-validation
vectorAdd, validator/main.go:1189-1302) with TPU-native XLA programs:

- ``vector_add``           — single-chip sanity via a Pallas kernel (MXU-free
                             VPU path; interpret mode off-TPU)
- ``allreduce_benchmark``  — psum over all local chips via shard_map on a 1-D
                             mesh; reports achieved algorithm bandwidth GB/s
                             (the BASELINE.json "ICI GB/s" metric)
- ``burn_in_step``         — jitted (dp, mp)-sharded matmul chain exercising
                             MXU + all_gather/reduce_scatter/psum over ICI;
                             the slice acceptance test run by the jax
                             validation component on multi-host slices
"""

from __future__ import annotations

import functools
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_operator.workloads import timing


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# vector add (pallas)


def _add_kernel(x_ref, y_ref, o_ref):
    o_ref[...] = x_ref[...] + y_ref[...]


def pallas_vector_add(x: jax.Array, y: jax.Array) -> jax.Array:
    """Tiled elementwise add; (8,128)-aligned blocks feed the VPU."""
    assert x.ndim == 2, "expects 2D (n, 128k) input"
    block = (min(x.shape[0], 256), min(x.shape[1], 512))
    grid = (pl.cdiv(x.shape[0], block[0]), pl.cdiv(x.shape[1], block[1]))
    return pl.pallas_call(
        _add_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(block, lambda i, j: (i, j)),
            pl.BlockSpec(block, lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec(block, lambda i, j: (i, j)),
        interpret=not _on_tpu(),
    )(x, y)


def vector_add(n: int = 1 << 20, seed: int = 0) -> dict:
    """CUDA vectorAdd analogue; returns {'ok', 'n', 'max_error'}."""
    cols = 512
    rows = max(8, n // cols)
    key = jax.random.PRNGKey(seed)
    kx, ky = jax.random.split(key)
    x = jax.random.normal(kx, (rows, cols), jnp.float32)
    y = jax.random.normal(ky, (rows, cols), jnp.float32)
    out = jax.jit(pallas_vector_add)(x, y)
    err = float(jnp.max(jnp.abs(out - (x + y))))
    return {"ok": err < 1e-5, "n": rows * cols, "max_error": err, "backend": jax.default_backend()}


# ---------------------------------------------------------------------------
# allreduce bandwidth


def allreduce_benchmark(
    size_mb: float = 64.0,
    iters: int = 10,
    warmup: int = 2,
    devices: Optional[list] = None,
    best_of: int = 3,
) -> dict:
    """psum a bf16 buffer across all chips; report achieved algbw GB/s.

    Ring-allreduce algorithm bandwidth: each chip moves 2*(n-1)/n * size
    bytes, so algbw = size / t and busbw = algbw * 2*(n-1)/n (NCCL-tests
    convention, reported the same way so numbers compare 1:1 with the
    reference's GPU fleet tooling).

    Methodology (r03): ``iters`` collectives run inside ONE compiled
    fori_loop with a single scalar readback — per-dispatch timing is
    untrustworthy on tunneled PJRT backends and host sync would serialize
    the ICI — and the dispatch+readback floor (a null program) is
    subtracted.  ``best_of`` repetitions with min/median reported: the r02
    round's 19% "regression" was single-shot noise nobody could see.
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("x",))
    elems_per_dev = max(1, int(size_mb * 1024 * 1024 / 2 / n))  # bf16 = 2 bytes
    # pad to lane width
    elems_per_dev = (elems_per_dev + 127) // 128 * 128
    global_elems = elems_per_dev * n

    sharding = NamedSharding(mesh, P("x"))
    if jax.process_count() > 1:
        # multi-controller (the distributed validation program): every
        # process contributes its local shards; device_put can't scatter a
        # host array across processes
        local = np.ones(
            (elems_per_dev * jax.local_device_count(),), np.float32
        ).astype(jnp.bfloat16)
        x = jax.make_array_from_process_local_data(sharding, local)
    else:
        x = jax.device_put(jnp.ones((global_elems,), jnp.bfloat16), sharding)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=P("x"), out_specs=P("x")
    )
    def chain(shard):
        if n > 1:
            # value stays exactly 1.0 every round: psum -> n, /n -> 1
            # (the replicated psum result must re-enter the loop as the
            # device-varying carry the fori_loop signature requires; pcast
            # replaced pvary in newer jax — keep the fallback for older)
            if hasattr(jax.lax, "pcast"):
                _vary = lambda v: jax.lax.pcast(v, "x", to="varying")  # noqa: E731
            else:  # pragma: no cover — older jax
                _vary = lambda v: jax.lax.pvary(v, "x")  # noqa: E731
            body = lambda _, s: _vary(jax.lax.psum(s, "x") / n)  # noqa: E731
            expected = 1.0
        else:
            # single chip moves no ICI traffic; accumulate so the loop body
            # is a real HBM read+write per iteration instead of an identity
            # XLA would fold away (reported as hbm-local, never gated)
            body = lambda _, s: s + 1  # noqa: E731
            expected = 1.0 + iters
        out = jax.lax.fori_loop(0, iters, body, shard)
        return out - (expected - 1.0)  # normalize back to ones

    @jax.jit
    def err(y):
        return jnp.max(jnp.abs(y.astype(jnp.float32) - 1.0))

    # dispatch + scalar-readback floor (min of 3: one noisy sample must not
    # over-subtract and inflate the reported bandwidth past the gate)
    float(err(x))  # compile
    overheads = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(err(x))
        overheads.append(time.perf_counter() - t0)
    overhead = min(overheads)

    for _ in range(max(1, warmup)):
        float(err(chain(x)))  # compile + settle
    raw = []
    max_err = 0.0
    for _ in range(best_of):
        t0 = time.perf_counter()
        # worst error across ALL reps: a corrupt repetition must fail the
        # check even when a later one is clean
        max_err = max(max_err, float(err(chain(x))))
        raw.append(time.perf_counter() - t0)
    # shared rule (workloads/timing.py): when the floor rivals the compute
    # (tiny buffers or a huge dispatch RTT) subtraction is meaningless —
    # report the unsubtracted, deflated rate and flag it so gates skip
    # rather than trust either direction
    times, overhead_dominated = timing.subtract_floor(raw, overhead, per=iters)
    dt = times[0]
    dt_median = times[len(times) // 2]

    size_bytes = global_elems * 2
    algbw = size_bytes / dt / 1e9
    busbw = algbw * (2 * (n - 1) / n) if n > 1 else algbw
    ok = max_err < 0.1
    return {
        "ok": ok,
        "devices": n,
        "size_mb": size_bytes / 1e6,
        "time_ms": dt * 1e3,
        "time_ms_median": dt_median * 1e3,
        "overhead_ms": overhead * 1e3,
        "overhead_dominated": overhead_dominated,
        "best_of": best_of,
        "algbw_gbps": algbw,
        "algbw_gbps_median": size_bytes / dt_median / 1e9,
        "busbw_gbps": busbw,
        "max_error": max_err,
        # n=1 moves no inter-chip traffic: the number is an HBM copy rate,
        # not an ICI bandwidth, and must never be gated or reported as one
        "transport": "ici" if n > 1 else "hbm-local",
        "backend": jax.default_backend(),
    }


def apply_allreduce_gate(result: dict, min_gbps: float) -> dict:
    """The ICI bandwidth gate policy, in ONE place (the workload-pod and the
    distributed multi-host paths must enforce identical rules):

    - gates busbw (the link-rate-comparable NCCL-tests number)
    - only over real ICI (single-chip HBM copy rates are never gated)
    - only on backends named in ALLREDUCE_GATE_BACKENDS (default tpu —
      CPU/gloo rates say nothing about ICI health)
    - never when the measurement was overhead-dominated (can't be trusted
      in either direction)

    Mutates ``result``: records ``min_gbps`` and whether the gate was
    actually ``gated`` (enforced), and flips ``ok`` on a miss."""
    backends = [
        b.strip()
        for b in os.environ.get("ALLREDUCE_GATE_BACKENDS", "tpu").split(",")
    ]
    enforced = (
        min_gbps > 0
        and result.get("transport") == "ici"
        and result.get("backend") in backends
        and not result.get("overhead_dominated")
    )
    result["min_gbps"] = min_gbps
    result["gated"] = enforced
    if enforced and result["busbw_gbps"] < min_gbps:
        result["ok"] = False
        result["error"] = (
            f"busbw {result['busbw_gbps']:.1f} < required {min_gbps} GB/s"
        )
    return result


# ---------------------------------------------------------------------------
# sharded burn-in (slice acceptance test)


def make_mesh(n_devices: Optional[int] = None, devices: Optional[list] = None) -> Mesh:
    """2-D (dp, mp) mesh over the available chips; mp rides the fastest ICI
    dimension (innermost), dp the outer."""
    devices = devices if devices is not None else jax.devices()
    if n_devices:
        devices = devices[:n_devices]
    n = len(devices)
    # both axes populated when possible so dp and mp collectives both flow
    if n == 1:
        mp = 1
    elif n % 4 == 0 and n > 4:
        mp = 4
    elif n % 2 == 0 and n > 2:
        mp = 2
    else:
        mp = n
    dp = n // mp
    return Mesh(np.array(devices).reshape(dp, mp), ("dp", "mp"))


def burn_in_params(mesh: Mesh, d_model: int = 512, d_hidden: int = 2048, seed: int = 0):
    """Two-layer MLP block params, mp-sharded (Megatron layout: W1 column-,
    W2 row-parallel so the block needs exactly one psum)."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    scale = 1.0 / np.sqrt(d_model)
    w1 = jax.device_put(
        (jax.random.normal(k1, (d_model, d_hidden), jnp.bfloat16) * scale),
        NamedSharding(mesh, P(None, "mp")),
    )
    w2 = jax.device_put(
        (jax.random.normal(k2, (d_hidden, d_model), jnp.bfloat16) * scale),
        NamedSharding(mesh, P("mp", None)),
    )
    return {"w1": w1, "w2": w2}


def burn_in_step(
    mesh: Mesh, params: dict, x: jax.Array, lr: float = 0.05
) -> tuple[jax.Array, dict]:
    """One real SGD train step: dp-sharded batch through an mp-sharded MLP,
    gradients pmean'd over dp, parameters updated in place — exercises MXU
    matmuls plus ICI collectives (implicit all_gather via sharding, the mp
    psum of row-parallel outputs, dp grad reduction).  Returns
    ``(loss, new_params)`` so repeated steps move the loss, making the
    acceptance test's trajectory a real signal instead of a re-run of one
    cached forward."""

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(None, "mp"), P("mp", None), P("dp", None)),
        out_specs=(P(), P(None, "mp"), P("mp", None)),
    )
    def step(w1, w2, xs):
        def loss_fn(w1, w2):
            h = jnp.maximum(xs.astype(jnp.bfloat16) @ w1, 0)  # [b, hidden/mp]
            y = h @ w2  # partial sum over mp shards
            y = jax.lax.psum(y, "mp")  # row-parallel reduce
            return jnp.mean(jnp.square(y.astype(jnp.float32)))

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(w1, w2)
        # data-parallel gradient reduction; each mp shard updates its own
        # parameter slice (grads are per-shard already — Megatron layout)
        g1 = jax.lax.pmean(grads[0], "dp")
        g2 = jax.lax.pmean(grads[1], "dp")
        new_w1 = (w1.astype(jnp.float32) - lr * g1.astype(jnp.float32)).astype(w1.dtype)
        new_w2 = (w2.astype(jnp.float32) - lr * g2.astype(jnp.float32)).astype(w2.dtype)
        return jax.lax.pmean(loss, "dp"), new_w1, new_w2

    loss, w1, w2 = step(params["w1"], params["w2"], x)
    return loss, {"w1": w1, "w2": w2}


def burn_in(
    mesh: Optional[Mesh] = None,
    steps: int = 3,
    batch: int = 64,
    d_model: int = 512,
) -> dict:
    """Run the acceptance test; returns loss trajectory + timing."""
    mesh = mesh or make_mesh()
    params = burn_in_params(mesh, d_model=d_model)
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (batch, d_model), jnp.bfloat16),
        NamedSharding(mesh, P("dp", None)),
    )
    step = jax.jit(functools.partial(burn_in_step, mesh))
    losses = []
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, params = step(params, x)
        losses.append(float(loss))
    dt = time.perf_counter() - t0
    finite = all(np.isfinite(l) for l in losses)
    # real updates ⇒ the trajectory must move; a flat line means the step
    # silently stopped training (the r1 constant-loss failure mode)
    decreasing = len(losses) < 2 or losses[-1] < losses[0]
    return {
        "ok": finite and decreasing,
        "devices": mesh.size,
        "mesh": dict(zip(mesh.axis_names, mesh.devices.shape)),
        "steps": steps,
        "losses": losses,
        "time_s": dt,
        "backend": jax.default_backend(),
    }
