"""Fleet-level XLA compilation artifact cache + node-local persistent cache.

Three layers (docs/PERFORMANCE.md "Compile cache & warm-pool validation"):

1. **Node-local jax cache** — :func:`enable` points jax at the persistent
   ``jax_compilation_cache_dir`` under the node's ``/run/tpu`` hostPath, so
   re-validations on one node hit disk instead of the compiler.  This was
   the whole module before the fleet plane existed.

2. **Artifact plane** — :class:`ArtifactStore`: content-addressed storage of
   serialized XLA executables keyed on :class:`CacheKey` (TPU generation,
   slice topology, jax/libtpu version, program fingerprint).  Entries are
   single-file envelopes carrying an integrity sha256 over the payload,
   published atomically (tmp + ``os.replace`` — a crash mid-write can never
   leave a truncated artifact a reader would deserialize), bounded in total
   size with LRU eviction, and counted (hits/misses/bytes) into the flight
   recorder → agent push → fleet aggregator chain as
   ``tpu_workload_compile_cache_*`` counters.

3. **Seeding plane** — :class:`FleetCacheClient` (workload side) +
   :class:`FleetCompileCache` (operator side, served by the Manager next to
   ``/push`` and relayed by the node metrics agent): the first node of each
   (generation, topology, versions) *kind* to validate publishes its
   artifacts; later validators :func:`prewarm` their local store before the
   first jit trace, so fleet re-validation pays one compile per kind plus a
   disk read per node instead of one compile per node.

The AOT helpers (:func:`aot_fingerprint` / :func:`compile_or_fetch`) wrap
jax's explicit lowering path: the program fingerprint hashes the lowered
StableHLO text (tracing is ~ms; XLA compilation is the 100ms–10s cost being
cached), and the artifact payload is ``jax.experimental.serialize_executable``
output.

Trust model: the envelope sha256 proves INTEGRITY (a torn or bit-flipped
transfer is recompiled, never loaded), not AUTHENTICITY — the fleet routes
are unauthenticated cluster-internal ports like ``/push``.  Because the
serialized-executable payload is a pickle, :func:`load_serialized`
deserializes BOTH pickle layers through restricted unpicklers that admit
only the enumerated jax/numpy bookkeeping classes a real artifact
references and refuse every other global — a crafted payload cannot name
arbitrary callables, it can at worst fail to load and cost a recompile.
The executable bytes themselves
are handed to XLA's own deserializer, the same surface jax's persistent
compilation cache trusts; deployments that cannot trust the cluster
network should leave ``TPU_FLEET_CACHE_URL`` unset (node-local caching
still works).

Everything here is an optimization, never a gate: any failure (unusable
path, corrupt artifact, unreachable fleet cache) falls back to compiling.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import pickle
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import asdict, dataclass
from typing import Callable, Optional

log = logging.getLogger("tpu_operator.compile_cache")

# environment contract (documented in docs/OBSERVABILITY.md, rendered into
# workload pods by the validator alongside TPU_COMPILE_CACHE)
ARTIFACTS_ENV = "TPU_COMPILE_CACHE_ARTIFACTS"
MAX_BYTES_ENV = "TPU_COMPILE_CACHE_MAX_BYTES"
FLEET_CACHE_URL_ENV = "TPU_FLEET_CACHE_URL"

ENVELOPE_MAGIC = "tpuxc1"
# artifact payload ceiling on BOTH the operator ingest route and the agent
# relay: the ports are unauthenticated and an unbounded body is an
# allocation amplifier (the /push discipline, sized for executables)
ARTIFACT_MAX_BYTES = 32 * 1024 * 1024
DEFAULT_STORE_MAX_BYTES = 512 * 1024 * 1024
_FETCH_TIMEOUT = 5.0


class CorruptArtifact(Exception):
    """Envelope failed parsing or integrity verification."""


@dataclass(frozen=True)
class CacheKey:
    """Identity of one compiled program: any field changing means the
    executable may be wrong for the hardware/software it would run on."""

    generation: str = ""
    topology: str = ""
    jax_version: str = ""
    libtpu_version: str = ""
    program: str = ""  # program fingerprint (lowered-HLO hash) or name

    def fingerprint(self) -> str:
        return hashlib.sha256(
            json.dumps(asdict(self), sort_keys=True).encode()
        ).hexdigest()

    def kind(self) -> str:
        """The warm-pool grouping: every field except the program — nodes
        of one kind can share every artifact of that kind."""
        return kind_fingerprint(
            self.generation, self.topology, self.jax_version, self.libtpu_version
        )


def key_from_fields(raw: dict) -> CacheKey:
    """CacheKey from an untrusted header field map (unknown fields
    dropped, values coerced to str) — the one construction rule shared by
    the envelope parser and the fleet index."""
    return CacheKey(**{
        f: str(raw.get(f, ""))
        for f in ("generation", "topology", "jax_version", "libtpu_version", "program")
    })


def kind_fingerprint(
    generation: str, topology: str, jax_version: str = "", libtpu_version: str = ""
) -> str:
    return hashlib.sha256(json.dumps(
        [generation, topology, jax_version, libtpu_version]
    ).encode()).hexdigest()


def current_versions() -> tuple[str, str]:
    """(jax version, libtpu version) of this process — the software half of
    every :class:`CacheKey` minted locally."""
    try:
        import jax

        jax_version = jax.__version__
    except Exception:  # noqa: BLE001 — keying must work without a backend
        jax_version = ""
    return jax_version, os.environ.get("TPU_LIBTPU_VERSION", "")


# ---------------------------------------------------------------------------
# Envelope codec.


def build_envelope(key: CacheKey, payload: bytes, created: Optional[float] = None) -> bytes:
    header = {
        "magic": ENVELOPE_MAGIC,
        "name": key.fingerprint(),
        "key": asdict(key),
        "sha256": hashlib.sha256(payload).hexdigest(),
        "size": len(payload),
        "created": round(created if created is not None else time.time(), 3),
    }
    return json.dumps(header, sort_keys=True).encode() + b"\n" + payload


def parse_envelope(data: bytes) -> tuple[CacheKey, dict, bytes]:
    """(key, header, payload) or :class:`CorruptArtifact`.  Every check a
    reader needs before trusting the payload lives here: magic, key/name
    consistency (content addressing), declared size, and the payload
    sha256 — a truncated or bit-flipped artifact is rejected, never
    deserialized."""
    newline = data.find(b"\n")
    if newline < 0:
        raise CorruptArtifact("no header line")
    try:
        header = json.loads(data[:newline])
    except (UnicodeDecodeError, ValueError) as e:
        raise CorruptArtifact(f"unparsable header: {e}") from e
    if not isinstance(header, dict) or header.get("magic") != ENVELOPE_MAGIC:
        raise CorruptArtifact("bad magic")
    raw_key = header.get("key")
    if not isinstance(raw_key, dict):
        raise CorruptArtifact("missing key")
    key = key_from_fields(raw_key)
    if header.get("name") != key.fingerprint():
        raise CorruptArtifact("name does not match key (content addressing broken)")
    payload = data[newline + 1:]
    if header.get("size") != len(payload):
        raise CorruptArtifact(
            f"truncated payload: header says {header.get('size')}, got {len(payload)}"
        )
    if header.get("sha256") != hashlib.sha256(payload).hexdigest():
        raise CorruptArtifact("payload sha256 mismatch")
    return key, header, payload


# ---------------------------------------------------------------------------
# Artifact plane.


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    puts: int = 0
    evictions: int = 0
    corrupt: int = 0
    bytes_read: int = 0
    bytes_written: int = 0
    prewarmed: int = 0
    published: int = 0

    def as_metrics(self) -> dict:
        return {
            "cache_hits": float(self.hits),
            "cache_misses": float(self.misses),
            "cache_bytes": float(self.bytes_read + self.bytes_written),
        }


class ArtifactStore:
    """Content-addressed artifact directory with integrity verification,
    atomic publication, and a byte-bounded LRU.

    Thread/process-safe by construction rather than locks: concurrent
    writers of one key both publish whole files via ``os.replace`` (last
    writer wins an identical artifact), and readers verify integrity, so
    no interleaving can surface a torn entry."""

    SUFFIX = ".xc"

    def __init__(self, root: str, max_bytes: Optional[int] = None):
        self.root = root
        env_max = os.environ.get(MAX_BYTES_ENV, "")
        if max_bytes is None:
            try:
                max_bytes = int(env_max) if env_max else DEFAULT_STORE_MAX_BYTES
            except ValueError:
                max_bytes = DEFAULT_STORE_MAX_BYTES
        self.max_bytes = max(0, max_bytes)
        self.stats = CacheStats()

    def path_for(self, key: CacheKey) -> str:
        return os.path.join(self.root, key.fingerprint() + self.SUFFIX)

    # ------------------------------------------------------------------
    def get(self, key: CacheKey) -> Optional[bytes]:
        """The verified payload, or None (miss).  A corrupt entry is
        deleted and recompiled — a wrong executable must never load."""
        path = self.path_for(key)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            self.stats.misses += 1
            return None
        try:
            stored_key, _, payload = parse_envelope(data)
            if stored_key != key:
                raise CorruptArtifact("stored key differs from requested key")
        except CorruptArtifact as e:
            log.warning("corrupt artifact %s: %s (recompiling)", path, e)
            self.stats.corrupt += 1
            self.stats.misses += 1
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        self.stats.hits += 1
        self.stats.bytes_read += len(payload)
        try:
            os.utime(path)  # LRU recency
        except OSError:
            pass
        return payload

    def put(self, key: CacheKey, payload: bytes) -> Optional[str]:
        """Atomic tmp+replace publication; returns the path, or None when
        persistence failed (the compile result is still usable in-memory —
        the cache is an optimization, never a gate)."""
        path = self.path_for(key)
        try:
            os.makedirs(self.root, exist_ok=True)
            envelope = build_envelope(key, payload)
            tmp = path + f".{os.getpid()}.{threading.get_ident()}.tmp"
            with open(tmp, "wb") as f:
                f.write(envelope)
            os.replace(tmp, path)
        except OSError as e:
            log.warning("artifact publish failed for %s: %s", path, e)
            return None
        self.stats.puts += 1
        self.stats.bytes_written += len(payload)
        self._evict_lru()
        return path

    def get_or_compile(
        self, key: CacheKey, compile_fn: Callable[[], bytes]
    ) -> tuple[bytes, bool]:
        """(payload, hit?).  Misses run ``compile_fn`` and publish."""
        payload = self.get(key)
        if payload is not None:
            return payload, True
        payload = compile_fn()
        self.put(key, payload)
        return payload, False

    # ------------------------------------------------------------------
    def entries(self) -> list[tuple[str, dict]]:
        """(artifact name, header) per entry — the publication manifest.
        Header-line reads only (a manifest walk must not pay payload
        bytes); unparsable headers are skipped, and payload integrity is
        verified where the payload is actually consumed (``get``, fleet
        ingest)."""
        out: list[tuple[str, dict]] = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in sorted(names):
            if not name.endswith(self.SUFFIX):
                continue
            header = self.read_header(name[: -len(self.SUFFIX)])
            if (
                header is None
                or header.get("name") != name[: -len(self.SUFFIX)]
                or not isinstance(header.get("key"), dict)
            ):
                continue
            out.append((header["name"], header))
        return out

    def read_envelope(self, name: str) -> Optional[bytes]:
        """Raw envelope bytes by artifact name (for publication/serving);
        name is validated as a hex digest so a request can never traverse
        out of the store root."""
        if not valid_artifact_name(name):
            return None
        try:
            with open(os.path.join(self.root, name + self.SUFFIX), "rb") as f:
                return f.read()
        except OSError:
            return None

    def exists(self, name: str) -> bool:
        """Cheap liveness probe (the LRU may have evicted the file) — no
        payload read; callers that need the bytes still go through the
        verifying readers."""
        return valid_artifact_name(name) and os.path.isfile(
            os.path.join(self.root, name + self.SUFFIX)
        )

    def read_header(self, name: str) -> Optional[dict]:
        """The envelope's header line only — index/manifest probes must
        not pay a multi-MB payload read per entry.  Unparsable headers
        read as absent (the verifying readers prune them on access)."""
        if not valid_artifact_name(name):
            return None
        try:
            with open(os.path.join(self.root, name + self.SUFFIX), "rb") as f:
                line = f.readline(1 << 20)
        except OSError:
            return None
        try:
            header = json.loads(line)
        except (UnicodeDecodeError, ValueError):
            return None
        if not isinstance(header, dict) or header.get("magic") != ENVELOPE_MAGIC:
            return None
        return header

    def total_bytes(self) -> int:
        total = 0
        try:
            for name in os.listdir(self.root):
                if name.endswith(self.SUFFIX):
                    total += os.path.getsize(os.path.join(self.root, name))
        except OSError:
            pass
        return total

    def _evict_lru(self) -> None:
        """Drop oldest-touched entries until within ``max_bytes``.  The
        just-published entry carries the newest mtime, so it goes last —
        it is evicted only when it alone exceeds the whole bound (an
        artifact bigger than the budget must not pin the store forever)."""
        if not self.max_bytes:
            return
        try:
            entries = [
                (os.path.getmtime(p), p, os.path.getsize(p))
                for name in os.listdir(self.root)
                if name.endswith(self.SUFFIX)
                for p in (os.path.join(self.root, name),)
            ]
        except OSError:
            return
        total = sum(size for _, _, size in entries)
        entries.sort()  # oldest mtime first
        for _, path, size in entries:
            if total <= self.max_bytes:
                break
            try:
                os.remove(path)
            except OSError:
                continue
            total -= size
            self.stats.evictions += 1

    # ------------------------------------------------------------------
    def record_flight_sample(self) -> None:
        """Surface the counters through the ambient flight recorder (→
        agent push → fleet aggregator as tpu_workload_compile_cache_*);
        no-op in untracked processes like every flight record call."""
        try:
            from tpu_operator.obs import flight

            flight.record("compile-cache", phase="cache", **self.stats.as_metrics())
        except Exception as e:  # noqa: BLE001 — telemetry must never gate
            log.debug("compile-cache flight sample failed: %s", e)


def valid_artifact_name(name: str) -> bool:
    """64-hex content digest — the one naming rule every surface (store,
    operator routes, agent relay) validates with; kind fingerprints share
    the shape."""
    return (
        isinstance(name, str)
        and len(name) == 64
        and all(c in "0123456789abcdef" for c in name)
    )


def default_store(root: Optional[str] = None) -> Optional[ArtifactStore]:
    """The node-local store under the artifact dir contract, or None when
    no location is configured (tests and dryruns must never write a
    persistent cache to the real host implicitly — the enable() rule)."""
    root = root or os.environ.get(ARTIFACTS_ENV, "")
    if not root or root == "0":
        return None
    return ArtifactStore(root)


# ---------------------------------------------------------------------------
# Seeding plane: fleet cache server object + workload-side client.


class FleetCompileCache:
    """Operator-side artifact cache: an :class:`ArtifactStore` plus a
    kind index, served by the Manager's HTTP surface (``/compile-cache/*``
    next to ``/push``) and relayed by the node metrics agent.

    Ingest re-verifies every envelope (integrity + content addressing) —
    the port is unauthenticated, and a corrupt or mis-keyed upload must be
    rejected at the door, never served to a warm-pool node.  Thread-safe:
    ingest arrives from the event loop, reads may come from anywhere."""

    MAX_ARTIFACTS = 4096  # distinct programs ceiling (cardinality guard)

    def __init__(self, root: str, max_bytes: Optional[int] = None, metrics=None):
        self.store = ArtifactStore(root, max_bytes=max_bytes)
        self.metrics = metrics
        self._lock = threading.Lock()
        # kind fingerprint -> {artifact name -> header}
        self._index: dict[str, dict[str, dict]] = {}
        self._names: set[str] = set()
        for name, header in self.store.entries():  # warm restart: reindex
            self._index_entry(name, header)

    def _index_entry(self, name: str, header: dict) -> None:
        key = key_from_fields(header["key"])
        with self._lock:
            self._index.setdefault(key.kind(), {})[name] = header
            self._names.add(name)

    # ------------------------------------------------------------------
    def _prune_dead(self) -> None:
        """Drop index entries whose backing file the store's LRU evicted —
        without this the MAX_ARTIFACTS cap fills permanently across
        upgrade waves (every wave mints new names) and the index serves
        phantom artifacts whose fetch 404s."""
        with self._lock:
            for kind in list(self._index):
                bucket = self._index[kind]
                for name in list(bucket):
                    if not self.store.exists(name):
                        del bucket[name]
                        self._names.discard(name)
                if not bucket:
                    del self._index[kind]

    def ingest(self, data: bytes) -> tuple[bool, str]:
        """(accepted?, artifact name or error).  Size cap is enforced by
        the HTTP route before the body reaches here."""
        try:
            key, header, payload = parse_envelope(data)
        except CorruptArtifact as e:
            self._count("rejected")
            return False, str(e)
        name = header["name"]
        # known AND still on disk ⇒ idempotent duplicate; a known name
        # whose file was LRU-evicted must re-store, not answer "duplicate"
        # while warm nodes 404 on the fetch
        with self._lock:
            known = name in self._names
        if known and self.store.exists(name):
            self._count("duplicate")
            return True, name  # idempotent re-publish (concurrent seeders)
        with self._lock:
            at_cap = not known and len(self._names) >= self.MAX_ARTIFACTS
        if at_cap:
            self._prune_dead()
            with self._lock:
                if len(self._names) >= self.MAX_ARTIFACTS:
                    self._count("rejected")
                    return False, "artifact cap reached"
        if self.store.put(key, payload) is None:
            self._count("rejected")
            return False, "store write failed"
        self._index_entry(name, header)
        self._count("stored")
        self._export_gauges()
        return True, name

    def index(self, kind: str) -> list[dict]:
        with self._lock:
            entries = dict(self._index.get(kind) or {})
        out = []
        dead = False
        for name, header in sorted(entries.items()):
            if not self.store.exists(name):
                dead = True  # evicted since indexing; never advertise it
                continue
            out.append({
                "name": name,
                "program": header["key"].get("program", ""),
                "size": header.get("size", 0),
            })
        if dead:
            self._prune_dead()
        return out

    def has_kind(self, kind: str) -> bool:
        return bool(self.index(kind))

    def has_kind_labels(
        self, generation: str, topology: str, libtpu_version: str = ""
    ) -> bool:
        """Warmness by raw key fields, jax version ignored — the
        coordinator-side probe (the operator cannot know remote
        validators' jax versions; a kind seeded under ANY jax build
        proves the seeding plane reached it)."""
        with self._lock:
            headers = [
                (name, header)
                for bucket in self._index.values()
                for name, header in bucket.items()
            ]
        for name, header in headers:
            key = header.get("key") or {}
            if (
                key.get("generation") == generation
                and key.get("topology") == topology
                and key.get("libtpu_version") == libtpu_version
                and self.store.exists(name)
            ):
                return True
        return False

    def get(self, name: str) -> Optional[bytes]:
        data = self.store.read_envelope(name)
        if data is not None:
            self._count("served")
        return data

    # ------------------------------------------------------------------
    def _count(self, outcome: str) -> None:
        if self.metrics is not None:
            self.metrics.compile_cache_requests_total.labels(outcome=outcome).inc()

    def _export_gauges(self) -> None:
        if self.metrics is None:
            return
        with self._lock:
            n = len(self._names)
        self.metrics.compile_cache_artifacts.set(n)
        self.metrics.compile_cache_bytes.set(self.store.total_bytes())


class FleetCacheClient:
    """Workload-side HTTP client for the fleet cache (the agent relay or
    the operator surface directly, per ``TPU_FLEET_CACHE_URL``).  Blocking
    urllib on purpose — it runs in workload processes before the first jit
    trace, exactly where an event loop does not exist.  Best-effort
    everywhere: an unreachable fleet cache means compiling, not failing."""

    def __init__(self, base_url: str = "", timeout: float = _FETCH_TIMEOUT):
        self.base_url = (base_url or os.environ.get(FLEET_CACHE_URL_ENV, "")).rstrip("/")
        self.timeout = timeout

    def enabled(self) -> bool:
        return bool(self.base_url)

    def _get(self, path: str) -> Optional[bytes]:
        try:
            with urllib.request.urlopen(
                self.base_url + path, timeout=self.timeout
            ) as resp:
                return resp.read()
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def index(self, kind: str) -> list[dict]:
        data = self._get("/compile-cache/index?kind=" + urllib.parse.quote(kind))
        if data is None:
            return []
        try:
            doc = json.loads(data)
        except ValueError:
            return []
        artifacts = doc.get("artifacts")
        return artifacts if isinstance(artifacts, list) else []

    def fetch(self, name: str) -> Optional[bytes]:
        if not valid_artifact_name(name):
            return None
        return self._get("/compile-cache/artifact/" + name)

    def publish(self, envelope: bytes) -> bool:
        if len(envelope) > ARTIFACT_MAX_BYTES:
            return False
        req = urllib.request.Request(
            self.base_url + "/compile-cache/artifact",
            data=envelope,
            headers={"Content-Type": "application/octet-stream"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status < 400
        except (urllib.error.URLError, OSError, ValueError):
            return False


def prewarm(
    store: ArtifactStore,
    kind: str,
    client: Optional[FleetCacheClient] = None,
) -> int:
    """Pull this kind's fleet artifacts into the local store BEFORE the
    first jit trace; returns artifacts fetched.  Every fetched envelope is
    re-verified locally (parse_envelope inside store.put's reader path) —
    a corrupt transfer costs a recompile, never a wrong executable."""
    client = client or FleetCacheClient()
    if not client.enabled():
        return 0
    fetched = 0
    for entry in client.index(kind):
        name = entry.get("name", "")
        if not valid_artifact_name(name):
            continue
        if store.exists(name):
            continue  # already local
        data = client.fetch(name)
        if data is None:
            continue
        try:
            key, _, payload = parse_envelope(data)
        except CorruptArtifact as e:
            log.warning("prewarm: corrupt artifact %s from fleet cache: %s", name, e)
            store.stats.corrupt += 1
            continue
        if key.kind() != kind:
            continue  # server confusion; never store under a foreign kind
        if store.put(key, payload) is not None:
            fetched += 1
    store.stats.prewarmed += fetched
    return fetched


def publish_kind(
    store: ArtifactStore,
    kind: str,
    client: Optional[FleetCacheClient] = None,
) -> int:
    """Push this kind's local artifacts to the fleet cache (the seeder's
    half of the warm pool); returns artifacts accepted."""
    client = client or FleetCacheClient()
    if not client.enabled():
        return 0
    published = 0
    for name, header in store.entries():
        if key_from_fields(header["key"]).kind() != kind:
            continue
        data = store.read_envelope(name)
        if data is not None and client.publish(data):
            published += 1
    store.stats.published += published
    return published


# ---------------------------------------------------------------------------
# AOT helpers over jax's explicit lowering path.


def aot_fingerprint(fn, *args, name: str = "") -> tuple[object, str]:
    """(lowered, program fingerprint).  Tracing+lowering costs milliseconds;
    the fingerprint hashes the lowered StableHLO text, so any change to the
    program, shapes, or dtypes changes the key."""
    import jax

    lowered = jax.jit(fn).lower(*args)
    digest = hashlib.sha256(lowered.as_text().encode()).hexdigest()
    return lowered, (f"{name}:{digest}" if name else digest)


def serialize_compiled(compiled) -> bytes:
    from jax.experimental.serialize_executable import serialize

    return pickle.dumps(serialize(compiled))


# The only globals genuine serialize_executable pickles reference — the
# OUTER pickle (pytree defs around the triple) and the INNER executable
# pickle (jax AOT bookkeeping; the compiled code itself travels as opaque
# bytes through a persistent_id hook straight into XLA's deserializer).
# The restricted unpicklers below refuse everything else, so a crafted
# payload cannot resolve arbitrary callables through pickle's reduce
# machinery — the worst a hostile artifact achieves is a load failure and
# a recompile.  Enumerated empirically against the pinned jax; an
# unlisted-but-genuine global on a future jax shows up as loud recompiles
# (CorruptArtifact in the logs), never as a widened trust surface.
_PICKLE_ALLOWED_GLOBALS = {
    ("jax._src.tree_util", "default_registry"),
    ("jaxlib.xla_extension.pytree", "PyTreeDef"),
    ("jaxlib.xla_extension", "PyTreeDef"),
    ("jax._src.core", "JaxprDebugInfo"),
    ("jax._src.core", "DebugInfo"),
    ("jax._src.core", "ShapedArray"),
    ("jax._src.core", "AbstractToken"),
    ("jax._src.interpreters.pxla", "AllArgsInfo"),
    ("jax._src.interpreters.pxla", "UnloadedMeshExecutable"),
    ("jax._src.layout", "DeviceLocalLayout"),
    ("jax._src.stages", "ArgInfo"),
    ("jaxlib.xla_extension", "DeviceList"),
    ("jaxlib.xla_extension", "SingleDeviceSharding"),
    ("jaxlib.xla_extension", "GSPMDSharding"),
    ("jaxlib.xla_extension", "NamedSharding"),
    ("numpy", "dtype"),
    ("numpy.dtypes", "Float32DType"),
}


class _RestrictedFindClass:
    """Mixin: allowlisted ``find_class`` shared by both pickle layers."""

    def find_class(self, module, name):  # noqa: D102 — pickle API
        if (module, name) in _PICKLE_ALLOWED_GLOBALS:
            return super().find_class(module, name)  # type: ignore[misc]
        raise CorruptArtifact(
            f"artifact pickle references disallowed global {module}.{name}"
        )


class _OuterUnpickler(_RestrictedFindClass, pickle.Unpickler):
    pass


def load_serialized(payload: bytes):
    """``jax.experimental.serialize_executable.deserialize_and_load``
    with BOTH pickle layers restricted to the allowlist above (jax's own
    helper unpickles the inner executable unrestricted)."""
    import jax
    from jax.experimental.serialize_executable import _JaxPjrtUnpickler

    serialized, in_tree, out_tree = _OuterUnpickler(io.BytesIO(payload)).load()

    class _InnerUnpickler(_RestrictedFindClass, _JaxPjrtUnpickler):
        pass

    backend = jax.devices()[0].client
    unloaded_executable, args_info_flat, no_kwargs = _InnerUnpickler(
        io.BytesIO(serialized), backend
    ).load()
    args_info = in_tree.unflatten(args_info_flat)
    return jax.stages.Compiled(
        unloaded_executable.load(), args_info, out_tree, no_kwargs=no_kwargs
    )


def compile_or_fetch(store: Optional[ArtifactStore], key: CacheKey, lowered):
    """Load ``key``'s executable from the artifact store, else compile (and
    publish locally).  Returns ``(executable, hit?, compile_seconds)`` —
    the seconds are the *measured critical-path cost*, feeding the
    ``compile`` join-phase segment.  A payload that fails to deserialize
    (foreign runtime build despite the key, pickle drift) is treated as
    corrupt: dropped and recompiled."""
    t0 = time.perf_counter()
    if store is not None:
        payload = store.get(key)
        if payload is not None:
            try:
                executable = load_serialized(payload)
                return executable, True, time.perf_counter() - t0
            except Exception as e:  # noqa: BLE001 — any load failure ⇒ recompile
                log.warning("artifact for %s failed to load: %s", key.program, e)
                store.stats.corrupt += 1
                try:
                    os.remove(store.path_for(key))
                except OSError:
                    pass
    compiled = lowered.compile()
    if store is not None:
        try:
            store.put(key, serialize_compiled(compiled))
        except Exception as e:  # noqa: BLE001 — unserializable backend: cache skips
            log.debug("executable for %s not serializable: %s", key.program, e)
    return compiled, False, time.perf_counter() - t0


# ---------------------------------------------------------------------------
# Node-local jax persistent cache (the original layer).


def enable() -> Optional[str]:
    """Point jax at the node-local persistent compilation cache.

    STRICTLY opt-in: only an explicit ``TPU_COMPILE_CACHE=<path>`` enables it
    (the operator injects it into workload pods and the validator DS, which
    mount the backing hostPath).  No implicit default — deriving one from the
    validation root made every test run and dryrun worker silently write a
    persistent cache to the real host's /run/tpu and leak the global
    ``jax_compilation_cache_dir`` for the rest of the process.

    Must run before the first jit compilation (config updates are decisive
    at trace time).  Returns the cache dir, or None when disabled or the
    location is unusable (never fails validation over a cache) — an
    *unusable* location additionally leaves a ``compile_cache_disabled``
    flight sample carrying the reason, so ``/debug/explain`` can name why a
    node's compile phase is unexpectedly slow instead of the cache just
    silently not being there."""
    path = os.environ.get("TPU_COMPILE_CACHE", "")
    if not path or path == "0":
        return None
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # keep jax's default min-compile-time threshold (1s): each cache
        # WRITE serializes the executable, which on a tunneled backend costs
        # a device round-trip — caching every trivial program made the cold
        # validation 3x slower; only the multi-second compiles are worth it
    except Exception as e:  # noqa: BLE001 — cache is an optimization, never a gate
        _record_disabled(path, e)
        return None
    return path


def _record_disabled(path: str, error: Exception) -> None:
    """One flight sample naming why the persistent cache is off: the
    sample rides the node's flight record (and push hop), where the
    explain/critical-path tooling looks when compile time surprises."""
    log.warning("compile cache at %s unusable: %s", path, error)
    try:
        from tpu_operator.obs import flight

        flight.record(
            "compile-cache",
            phase="compile_cache_disabled",
            compile_cache_disabled=1.0,
            reason=f"{type(error).__name__}: {error}",
            path=path,
        )
    except Exception as e:  # noqa: BLE001 — telemetry must never gate
        log.debug("compile_cache_disabled flight sample failed: %s", e)
