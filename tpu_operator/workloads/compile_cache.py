"""Persistent XLA compilation cache for the validation workloads.

The validator deliberately re-proves nodes (preStop removes ``*-ready`` so
dependents re-gate; the upgrade machine deletes validator pods to force
fresh evidence), so the same XLA programs — vector-add, the chained
allreduce, the burn-in step, the matmul sweep — recompile on every
re-validation.  On a tunneled PJRT backend each compile costs ~2s, which is
most of a validation round's wall clock.  The TPU-idiomatic fix is XLA's
persistent compilation cache (``jax_compilation_cache_dir``): keyed on HLO +
backend config, so re-validations and post-restart validator pods hit disk
instead of the compiler.

The cache lives under the node's ``/run/tpu`` hostPath (workload pods mount
it), surviving pod churn but not node replacement — exactly the lifetime of
the evidence it accelerates.  Enabled ONLY by an explicit
``TPU_COMPILE_CACHE=<path>`` env (the operator injects it in-cluster);
unset or ``0`` means no persistent cache.

Reference contrast: the CUDA vectorAdd validation image
(validator/main.go:1189-1302) ships precompiled SASS so NVIDIA never pays
this cost; for XLA the persistent cache is the equivalent of shipping
compiled programs.
"""

from __future__ import annotations

import os
from typing import Optional


def enable() -> Optional[str]:
    """Point jax at the node-local persistent compilation cache.

    STRICTLY opt-in: only an explicit ``TPU_COMPILE_CACHE=<path>`` enables it
    (the operator injects it into workload pods and the validator DS, which
    mount the backing hostPath).  No implicit default — deriving one from the
    validation root made every test run and dryrun worker silently write a
    persistent cache to the real host's /run/tpu and leak the global
    ``jax_compilation_cache_dir`` for the rest of the process.

    Must run before the first jit compilation (config updates are decisive
    at trace time).  Returns the cache dir, or None when disabled or the
    location is unusable (never fails validation over a cache)."""
    path = os.environ.get("TPU_COMPILE_CACHE", "")
    if not path or path == "0":
        return None
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # keep jax's default min-compile-time threshold (1s): each cache
        # WRITE serializes the executable, which on a tunneled backend costs
        # a device round-trip — caching every trivial program made the cold
        # validation 3x slower; only the multi-second compiles are worth it
    except Exception:  # noqa: BLE001 — cache is an optimization, never a gate
        return None
    return path
