"""Multi-host distributed validation workload.

The TPU-native capability the reference never needed (SURVEY §7 hard parts
1 & 3): GPU validation is node-local (one CUDA pod per node,
validator/main.go:1189-1302), but a multi-host TPU slice is only healthy if
ALL its hosts can run ONE program over ICI.  This module is that program —
the container command of the per-host validation pods the validator spawns:

1. ``jax.distributed.initialize(coordinator, num_processes, process_id)``
   — multi-controller rendezvous (worker 0's pod is the coordinator).
2. A global psum whose expected value encodes every process's contribution
   — a wrong/absent link changes the sum, so success proves every ICI path.
3. A short sharded burn-in (real SGD steps) over the GLOBAL (dp, mp) mesh —
   MXU + collective traffic across hosts, the slice acceptance test.

Runs identically on the CPU backend (gloo collectives) for tests and the
driver's multi-chip dry-run: N processes × M virtual devices each.

Env contract (injected by the validator's pod spec):
  COORDINATOR_ADDRESS  host:port of process 0 (headless-Service DNS in-cluster)
  NUM_PROCESSES        slice host count
  PROCESS_ID           this host's worker id (falls back to TPU_WORKER_ID)
  BURN_IN_STEPS        optional, default 3
  WATCHDOG_TIMEOUT_S   peer-death detection bound (default 20; watchdog.py)
  DIST_INIT_TIMEOUT_S  rendezvous-phase bound (default 120)
  FAULT_INJECT         test-only: "<phase>:<process_id>" SIGKILLs that
                       worker at that phase entry (fault-injection tests)
"""

from __future__ import annotations

import functools
import json
import os
import signal
import sys
import time
from typing import Optional

import numpy as np

# the failing worker's phase, readable from main()'s exception handler
_LAST_PHASE: Optional[str] = None


def _enter_phase(wd, name: str, process_id: int) -> None:
    """Phase transition: record for post-mortem evidence (watchdog KV +
    drop-box + a stdout line the orchestrator can stream), then the
    fault-injection hook — a killed worker must die exactly AT the phase
    boundary the test names, after the transition is already published."""
    global _LAST_PHASE
    _LAST_PHASE = name
    if wd is not None:
        wd.set_phase(name)
    print(json.dumps({"phase": name, "process_id": process_id}), flush=True)
    spec = os.environ.get("FAULT_INJECT", "")
    if spec:
        phase, _, wid = spec.partition(":")
        if phase == name and wid.strip().isdigit() and int(wid) == process_id:
            print(
                json.dumps({"fault_injected": name, "process_id": process_id}),
                flush=True,
            )
            os.kill(os.getpid(), signal.SIGKILL)


def run_worker(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    steps: int = 3,
    d_model: int = 128,
    d_hidden: int = 256,
) -> dict:
    """Initialize the multi-controller runtime, prove the global collective,
    run the burn-in.  Returns a result dict with ``ok``."""
    import jax

    from tpu_operator import workloads

    workloads.honor_cpu_platform_request()

    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            # a member dying DURING the rendezvous strands the others inside
            # initialize(); this bounds that phase (default 300 is the whole
            # pod budget — a hung rendezvous must fail well inside it)
            initialization_timeout=int(
                float(os.environ.get("DIST_INIT_TIMEOUT_S", "120") or 120)
            ),
            # backstop only: the coordination service's own heartbeat abort.
            # The PeerWatchdog below detects peer death far sooner (and with
            # structured evidence); this bounds the corner where the
            # watchdog itself is wedged
            heartbeat_timeout_seconds=int(
                float(os.environ.get("DIST_HEARTBEAT_TIMEOUT_S", "60") or 60)
            ),
        )
    devices = jax.devices()  # GLOBAL across all processes
    local = jax.local_device_count()

    # bounded peer-death detection from here on (watchdog.py: a dead peer
    # or coordinator fails THIS worker in ~WATCHDOG_TIMEOUT_S with
    # structured evidence, instead of wedging in a collective for the
    # whole pod budget)
    wd = None
    if num_processes > 1:
        from jax._src import distributed as jax_distributed

        from tpu_operator.workloads.watchdog import DEFAULT_TIMEOUT_S, PeerWatchdog

        wd = PeerWatchdog(
            jax_distributed.global_state.client,
            process_id,
            num_processes,
            timeout=float(
                os.environ.get("WATCHDOG_TIMEOUT_S", str(DEFAULT_TIMEOUT_S))
                or DEFAULT_TIMEOUT_S
            ),
            scope=os.environ.get("RESULTS_SCOPE", ""),
        )
        wd.start()
    try:
        return _run_checks(
            wd, process_id, num_processes, devices, local, steps,
            d_model, d_hidden,
        )
    finally:
        if wd is not None:
            wd.stop()


def _run_checks(
    wd,
    process_id: int,
    num_processes: int,
    devices,
    local: int,
    steps: int,
    d_model: int,
    d_hidden: int,
) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    t0 = time.perf_counter()
    _enter_phase(wd, "device-check", process_id)

    # -- device-count truth: the validator promised chips-per-host via
    # EXPECTED_DEVICES; the runtime must have initialized exactly that many
    # locally AND processes x that many globally — a host with dead chips
    # (or a rendezvous that silently lost a member's devices) fails here
    # with the counts instead of psum-ing over the wrong mesh
    from tpu_operator.workloads import collectives

    expected_env = os.environ.get("EXPECTED_DEVICES", "")
    devcheck = None
    if expected_env:
        try:
            devcheck = collectives.device_count_check(int(expected_env), num_processes)
        except ValueError:
            # same contract as run_validation: a malformed env surfaces as
            # a structured failure, not a traceback with no evidence
            devcheck = {
                "ok": False,
                "error": f"malformed EXPECTED_DEVICES={expected_env!r}",
            }
    if devcheck is not None and not devcheck["ok"]:
        return {
            "ok": False,
            "process_id": process_id,
            "num_processes": num_processes,
            "global_devices": len(devices),
            "local_devices": local,
            "devices_check": devcheck,
            "error": devcheck.get("error", "device count mismatch"),
            "backend": jax.default_backend(),
        }

    # -- global psum proof: every process contributes (id+1) per chip; the
    # expected total is only reachable if every link carried its share
    _enter_phase(wd, "psum", process_id)
    mesh1d = Mesh(np.array(devices), ("x",))
    contrib = jax.make_array_from_process_local_data(
        NamedSharding(mesh1d, P("x")),
        np.full((local,), float(process_id + 1), np.float32),
    )

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh1d, in_specs=P("x"), out_specs=P("x"))
    def allsum(shard):
        return jax.lax.psum(shard, "x")

    total = float(np.asarray(allsum(contrib).addressable_shards[0].data)[0])
    # each process holds `local` chips of value (id+1)
    expected = float(local * sum(range(1, num_processes + 1)))
    psum_ok = total == expected

    # -- allreduce bandwidth over the global mesh: the armed ICI gate
    # (BASELINE "expected ICI GB/s").  ALLREDUCE_MIN_GBPS is injected by the
    # validator from the accelerator catalogue; the gate applies only on
    # backends named in ALLREDUCE_GATE_BACKENDS (default tpu — CPU/gloo
    # rates say nothing about ICI health)
    _enter_phase(wd, "allreduce", process_id)
    bench = collectives.allreduce_benchmark(
        size_mb=float(os.environ.get("ALLREDUCE_SIZE_MB", "16")),
        iters=5,
        warmup=1,
        devices=devices,
        best_of=2,
    )
    try:
        min_gbps = float(os.environ.get("ALLREDUCE_MIN_GBPS", "0") or 0)
    except ValueError:
        min_gbps = 0.0
    collectives.apply_allreduce_gate(bench, min_gbps)
    bw_ok = bool(bench["ok"])

    # -- ring exchange: the per-LINK diagnostic — every individual ICI hop
    # must carry its payload exactly, and the reported rate is bottlenecked
    # by the slowest link (the allreduce can't localize a bad link).
    # Report-only unless RING_MIN_GBPS arms the gate.
    _enter_phase(wd, "ring", process_id)
    ring = collectives.ring_benchmark(
        size_mb=float(os.environ.get("RING_SIZE_MB", "8")),
        iters=2,
        best_of=2,
        devices=devices,
    )
    try:
        ring_min = float(os.environ.get("RING_MIN_GBPS", "0") or 0)
    except ValueError:
        ring_min = 0.0
    collectives.apply_ring_gate(ring, ring_min)
    ring_ok = bool(ring["ok"])

    # -- burn-in over the global (dp, mp) mesh: real SGD steps with MXU
    # matmuls + cross-host collectives (mp psum, dp grad pmean)
    _enter_phase(wd, "burn-in", process_id)
    mesh = collectives.make_mesh(devices=devices)
    dp, mp = mesh.shape["dp"], mesh.shape["mp"]

    # params must be GLOBAL arrays in multi-controller mode: jit with
    # out_shardings constructs them without host-side device_put scatter
    def init(key):
        k1, k2 = jax.random.split(key)
        scale = 1.0 / np.sqrt(d_model)
        return {
            "w1": (jax.random.normal(k1, (d_model, d_hidden), jnp.bfloat16) * scale),
            "w2": (jax.random.normal(k2, (d_hidden, d_model), jnp.bfloat16) * scale),
        }

    params = jax.jit(
        init,
        out_shardings={
            "w1": NamedSharding(mesh, P(None, "mp")),
            "w2": NamedSharding(mesh, P("mp", None)),
        },
    )(jax.random.PRNGKey(0))
    # Global batch sized to the dp axis alone — every process builds the SAME
    # deterministic global array and each device picks out its own slice, so
    # the construction is correct for ANY hosts-vs-dp topology (8 single-chip
    # hosts on a dp=2 mesh included; the old per-process-local sizing only
    # tiled when num_processes divided dp).
    global_batch = 8 * dp
    gx = np.random.default_rng(1).standard_normal(
        (global_batch, d_model), dtype=np.float32
    ).astype(jnp.bfloat16)
    x = jax.make_array_from_callback(
        (global_batch, d_model),
        NamedSharding(mesh, P("dp", None)),
        lambda idx: gx[idx],
    )
    step = jax.jit(functools.partial(collectives.burn_in_step, mesh))
    losses = []
    for _ in range(steps):
        loss, params = step(params, x)
        losses.append(float(loss))
    finite = all(np.isfinite(l) for l in losses)
    decreasing = len(losses) < 2 or losses[-1] < losses[0]

    # -- ring attention over the global 1-D ring: sequence parallelism
    # ACROSS hosts — the long-context pattern (ring attention holds one KV
    # block per chip, the layout that lets sequences outgrow a host; the
    # blocks ride the same per-link ring the diagnostic above measured).
    # Exact against the single-device reference, so a wrong hop or mask is
    # a failure, not noise — which also means the PROBE's sequence must
    # stay modest (the reference gathers the full sequence).
    from tpu_operator.workloads import ring_attention

    _enter_phase(wd, "ring-attention", process_id)
    ra = ring_attention.acceptance(
        # small by default: every slice host compiles this program inside
        # its validation pod — the hop/mask/rendezvous proof needs blocks
        # to span the ring, not big ones (quick_check covers real shapes)
        seq_per_chip=int(os.environ.get("RING_ATTN_SEQ_PER_CHIP", "8")),
        heads=2, head_dim=16, devices=devices,
    )
    ra_ok = bool(ra["ok"])

    # -- expert parallelism across hosts: the MoE dispatch all-to-all is
    # the only pattern whose traffic crosses EVERY chip pair — on a
    # multi-host slice that means every DCN/ICI route at once, the
    # full-bisection proof the neighbour-ring hops above can't give.
    # Exact against the dense reference (tie-proof quantized routing).
    from tpu_operator.workloads import moe

    _enter_phase(wd, "moe", process_id)
    ep = moe.acceptance(
        tokens_per_shard=int(os.environ.get("MOE_TOKENS_PER_SHARD", "16")),
        d_model=16, d_hidden=32, devices=devices,
    )
    ep_ok = bool(ep["ok"])

    from tpu_operator.workloads.watchdog import TERMINAL_PHASE

    # publishing the terminal phase BEFORE returning is what lets peers'
    # watchdogs tell "finished and stopped beating" from "died mid-run"
    _enter_phase(wd, TERMINAL_PHASE, process_id)
    return {
        "ok": (psum_ok and finite and decreasing and bw_ok and ring_ok
               and ra_ok and ep_ok),
        "process_id": process_id,
        "num_processes": num_processes,
        "global_devices": len(devices),
        "local_devices": local,
        "mesh": {"dp": dp, "mp": mp},
        "devices_check": devcheck,
        "psum": {"total": total, "expected": expected, "ok": psum_ok},
        "allreduce": {
            k: bench.get(k)
            for k in ("ok", "busbw_gbps", "algbw_gbps", "size_mb", "transport",
                      "overhead_dominated", "min_gbps", "gated", "error")
            if k in bench
        },
        "ring": {
            k: ring.get(k)
            for k in ("ok", "link_gbps", "max_error", "hops",
                      "overhead_dominated", "min_gbps", "gated", "error")
            if k in ring
        },
        "ring_attention": {
            k: ra.get(k)
            for k in ("ok", "seq", "seq_per_chip", "causal", "max_error", "time_s")
            if k in ra
        },
        "moe": {
            k: ep.get(k)
            for k in ("ok", "experts", "tokens", "dropped_fraction",
                      "max_error", "time_s")
            if k in ep
        },
        "losses": losses,
        "time_s": time.perf_counter() - t0,
        "backend": jax.default_backend(),
    }


def free_ports(n: int) -> list[int]:
    """``n`` distinct ephemeral ports: all sockets bound SIMULTANEOUSLY
    before any is closed, so concurrent rendezvous groups can never be
    handed the same port (three independent bind/close cycles could be —
    the kernel is free to reuse a just-closed port)."""
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def spawn_local_workers_outcomes(
    num_processes: int,
    devices_per_proc: int,
    steps: int = 2,
    extra_env: Optional[dict] = None,
    timeout: float = 300,
    port: Optional[int] = None,
) -> list[dict]:
    """Spawn ``num_processes`` REAL worker processes on the CPU backend
    against a local coordinator — the one harness behind the driver's
    multi-chip dryrun and the multi-process tests (the env contract below
    is what the validator's pod spec injects in-cluster; keeping it in one
    place keeps the dryrun and the tests from diverging).

    Returns one outcome dict per worker — returncode, elapsed wall time,
    the last JSON line it printed (the result or the watchdog's evidence),
    and output tails — WITHOUT asserting success: the fault-injection
    tests need the failing shapes intact.  Callers running SEVERAL groups
    concurrently must pre-allocate distinct ``port``s via ``free_ports``."""
    import subprocess

    from tpu_operator import workloads

    if port is None:
        port = free_ports(1)[0]
    procs = []
    for wid in range(num_processes):
        env = {
            **os.environ,
            # workers re-import the package via -m; see subprocess_pythonpath
            "PYTHONPATH": workloads.subprocess_pythonpath(),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices_per_proc}",
            "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "NUM_PROCESSES": str(num_processes),
            "PROCESS_ID": str(wid),
            "BURN_IN_STEPS": str(steps),
            **(extra_env or {}),
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "tpu_operator.workloads.distributed"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    import threading

    t0 = time.monotonic()
    deadline = t0 + timeout
    # drain every worker CONCURRENTLY and stamp each one's own exit time:
    # sequential drains would credit a fast detection with the slowest
    # sibling's wall time (wrong detection-latency evidence), and polling
    # without draining would deadlock a worker that filled its pipe buffer
    drained: dict[int, tuple] = {}

    def _drain(wid: int, proc) -> None:
        out, err = proc.communicate()
        drained[wid] = (out, err, round(time.monotonic() - t0, 3))

    threads = [
        threading.Thread(target=_drain, args=(wid, p), daemon=True)
        for wid, p in enumerate(procs)
    ]
    for th in threads:
        th.start()
    outcomes = []
    try:
        for th in threads:
            th.join(timeout=max(0.1, deadline - time.monotonic()))
        for wid, (th, proc) in enumerate(zip(threads, procs)):
            timed_out = th.is_alive()
            if timed_out:
                proc.kill()
                th.join(timeout=10)
            out, err, elapsed = drained.get(
                wid, ("", "", round(time.monotonic() - t0, 3))
            )
            result = None
            for line in reversed((out or "").splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        result = json.loads(line)
                        break
                    except json.JSONDecodeError:
                        continue
            outcomes.append({
                "process_id": wid,
                "returncode": proc.returncode,
                "elapsed_s": elapsed,
                "timed_out": timed_out,
                "result": result,
                # signature scan over the FULL stderr — a LOG(FATAL) stack
                # dump can push it past any display tail
                "coordinator_loss": any(
                    sig in (err or "") for sig in _COORDINATOR_LOSS_SIGNATURES
                ),
                "stdout_tail": (out or "")[-2000:],
                "stderr_tail": (err or "")[-2000:],
            })
    finally:
        # one worker failing must not strand the rest blocked on the dead
        # coordinator with unread pipes
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
    return outcomes


def spawn_local_workers(
    num_processes: int,
    devices_per_proc: int,
    steps: int = 2,
    extra_env: Optional[dict] = None,
    timeout: float = 300,
    port: Optional[int] = None,
) -> list[dict]:
    """``spawn_local_workers_outcomes`` for the healthy path: returns each
    worker's parsed result JSON; raises AssertionError when a worker exits
    non-zero."""
    outcomes = spawn_local_workers_outcomes(
        num_processes, devices_per_proc, steps=steps,
        extra_env=extra_env, timeout=timeout, port=port,
    )
    results = []
    for o in outcomes:
        assert o["returncode"] == 0, (
            f"distributed worker {o['process_id']} failed:\n"
            f"{o['stdout_tail']}\n{o['stderr_tail']}"
        )
        results.append(o["result"])
    return results


# the runtime's own abort message when the coordination service leader
# (worker 0) disappears: the agent's error-poll RPC fails on socket close
# and LOG(FATAL)s the survivor before any Python handler can run, so this
# stderr signature IS the evidence for that shape
_COORDINATOR_LOSS_SIGNATURES = (
    "Failed to send RPC to coordination service",
    "leader task was preempted/died",
)


def rendezvous_post_mortem(outcomes: list[dict]) -> dict:
    """Classify a fault-injected (or failed) rendezvous run into structured
    evidence: which members died, how each survivor detected the failure
    (own watchdog vs runtime abort on coordinator loss), at which phase,
    and whether every survivor failed in bounded time (nobody burned the
    full pod budget waiting on a dead peer)."""
    workers = []
    directly_dead: set[int] = set()
    named_dead: set[int] = set()
    for o in outcomes:
        rc = o["returncode"]
        result = o.get("result") or {}
        fault = (result.get("fault") or {}) if isinstance(result, dict) else {}
        dead_members = [d.get("process_id") for d in fault.get("dead_members", [])]
        if rc == 0:
            kind = "succeeded"
        elif fault.get("type") == "peer-heartbeat-lost":
            kind = "watchdog-peer-death"
            named_dead.update(m for m in dead_members if m is not None)
        elif fault.get("type") == "coordinator-unreachable":
            kind = "watchdog-coordinator-loss"
            named_dead.add(0)
        elif o.get("coordinator_loss") or any(
            sig in (o.get("stderr_tail") or "")
            for sig in _COORDINATOR_LOSS_SIGNATURES
        ):
            # the runtime's LOG(FATAL) abort on coordinator loss — checked
            # BEFORE the signal branch: the abort itself is a signal death
            # (SIGABRT), but this worker was a victim, not the fault
            kind = "aborted-coordinator-loss"
            named_dead.add(0)
        elif rc is not None and rc < 0 and (
            not o.get("timed_out")
            or '"fault_injected"' in (o.get("stdout_tail") or "")
        ):
            # the injected fault itself (SIGKILL).  A fault-killed worker
            # whose drain also crossed the harness deadline is still a
            # direct death — its fault_injected stdout marker proves it —
            # so dead_members cannot under-report on a slow box.  But a
            # harness kill of a worker that merely HUNG (timed_out, no
            # marker) is not a death to attribute survivors' exits to.
            kind = "killed"
            directly_dead.add(o["process_id"])
        else:
            kind = "failed"
        workers.append({
            "process_id": o["process_id"],
            "outcome": kind,
            "returncode": rc,
            "elapsed_s": o.get("elapsed_s"),
            "timed_out": bool(o.get("timed_out")),
            "phase": result.get("phase") if isinstance(result, dict) else None,
            "dead_members": dead_members or None,
        })
    survivors = [w for w in workers if w["outcome"] != "killed"]
    dead = sorted(directly_dead | named_dead)
    return {
        "ok": all(w["outcome"] == "succeeded" for w in workers),
        "workers": workers,
        "dead_members": dead,
        # bounded = every survivor exited by itself (nonzero, not our
        # harness kill at the deadline) — the detection worked
        "survivors_failed_bounded": (
            all(not w["timed_out"] and w["returncode"] != 0 for w in survivors)
            if dead else None
        ),
        "max_survivor_elapsed_s": max(
            (w["elapsed_s"] for w in survivors), default=0.0
        ),
    }


def main() -> int:
    from tpu_operator.obs import flight
    from tpu_operator.validator import status as vstatus
    from tpu_operator.workloads import compile_cache

    compile_cache.enable()
    coordinator = os.environ.get("COORDINATOR_ADDRESS", "")
    num_processes = int(os.environ.get("NUM_PROCESSES", "1"))
    process_id = int(
        os.environ.get("PROCESS_ID", os.environ.get("TPU_WORKER_ID", "0") or "0")
    )
    steps = int(os.environ.get("BURN_IN_STEPS", "3"))
    scope = os.environ.get("RESULTS_SCOPE", "")
    if num_processes > 1 and not coordinator:
        print(json.dumps({"ok": False, "error": "COORDINATOR_ADDRESS required"}))
        return 1
    # flight record beside the results drop-box (the pod mounts that dir);
    # per-check samples flow from the instrumented collectives benchmarks
    recorder = flight.recorder_for(vstatus.flight_record_path(scope))
    with flight.activate(recorder):
        try:
            result = run_worker(coordinator, num_processes, process_id, steps=steps)
        except Exception as e:  # noqa: BLE001 — the exit code IS the validation verdict
            evidence = {
                "ok": False,
                "process_id": process_id,
                # the phase names WHERE the failure hit (e.g. a collective
                # erroring because its peer died) — the post-mortem evidence
                "phase": _LAST_PHASE,
                "error": str(e),
            }
            print(json.dumps(evidence), flush=True)
            vstatus.write_workload_results({"distributed": evidence}, scope=scope)
            return 1
        flight.record_result("distributed", result)
    print(json.dumps(result), flush=True)
    # node-local drop-box for the validator → node-status exporter → alerts;
    # RESULTS_SCOPE (injected for the cross-slice pods) keeps DCN figures
    # from overwriting the slice's ICI figures
    vstatus.write_workload_results({"distributed": result}, scope=scope)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
