"""HBM streaming-bandwidth benchmark (STREAM-scale analogue for TPU).

The third leg of the perf triad the validator can measure on a chip: MXU
(matmul_bench MFU), ICI (collectives allreduce busbw), and HBM — the usual
bottleneck for memory-bound ops.  The reference never measured GPU memory
bandwidth either (its CUDA workload is a correctness vectorAdd,
validator/main.go:1189-1302); reporting achieved-vs-spec HBM GB/s is a
capability on top of parity.

Methodology (matches collectives.allreduce_benchmark r03): ``iters``
elementwise scales of one large buffer run inside a single compiled
fori_loop with one scalar readback (per-dispatch timing is untrustworthy on
tunneled PJRT backends), the dispatch+readback floor measured by a null
program is subtracted, best-of-N reported.  Each iteration reads and writes
the full buffer: bytes = 2 * size * iters.  The buffer (default 256 MB)
exceeds any on-chip VMEM so the traffic streams HBM.  The multiplier is
1.0000001, not 1.0 — an identity loop body would fold away.

``iters`` defaults to 1024 so the chain (~1.3s on v5e) dwarfs the
~100 ms tunneled-dispatch floor: at r03's 256 iters the floor was a third
of the raw time, and floor-sample noise once inflated a run to a bogus
0.96 of peak.  MEASURED CEILING (r04 sweep on a real v5e, documented in
docs/PARITY.md): elementwise streaming sustains ~650-660 GB/s — ~0.80 of
the 819 GB/s spec — flat across 256 MB-1 GB working sets, f32/bf16, 1-D/
2-D layouts, scale and triad patterns (a naive pallas copy kernel is
2x worse: no cross-iteration DMA overlap).  Treat ~0.80 as this access
pattern's healthy baseline, not degradation; the spec number is pin
bandwidth no elementwise stream reaches.
"""

from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp

from tpu_operator.obs import flight
from tpu_operator.obs import profile as obs_profile
from tpu_operator.workloads import timing


def hbm_benchmark(
    size_mb: float = 256.0,
    iters: int = 1024,  # chain ~1.3s: floor-subtraction noise under 1% (see module doc)
    best_of: int = 3,
) -> dict:
    """Stream a buffer through HBM; report achieved GB/s and the fraction
    of the detected generation's published bandwidth."""
    from tpu_operator.workloads import matmul_bench

    n = max(1024, int(size_mb * 1024 * 1024 / 4))  # f32 = 4 bytes
    x = jnp.ones((n,), jnp.float32)

    @jax.jit
    def null(x):
        # same dispatch + scalar-readback shape as the timed program
        return x[0] + x[n // 2]

    @jax.jit
    def chain(x):
        y = jax.lax.fori_loop(0, iters, lambda i, s: s * 1.0000001, x)
        return y[0] + y[n // 2]

    float(null(x))
    compile_s = timing.timed(lambda: float(chain(x)))  # compile + warm
    flight.record("hbm", "compile", compile_s=compile_s)
    floor = min(
        timing.timed(lambda: float(null(x))) for _ in range(max(2, best_of))
    )
    bytes_per_rep = 2 * x.nbytes * iters
    raw = []
    for rep in range(best_of):
        raw.append(timing.timed(lambda: float(chain(x))))
        flight.record(
            "hbm", "step", step=rep, step_s=raw[-1],
            gbps=bytes_per_rep / raw[-1] / 1e9,
        )
        flight.record_step(
            "hbm", step_seq=rep, wall_s=raw[-1],
            phases={obs_profile.PHASE_COMPUTE: raw[-1]},
        )
    raw = sorted(raw)
    times, overhead_dominated = timing.subtract_floor(raw, floor)
    dt = times[0]
    dt_median = times[len(times) // 2]

    moved = 2 * x.nbytes * iters  # read + write per iteration
    gbps = moved / dt / 1e9
    generation = matmul_bench.detect_generation()
    peak = _peak_hbm_gbps(generation)
    return {
        "ok": True,
        "size_mb": x.nbytes / 1e6,
        "iters": iters,
        "best_of": best_of,
        "time_ms": dt * 1e3,
        "overhead_ms": floor * 1e3,
        "overhead_dominated": overhead_dominated,
        "gbps": gbps,
        "gbps_median": moved / dt_median / 1e9,
        "gbps_min": moved / times[-1] / 1e9,
        "generation": generation,
        "peak_hbm_gbps": peak,
        "fraction_of_peak": round(gbps / peak, 4) if peak else None,
        "backend": jax.default_backend(),
    }


def _peak_hbm_gbps(generation: str) -> float:
    from tpu_operator.k8s.nodeinfo import generation_info

    return generation_info(generation).hbm_gbps


def quick_benchmark() -> dict:
    """The validator's in-process perf probe: the full-size stream on TPU
    (the number must be comparable to bench.py's); a toy buffer on other
    backends so tests stay fast."""
    if jax.default_backend() == "tpu":
        return hbm_benchmark()
    return hbm_benchmark(size_mb=8.0, iters=4, best_of=2)


def apply_hbm_gate(result: dict, min_gbps: float) -> dict:
    """HBM_MIN_GBPS gate (shared rule: timing.apply_min_gate; no ICI
    requirement — the stream is chip-local by construction)."""
    return timing.apply_min_gate(
        result, metric="gbps", minimum=min_gbps,
        backends_env="HBM_GATE_BACKENDS", label="hbm",
    )


def main() -> int:
    from tpu_operator.workloads import compile_cache

    from tpu_operator import workloads

    workloads.honor_cpu_platform_request()
    compile_cache.enable()
    result = hbm_benchmark(
        size_mb=float(os.environ.get("HBM_SIZE_MB", "256")),
        iters=int(os.environ.get("HBM_ITERS", "1024")),
        best_of=int(os.environ.get("HBM_BEST_OF", "3")),
    )
    apply_hbm_gate(result, float(os.environ.get("HBM_MIN_GBPS", "0") or 0))
    flight.record_result("hbm", result)
    flight.close_active()
    print(json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
