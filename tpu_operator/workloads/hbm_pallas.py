"""Pallas DMA-pipeline HBM streaming cross-check.

A second, independent methodology for HBM bandwidth next to
``hbm_bench`` (XLA elementwise stream): a hand-rolled pallas kernel that
moves the buffer HBM→VMEM→HBM through a ``slots``-deep double-buffered
async-DMA pipeline (pallas_guide double-buffering pattern), bypassing the
VPU entirely.  Two reasons it exists:

1. **Ceiling evidence.** On a real v5e both methodologies — plus a direct
   HBM→HBM DMA variant — converge at ~660 GB/s (~0.81 of the 819 GB/s
   spec): elementwise 660, 2-slot DMA pipeline 658, 4-slot 664, direct
   HBM→HBM 540 (r04 sweep, docs/PARITY.md).  The agreement across access
   patterns is what justifies reading ``fraction_of_peak ≈ 0.8`` as the
   chip's streaming ceiling rather than a methodology artifact.
2. **Fault isolation.** The elementwise stream exercises DMA *and* the
   VPU pipeline; this kernel exercises DMA alone.  If the two figures
   diverge on a degraded node, the fault is in the compute pipeline, not
   the memory system (and vice versa) — evidence no single methodology
   can produce.

Timing follows the shared rule (timing.py): ``iters`` full passes inside
ONE compiled program, dispatch floor subtracted, best-of-N.  The r04 sweep
also demonstrated why the chain must dwarf the floor: at 256 iters a lucky
floor sample inflated this kernel to a bogus 803 GB/s; at 1024 iters it
reports a stable 658-664.

No reference analogue (the CUDA workload is a correctness vectorAdd,
validator/main.go:1189-1302); this is capability on top of parity.
"""

from __future__ import annotations

import functools
import json
import os
import sys

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_operator.workloads import timing

_COLS = 512  # (8, 128)-aligned lanes; chunk = chunk_rows x 512 f32


def _pipeline_kernel(iters, num_chunks, chunk_rows, slots,
                     in_ref, out_ref, scratch, in_sems, out_sems):
    """``iters`` passes of: read chunk HBM→VMEM, write it VMEM→HBM, with
    ``slots`` chunks in flight (reads run ahead while writes drain)."""

    def one_pass(_, carry):
        def rd(c, slot):
            return pltpu.make_async_copy(
                in_ref.at[pl.ds(c * chunk_rows, chunk_rows), :],
                scratch.at[slot],
                in_sems.at[slot],
            )

        def wr(c, slot):
            return pltpu.make_async_copy(
                scratch.at[slot],
                out_ref.at[pl.ds(c * chunk_rows, chunk_rows), :],
                out_sems.at[slot],
            )

        for k in range(slots):  # static warm-up: fill the pipeline
            rd(k, k).start()

        def body(c, carry):
            slot = jax.lax.rem(c, slots)
            rd(c, slot).wait()
            wr(c, slot).start()

            @pl.when(c + slots < num_chunks)
            def _():
                # the slot's write must drain before its buffer is reused
                wr(c, slot).wait()
                rd(c + slots, slot).start()

            @pl.when(c + slots >= num_chunks)
            def _():
                wr(c, slot).wait()

            return carry

        return jax.lax.fori_loop(0, num_chunks, body, carry)

    jax.lax.fori_loop(0, iters, one_pass, 0)


def dma_pipeline_copy(x: jax.Array, iters: int, chunk_rows: int, slots: int) -> jax.Array:
    """The jittable pallas program: copy ``x`` through the DMA pipeline
    ``iters`` times; returns the copy (bit-identical to ``x``)."""
    rows = x.shape[0]
    if rows % chunk_rows:
        # a remainder tail would never be copied — "bit-identical" above
        # would silently be a lie for the last rows
        raise ValueError(f"rows={rows} not divisible by chunk_rows={chunk_rows}")
    num_chunks = rows // chunk_rows
    if not 1 <= slots <= num_chunks:
        # the static warm-up DMAs the first `slots` chunks; more slots than
        # chunks would read past the end of the buffer
        raise ValueError(f"slots={slots} outside [1, {num_chunks}]")
    return pl.pallas_call(
        functools.partial(_pipeline_kernel, iters, num_chunks, chunk_rows, slots),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[
            pltpu.VMEM((slots, chunk_rows, x.shape[1]), x.dtype),
            pltpu.SemaphoreType.DMA((slots,)),
            pltpu.SemaphoreType.DMA((slots,)),
        ],
        interpret=jax.default_backend() != "tpu",
    )(x)


def dma_stream_benchmark(
    size_mb: float = 256.0,
    iters: int = 1024,  # chain ~1s on v5e: floor noise under 1% (module doc)
    chunk_mb: float = 4.0,
    slots: int = 4,
    best_of: int = 3,
) -> dict:
    """Stream a buffer through the DMA pipeline; report achieved GB/s and
    fraction of the generation's published bandwidth."""
    from tpu_operator.workloads import hbm_bench, matmul_bench

    chunk_rows = max(8, int(chunk_mb * 1024 * 1024 / 4 / _COLS))
    rows = max(chunk_rows, int(size_mb * 1024 * 1024 / 4 / _COLS))
    rows -= rows % chunk_rows
    slots = max(1, min(slots, rows // chunk_rows))
    x = jnp.ones((rows, _COLS), jnp.float32)

    jfn = jax.jit(functools.partial(
        dma_pipeline_copy, iters=iters, chunk_rows=chunk_rows, slots=slots
    ))

    @jax.jit
    def null(x):
        return x[0, 0] + x[rows // 2, 0]

    y = jfn(x)  # compile + warm
    y.block_until_ready()
    # full-buffer self-check: min==max==1.0 reads every element, so a
    # kernel regression that skips an interior chunk (leaving it
    # uninitialized) fails here — a trailing-element probe would not
    lo, hi = jax.jit(lambda a: (jnp.min(a), jnp.max(a)))(y)
    if float(lo) != 1.0 or float(hi) != 1.0:
        return {"ok": False, "error": "DMA pipeline copy produced wrong data",
                "backend": jax.default_backend()}
    float(null(x))
    floor = min(timing.timed(lambda: float(null(x))) for _ in range(max(2, best_of)))
    raw = sorted(
        timing.timed(lambda: jfn(x).block_until_ready()) for _ in range(best_of)
    )
    times, overhead_dominated = timing.subtract_floor(raw, floor)
    moved = 2 * x.nbytes * iters  # HBM read + HBM write per pass
    generation = matmul_bench.detect_generation()
    peak = hbm_bench._peak_hbm_gbps(generation)
    gbps = moved / times[0] / 1e9
    return {
        "ok": True,
        "methodology": "pallas-dma-pipeline",
        "size_mb": x.nbytes / 1e6,
        "iters": iters,
        "chunk_mb": chunk_rows * _COLS * 4 / 1e6,
        "slots": slots,
        "best_of": best_of,
        "time_ms": times[0] * 1e3,
        "overhead_ms": floor * 1e3,
        "overhead_dominated": overhead_dominated,
        "gbps": gbps,
        "gbps_median": moved / times[len(times) // 2] / 1e9,
        "generation": generation,
        "peak_hbm_gbps": peak,
        "fraction_of_peak": round(gbps / peak, 4) if peak else None,
        "backend": jax.default_backend(),
    }


def quick_benchmark() -> dict:
    """The validator's post-ready cross-check probe: full size on TPU
    (comparable to hbm_bench's figure); toy interpreted shapes elsewhere."""
    if jax.default_backend() == "tpu":
        return dma_stream_benchmark()
    return dma_stream_benchmark(size_mb=0.5, iters=2, chunk_mb=0.125, slots=2, best_of=2)


def main() -> int:
    from tpu_operator import workloads
    from tpu_operator.workloads import compile_cache

    workloads.honor_cpu_platform_request()
    compile_cache.enable()
    result = dma_stream_benchmark(
        size_mb=float(os.environ.get("HBM_SIZE_MB", "256")),
        iters=int(os.environ.get("HBM_ITERS", "1024")),
        chunk_mb=float(os.environ.get("HBM_DMA_CHUNK_MB", "4")),
        slots=int(os.environ.get("HBM_DMA_SLOTS", "4")),
        best_of=int(os.environ.get("HBM_BEST_OF", "3")),
    )
    print(json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
