"""Long-context prefill: K/V-streamed flash attention on one chip.

Ring attention (ring_attention.py) lets sequences outgrow a HOST by
keeping one block per chip — but the per-chip block itself must not
materialize its scores either, or the chip's HBM caps the block at
~sqrt(HBM).  This module closes that half: full causal attention over a
long local sequence with K/V streamed through the fused flash kernel
one block at a time (the same ``flash_block_update`` + online-softmax
state the ring uses per hop, here driven by an in-chip ``fori_loop``) —
peak memory is O(T·D) activations plus one [blk_q, block_k] score tile
in VMEM, never the [T, T] score matrix.  Composed with the ring this
means sequence length is bounded by activation storage alone, at any
slice size.

Causal block skipping: a K/V block strictly above the diagonal for every
query in the shard contributes nothing — ``lax.cond`` skips its matmuls
entirely, the standard flash triangular saving (~2x at long T).

Exactness evidence at scales where the full reference is impossible
(32k² f32 scores per head = 4 GB): spot-check q-tiles — one tile's
reference needs only a [tile, T] score slab, so the first and last tiles
(the diagonal edge and the full-context row) are verified exactly.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_operator.obs import profile as obs_profile
from tpu_operator.workloads import timing
from tpu_operator.workloads.ring_attention import (
    NEG_INF,
    merge_heads as _merge,
    online_softmax_block_update,
)

import functools

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _flash_full_kernel(causal, scale, blk_q, blk_k,
                       qoff_ref, koff_ref, q_ref, k_ref, v_ref,
                       o_out, lse_out, m_sc, l_sc, acc_sc):
    kk = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kk == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    q_base = qoff_ref[0] + pl.program_id(1) * blk_q
    k_base = koff_ref[0] + kk * blk_k
    # causal: a block whose FIRST key is past the tile's last query is
    # fully masked — predicate the whole update off (the flash
    # triangular saving, ~2x at long T)
    live = (k_base <= q_base + blk_q - 1) if causal else True

    @pl.when(live)
    def _update():
        m_new, l_new, acc_new = online_softmax_block_update(
            causal, scale, q_ref[0], k_ref[0], v_ref[0],
            m_sc[...], l_sc[...], acc_sc[...], q_base, k_base,
        )
        m_sc[...] = m_new
        l_sc[...] = l_new
        acc_sc[...] = acc_new

    @pl.when(kk == nk - 1)
    def _finish():
        l = l_sc[...]
        denom = jnp.where(l > 0, l, 1.0)
        o_out[0] = (acc_sc[...] / denom).astype(o_out.dtype)
        lse_out[0] = m_sc[...] + jnp.log(denom)


def _block_div(t: int, want: int) -> int:
    """Largest divisor of ``t`` that is <= ``want`` and a multiple of 8
    (Mosaic tiling); ``t`` itself only when no aligned divisor exists
    (tiny test shapes)."""
    if t <= want:
        return t
    for blk in range(min(t, want - want % 8), 7, -8):
        if t % blk == 0:
            return blk
    return t


def flash_attention_local(q, k, v, causal: bool = True, block_k: int = 1024,
                          block_q: int = 1024, q_off: int = 0, k_off: int = 0):
    """Causal flash attention in the merged layout ``[BH, T, D]``: ONE
    pallas program, grid (bh, q-tile, k-block) with k innermost — the
    online-softmax state lives in VMEM scratch across a q-tile's k sweep
    and each output tile is written once (the streamed-state fori_loop
    this replaces re-read the full O(T·D) state per k block and measured
    13 attn-TFLOPs at 32k; see prefill_benchmark).  Returns
    (out [BH, Tq, D], lse [BH, Tq]).  ``q_off``/``k_off``: global
    sequence offsets (a ring shard can stream its held block too).
    Defaults from an r04 32k sweep on v5e: (block_q=1024, block_k=1024)
    measured ~92 causal attn-TFLOPs (run-to-run tunnel variance up to
    ~15%), ahead of 512-row q blocks (~62) and 256-col k blocks (~33);
    2048-row q blocks exceed VMEM."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    # non-divisible sequences: largest aligned divisor at most the
    # requested block (NOT one giant block — a [blk_q, tk] score tile at
    # the long sequences this module exists for would blow VMEM)
    block_k = _block_div(tk, block_k)
    block_q = _block_div(tq, block_q)
    scale = 1.0 / np.sqrt(d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, tq // block_q, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kk, *_: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk, *_: (i, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk, *_: (i, kk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kk, *_: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kk, *_: (i, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    out, lse3 = pl.pallas_call(
        functools.partial(_flash_full_kernel, causal, scale, block_q, block_k),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        interpret=jax.default_backend() != "tpu",
    )(
        jnp.asarray([q_off], jnp.int32),
        jnp.asarray([k_off], jnp.int32),
        q, k, v,
    )
    return out, lse3[..., 0]


def _tile_reference(q_tile, k, v, tile_off, causal):
    """Exact attention for one merged-layout q tile against the full
    sequence — [tile, T] scores only, feasible at any T."""
    s = jnp.einsum("btd,bkd->btk", q_tile.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(q_tile.shape[-1])
    if causal:
        t = k.shape[1]
        q_pos = tile_off + jnp.arange(q_tile.shape[1])
        s = jnp.where(q_pos[None, :, None] >= jnp.arange(t)[None, None, :],
                      s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("btk,bkd->btd", w.astype(v.dtype), v)


def _amortized_time(
    chain_call, null_call, iters: int, best_of: int, name: str = ""
):
    """The one timing harness both probes run: compile/settle both
    programs, measure the dispatch+readback floor with the null program,
    wall-clock ``best_of`` chained runs, floor-subtract per iteration
    (workloads/timing.py rules).  Returns (per_iter_times_sorted,
    overhead_dominated, last_chain_value) — the full sorted sample list so
    callers publish best AND spread (error-bar rule), the value so callers
    can fold finiteness into ok.  ``name`` tags each repetition (and the
    compile) in the flight record."""
    from tpu_operator.obs import flight

    t_compile = time.perf_counter()
    last = chain_call()  # compile + settle
    if name:
        flight.record(name, "compile", compile_s=time.perf_counter() - t_compile)
    null_call()
    overhead = min(timing.timed(null_call) for _ in range(3))
    raw = []
    for rep in range(best_of):
        t0 = time.perf_counter()
        last = chain_call()
        raw.append(time.perf_counter() - t0)
        if name:
            flight.record(name, "step", step=rep, step_s=raw[-1])
            flight.record_step(
                name, step_seq=rep, wall_s=raw[-1],
                phases={obs_profile.PHASE_COMPUTE: raw[-1]},
            )
    times, dominated = timing.subtract_floor(raw, overhead, per=iters)
    return times, dominated, last


def prefill_benchmark(
    seq: int = 32768,
    heads: int = 8,
    head_dim: int = 128,
    batch: int = 1,
    block_k: int = 1024,
    tile: int = 128,
    causal: bool = True,
    best_of: int = 3,
    iters: int = 8,
) -> dict:
    """Long-context prefill attention on one chip: throughput + spot-check
    exactness.  Returns the check-result dict (run_validation shape).

    Timing: ``iters`` prefills chained inside ONE compiled fori_loop
    (each iteration's output becomes the next query — data-dependent, no
    dead-code elimination), so the ~100ms tunneled dispatch floor
    amortizes instead of dominating a single ~25ms prefill."""
    bh = batch * heads

    def init(key):
        ks = jax.random.split(key, 3)
        shape = (bh, seq, head_dim)
        return tuple(jax.random.normal(kk, shape, jnp.bfloat16) for kk in ks)

    q, k, v = jax.jit(init)(jax.random.PRNGKey(11))

    @jax.jit
    def single(q, k, v):
        return flash_attention_local(q, k, v, causal, block_k)

    @jax.jit
    def chain(q, k, v):
        def body(_, q):
            out, _ = flash_attention_local(q, k, v, causal, block_k)
            return out
        return jnp.sum(jax.lax.fori_loop(0, iters, body, q)[0, 0].astype(jnp.float32))

    out, _ = single(q, k, v)  # compile + settle (also the exactness subject)
    out.block_until_ready()

    @jax.jit
    def null(q):
        return jnp.sum(q[0, 0].astype(jnp.float32))

    times, overhead_dominated, _ = _amortized_time(
        lambda: float(chain(q, k, v)), lambda: float(null(q)), iters, best_of,
        name="longctx",
    )
    dt = times[0]

    # exactness: first tile (diagonal edge) and last tile (attends to the
    # whole context) against the per-tile reference
    @jax.jit
    def spot_errors(q, k, v, out):
        errs = []
        for off in (0, seq - tile):
            qt = jax.lax.dynamic_slice(q, (0, off, 0), (bh, tile, head_dim))
            ot = jax.lax.dynamic_slice(out, (0, off, 0), (bh, tile, head_dim))
            ref = _tile_reference(qt, k, v, off, causal)
            errs.append(jnp.max(jnp.abs(
                ot.astype(jnp.float32) - ref.astype(jnp.float32)
            )))
        return jnp.stack(errs)

    errs = [float(e) for e in spot_errors(q, k, v, out)]
    max_err = max(errs)
    # attention FLOPs (causal: half the score/PV work is masked out)
    flops = 4.0 * bh * seq * seq * head_dim * (0.5 if causal else 1.0)
    return {
        "ok": bool(np.isfinite(max_err) and max_err < 2e-2),
        "seq": seq,
        "heads": heads,
        "head_dim": head_dim,
        "block_k": block_k,
        "causal": causal,
        "time_s": dt,
        "overhead_dominated": overhead_dominated,
        "tokens_per_sec": batch * seq / dt,
        "attn_tflops": flops / dt / 1e12,
        "attn_tflops_spread": {
            "min": flops / times[-1] / 1e12,
            "median": flops / times[len(times) // 2] / 1e12,
            "max": flops / dt / 1e12,
        },
        "max_error": max_err,
        "spot_tiles": [0, seq - tile],
        "backend": jax.default_backend(),
    }


def quick_check() -> dict:
    """The validator's probe: 32k tokens on TPU; tiny interpret shapes
    elsewhere."""
    if jax.default_backend() == "tpu":
        return prefill_benchmark()
    return prefill_benchmark(seq=256, heads=2, head_dim=8, block_k=64,
                             tile=32, best_of=2)


def decode_quick_check() -> dict:
    """The decode probe: 32k cache on TPU; tiny interpret shapes
    elsewhere (a 1024-iteration chain would crawl in the interpreter)."""
    if jax.default_backend() == "tpu":
        return decode_benchmark()
    return decode_benchmark(seq=128, heads=2, head_dim=8, block_k=32,
                            iters=2, best_of=2)


def main() -> int:
    import json

    from tpu_operator import workloads
    from tpu_operator.workloads import compile_cache

    workloads.honor_cpu_platform_request()
    compile_cache.enable()
    result = quick_check()
    from tpu_operator.obs import flight

    flight.record_result("longctx", result)
    flight.close_active()
    print(json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


def decode_benchmark(
    seq: int = 32768,
    heads: int = 8,
    head_dim: int = 128,
    batch: int = 1,
    block_k: int = 1024,
    iters: int = 1024,
    best_of: int = 3,
) -> dict:
    """Decode-attention throughput: one query position against a long KV
    cache — the HBM-bound half of serving (each decoded token must read
    the whole cache; the ceiling is cache bytes / HBM bandwidth, not
    FLOPs).  Reuses the full-flash kernel with an 8-row query tail (the
    Mosaic row-tile minimum; row -1 is the decode position), chained
    data-dependently inside one fori_loop so the dispatch floor
    amortizes.  Reports per-token decode latency and achieved cache-read
    bandwidth vs the chip's HBM spec.  r04 on v5e: 202us/token at 32k
    cache, 664 GB/s — the chip's measured streaming ceiling (~0.81 of
    spec, hbm_bench's own figure), i.e. decode attention is exactly
    cache-bound as it should be; 256 iters under-amortized the dispatch
    floor and read a misleading 222 GB/s."""
    from tpu_operator.k8s.nodeinfo import generation_info
    from tpu_operator.workloads import matmul_bench

    bh = batch * heads
    tail = 8

    def init(key):
        ks = jax.random.split(key, 3)
        q = jax.random.normal(ks[0], (bh, tail, head_dim), jnp.bfloat16)
        k = jax.random.normal(ks[1], (bh, seq, head_dim), jnp.bfloat16)
        v = jax.random.normal(ks[2], (bh, seq, head_dim), jnp.bfloat16)
        return q, k, v

    q, k, v = jax.jit(init)(jax.random.PRNGKey(13))

    @jax.jit
    def chain(q, k, v):
        def body(_, q):
            out, _ = flash_attention_local(
                q, k, v, causal=True, block_k=block_k, q_off=seq - tail
            )
            return out  # next decode's query depends on this one's output
        return jnp.sum(jax.lax.fori_loop(0, iters, body, q)[:, -1].astype(jnp.float32))

    @jax.jit
    def null(q):
        return jnp.sum(q[:, -1].astype(jnp.float32))

    times, overhead_dominated, last = _amortized_time(
        lambda: float(chain(q, k, v)), lambda: float(null(q)), iters, best_of,
        name="decode",
    )
    dt = times[0]

    cache_bytes = 2.0 * bh * seq * head_dim * 2  # K and V, bf16
    generation = matmul_bench.detect_generation()
    peak = generation_info(generation).hbm_gbps
    result = {
        # the chained decodes' readback is the correctness signal at real
        # shapes (exactness is pinned at interpret shapes): NaN/garbage
        # from a miscompiled extreme-aspect kernel must fail the check,
        # not just time well
        "ok": bool(np.isfinite(dt) and dt > 0 and np.isfinite(last)),
        "seq": seq,
        "heads": heads,
        "head_dim": head_dim,
        "batch": batch,
        "decode_us": dt * 1e6,
        "decode_us_median": times[len(times) // 2] * 1e6,
        "decode_us_max": times[-1] * 1e6,
        "decodes_per_sec": batch / dt,
        "cache_gbps": cache_bytes / dt / 1e9,
        "cache_gbps_min": cache_bytes / times[-1] / 1e9,
        "overhead_dominated": overhead_dominated,
        "backend": jax.default_backend(),
        "generation": generation,
    }
    if peak > 0:
        result["cache_fraction_of_peak"] = round(result["cache_gbps"] / peak, 4)
    return result


if __name__ == "__main__":
    import sys

    sys.exit(main())
