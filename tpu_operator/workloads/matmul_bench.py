"""Single-chip compute benchmark: bf16 matmul sweep → TFLOPs → MFU.

The perf half of the validation story the reference never had: its CUDA
workload (validator/main.go:1189-1302) proves a vectorAdd runs, but never
measures the device.  Here the jax validation component and bench.py measure
what the chip actually delivers — a dense bf16 matmul sweep sized to fill
the MXU, best-of-N timed, reported as achieved TFLOPs and as MFU against
the detected generation's published peak (k8s/nodeinfo.py ACCELERATORS):
v4 275, v5e 197, v5p 459, v6e 918 bf16 TFLOPs per chip.

TPU-first details:
- bf16 inputs, f32 accumulation (``preferred_element_type``) — the MXU's
  native contraction mode; anything else underreports the hardware.
- square sizes 1k-8k: large enough that XLA tiles the full systolic array
  and the measurement is compute-bound, not launch-bound.
- timing excludes warmup (first call compiles), uses ``block_until_ready``,
  and reports the best of N repetitions — dispatch jitter and SMT noise
  make single-shot numbers meaningless (the r02 allreduce regression was
  exactly this).

Runs identically (slowly, in f32-emulated bf16) on the CPU backend for
tests; ``main()`` prints one JSON line for subprocess capture by bench.py.
"""

from __future__ import annotations

import json
import math
import sys
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from tpu_operator.obs import flight
from tpu_operator.obs import profile as obs_profile
from tpu_operator.workloads import timing


DEFAULT_SIZES = (1024, 2048, 4096, 8192)

# PJRT device_kind → catalogue generation (the in-cluster path reads the GKE
# accelerator label instead; this is for bare processes like bench.py)
_KIND_PATTERNS = (
    ("v6e", "v6e"),
    ("v6 lite", "v6e"),
    ("v5p", "v5p"),
    ("v5 lite", "v5e"),
    ("v5e", "v5e"),
    ("v4", "v4"),
)


def detect_generation(device: Optional[jax.Device] = None) -> str:
    """Chip generation from the PJRT device kind ('TPU v5 lite' → v5e)."""
    device = device if device is not None else jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for pattern, generation in _KIND_PATTERNS:
        if pattern in kind:
            return generation
    return "unknown"


def peak_bf16_tflops(generation: str) -> float:
    """Published per-chip dense bf16 peak for the generation (0 = unknown)."""
    from tpu_operator.k8s.nodeinfo import generation_info

    return generation_info(generation).peak_bf16_tflops


# FLOPs per timed repetition: sized so every matmul size amortizes the
# host→device dispatch + scalar-readback round trip (which on a tunneled
# PJRT backend is ~100 ms and would otherwise swamp sub-8k matmuls).
# 1e14 FLOPs ≈ 0.5 s of chip time at ~200 TFLOPs.
_FLOP_BUDGET = 1.0e14
_MAX_CHAIN_ITERS = 50_000
NORM_PERIOD = 8  # matmuls per RMS re-normalization (see _chain_fn)


def _chain_fn(size: int, iters: int):
    """One compiled program running ``iters`` dependent matmuls.

    Individual dispatch timing is untrustworthy (async dispatch; tunneled
    backends ack block_until_ready early) and fetching the product uploads
    the whole buffer — so the benchmark runs the chain on-device via
    fori_loop and transfers ONE scalar.  The loop-carried product makes
    every matmul data-dependent on the previous (no dead-code elimination),
    and the sum output depends on every element (no slice propagation
    shrinking the contraction)."""

    # A fixed 1/sqrt(n) scale diverges over long chains (the product aligns
    # with b's top singular direction, σ≈2·sqrt(n) for gaussian b, so it
    # gains ~2x per step) — but RMS-normalizing every step serializes a VPU
    # reduction against each matmul and costs ~8% MXU utilization.  So:
    # a fixed 1/(2·sqrt(n)) scale inside an unrolled burst keeps the value
    # bounded for NORM_PERIOD steps, and one RMS pass per burst re-centres
    # it exactly; the reduction amortizes to noise.
    inv = 1.0 / (2.0 * size**0.5)

    @jax.jit
    def chain(c: jax.Array, b: jax.Array) -> jax.Array:
        def burst(_, c):
            def step(_, c2):
                # f32 accumulation: the MXU's native contraction mode
                o = jax.lax.dot_general(
                    c2, b, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                return (o * inv).astype(jnp.bfloat16)

            c = jax.lax.fori_loop(0, NORM_PERIOD, step, c)
            o = c.astype(jnp.float32)
            o = o / (jnp.sqrt(jnp.mean(jnp.square(o))) + 1e-30)
            return o.astype(jnp.bfloat16)

        c = jax.lax.fori_loop(0, iters // NORM_PERIOD, burst, c)
        return jnp.sum(c.astype(jnp.float32))

    return chain


def chain_iters(size: int, flop_budget: float = _FLOP_BUDGET) -> int:
    raw = min(_MAX_CHAIN_ITERS, int(flop_budget / (2.0 * size**3)))
    # round up to a whole number of normalization bursts
    return max(1, -(-raw // NORM_PERIOD)) * NORM_PERIOD


def _time_matmul(
    size: int, iters: Optional[int], warmup: int, best_of: int, flop_budget: float
) -> dict:
    iters = iters if iters else chain_iters(size, flop_budget)
    iters = max(1, -(-iters // NORM_PERIOD)) * NORM_PERIOD  # whole bursts
    key = jax.random.PRNGKey(size)
    ka, kb = jax.random.split(key)
    a = jax.random.normal(ka, (size, size), jnp.bfloat16)
    b = jax.random.normal(kb, (size, size), jnp.bfloat16)
    chain = _chain_fn(size, iters)

    # dispatch + scalar-readback round trip, measured with a null program:
    # on a tunneled PJRT backend this is tens of ms and would deflate the
    # computed rate; subtracting the floor makes TFLOPs reflect chip time
    @jax.jit
    def null(c):
        return jnp.sum(c.astype(jnp.float32))

    float(null(a))  # compile
    overhead = min(timing.timed(lambda: float(null(a))) for _ in range(3))

    compile_s = timing.timed(lambda: float(chain(a, b)))  # compile + settle
    flight.record("matmul", "compile", compile_s=compile_s, size=size)
    for _ in range(max(1, warmup) - 1):
        float(chain(a, b))  # scalar transfer forces sync
    raw = []
    checksum = 0.0
    flops_per_matmul = 2.0 * size * size * size
    for rep in range(best_of):
        t0 = time.perf_counter()
        checksum = float(chain(a, b))
        raw.append(time.perf_counter() - t0)
        flight.record(
            "matmul", "step", step=rep, size=size, step_s=raw[-1],
            # amortized, floor-unsubtracted live rate (shared-rule verdict
            # applied below; the series is a monitoring signal)
            tflops=flops_per_matmul * iters / raw[-1] / 1e12,
        )
        flight.record_step(
            "matmul", step_seq=rep, wall_s=raw[-1],
            phases={obs_profile.PHASE_COMPUTE: raw[-1]},
        )
    # shared rule (workloads/timing.py): floor-subtract per-matmul time;
    # when the floor rivals the compute, fall back to the unsubtracted,
    # deflated rate and flag it so MFU gates skip rather than trust either
    # direction
    times, overhead_dominated = timing.subtract_floor(raw, overhead, per=iters)
    best = times[0]
    median = times[len(times) // 2]
    flops = 2.0 * size * size * size
    return {
        "size": size,
        "iters": iters,
        "overhead_ms": overhead * 1e3,
        "overhead_dominated": overhead_dominated,
        "time_ms": best * 1e3,
        "time_ms_median": median * 1e3,
        "tflops": flops / best / 1e12,
        "tflops_median": flops / median / 1e12,
        # full best-of-N spread: a published figure without its error bar
        # reads run variance as regression (the r04 0.952->0.905 scare was
        # transport noise — the measured run-to-run envelope is ~0.89-0.95)
        "tflops_min": flops / times[-1] / 1e12,
        "finite": math.isfinite(checksum),
    }


def matmul_benchmark(
    sizes: Sequence[int] = DEFAULT_SIZES,
    iters: Optional[int] = None,
    warmup: int = 1,
    best_of: int = 3,
    flop_budget: float = _FLOP_BUDGET,
) -> dict:
    """Sweep the sizes; report per-size TFLOPs plus best-overall and MFU."""
    generation = detect_generation()
    peak = peak_bf16_tflops(generation)
    results = [
        _time_matmul(int(s), iters, warmup, best_of, flop_budget) for s in sizes
    ]
    best = max(results, key=lambda r: r["tflops"])
    mfu = best["tflops"] / peak if peak else None
    return {
        "ok": all(r["tflops"] > 0 and r["finite"] for r in results),
        "backend": jax.default_backend(),
        "device_kind": getattr(jax.devices()[0], "device_kind", ""),
        "generation": generation,
        "peak_bf16_tflops": peak or None,
        "results": results,
        "best_size": best["size"],
        "overhead_dominated": best["overhead_dominated"],
        "tflops": best["tflops"],
        # the best size's best-of-N spread, published alongside the
        # headline so a reader can tell noise from regression
        "tflops_spread": {
            "min": best["tflops_min"],
            "median": best["tflops_median"],
            "max": best["tflops"],
        },
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_median": round(best["tflops_median"] / peak, 4) if peak else None,
        "mfu_min": round(best["tflops_min"] / peak, 4) if peak else None,
    }


def apply_mfu_gate(result: dict, min_mfu: float) -> dict:
    """The MFU gate policy, shared by the CLI and run_validation: enforce
    only when a peak is known (mfu not None) and the best measurement was
    not overhead-dominated.  Mutates ``result`` with the outcome."""
    enforced = (
        min_mfu > 0
        and result.get("mfu") is not None
        and not result.get("overhead_dominated")
    )
    result["min_mfu"] = min_mfu
    result["gated"] = enforced
    if enforced and result["mfu"] < min_mfu:
        result["ok"] = False
        result["error"] = f"mfu {result['mfu']:.3f} < required {min_mfu}"
    return result


def quick_benchmark() -> dict:
    """Trimmed sweep for the validator's perf probes: one MXU-filling size
    at the FULL flop budget on TPU (~0.5 s of chip time — r03 used a tenth,
    whose ~130 ms chain sat inside the ~85 ms tunneled-dispatch floor and
    came out overhead-dominated at 0.37 "MFU" on a chip that measures 0.95
    with the same methodology properly amortized); a toy size on other
    backends so tests stay fast.  The probe no longer rides the readiness
    critical path, so chip time is the right trade for a trustworthy
    number.  TWO sizes, not one: the exported figure is best-over-sizes,
    the same semantics as bench.py's sweep — a single fixed size ran up to
    12% under the sweep's best in r04 runs, which against the bench-path
    number reads as degradation that isn't there."""
    if jax.default_backend() == "tpu":
        return matmul_benchmark(sizes=(2048, 4096), flop_budget=_FLOP_BUDGET)
    return matmul_benchmark(sizes=(256,), iters=NORM_PERIOD, best_of=2)


def main() -> int:
    import os

    from tpu_operator.workloads import compile_cache

    from tpu_operator import workloads

    workloads.honor_cpu_platform_request()
    compile_cache.enable()  # skips recompiles only; execution timing unaffected

    sizes = tuple(
        int(s)
        for s in os.environ.get("MATMUL_SIZES", "").split(",")
        if s.strip()
    ) or DEFAULT_SIZES
    iters_env = os.environ.get("MATMUL_ITERS", "")
    result = matmul_benchmark(
        sizes=sizes,
        iters=int(iters_env) if iters_env else None,
        best_of=int(os.environ.get("MATMUL_BEST_OF", "3")),
    )
    apply_mfu_gate(result, float(os.environ.get("MATMUL_MIN_MFU", "0")))
    flight.record_result("matmul", result)
    flight.close_active()
    print(json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
