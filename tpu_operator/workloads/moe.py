"""Expert parallelism: Switch-style MoE layer with all-to-all dispatch.

Completes the parallelism census (SURVEY §2.6) next to dp (burn-in),
tp/sp (transformer step), and the two sequence-parallel attention
strategies: experts shard one-group-per-chip over an ``ep`` mesh axis,
tokens are routed top-1 (Switch Transformer, Fedus et al.), and TWO
all-to-alls move each token to its expert's chip and back.  This is also
a hardware diagnostic the other workloads don't give: the dispatch
all-to-all is the only collective whose traffic crosses EVERY chip pair,
so a single bad ICI link that a neighbour-ring ppermute happens to skip
still shows up here.

Static shapes throughout (XLA tracing): routing materialises a
``[tokens, E, C]`` one-hot dispatch tensor (capacity C per expert per
shard); tokens over capacity are dropped — their combine weight is zero,
exactly the reference recipe — so no data-dependent shapes ever reach
the compiler.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _capacity(tokens_per_shard: int, num_experts: int, capacity_factor: float) -> int:
    return max(1, int(np.ceil(tokens_per_shard * capacity_factor / num_experts)))


def route_top1(logits: jax.Array, capacity: int):
    """Top-1 routing with per-expert capacity.

    ``logits`` [N, E] → (dispatch [N, E, C] one-hot, combine [N, E, C]
    prob-weighted, aux) — the Switch data path.  Position within an
    expert's buffer is the token's rank among same-expert tokens (cumsum
    order); ranks ≥ C are dropped (all-zero rows in both tensors)."""
    n, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                      # [N]
    prob = jnp.max(probs, axis=-1)                           # [N]
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)    # [N, E]
    # rank of each token within its expert = exclusive cumsum of the
    # one-hot down the token axis
    rank = (jnp.cumsum(onehot, axis=0) - onehot) * onehot    # [N, E]
    rank = jnp.sum(rank, axis=-1).astype(jnp.int32)          # [N]
    kept = rank < capacity
    dispatch = (
        onehot[:, :, None]
        * jax.nn.one_hot(rank, capacity, dtype=jnp.float32)[:, None, :]
        * kept[:, None, None]
    )                                                        # [N, E, C]
    combine = dispatch * prob[:, None, None]
    # load-balancing auxiliary loss (mean prob × mean assignment per
    # expert, scaled by E — the Switch aux), plus drop accounting
    density = jnp.mean(onehot, axis=0)
    density_prob = jnp.mean(probs, axis=0)
    aux = {
        "aux_loss": jnp.sum(density * density_prob) * e,
        "dropped_fraction": 1.0 - jnp.mean(kept.astype(jnp.float32)),
    }
    return dispatch, combine, aux


def moe_params(
    mesh: Mesh, d_model: int = 64, d_hidden: int = 128,
    experts_per_shard: int = 1, seed: int = 0,
):
    """Router (replicated) + expert FFN weights sharded over ``ep``:
    w1/w2 lead with the global expert axis, split one group per chip.

    Constructed BY jit with output shardings — correct in multi-controller
    mode too (a host-side device_put of the full array can only target
    addressable devices)."""
    ep = mesh.shape["ep"]
    e = ep * experts_per_shard
    scale = 1.0 / np.sqrt(d_model)

    def init(key):
        kr, k1, k2 = jax.random.split(key, 3)
        return {
            "wr": jax.random.normal(kr, (d_model, e), jnp.float32) * scale,
            "w1": jax.random.normal(k1, (e, d_model, d_hidden), jnp.float32) * scale,
            "w2": jax.random.normal(k2, (e, d_hidden, d_model), jnp.float32) * scale,
        }

    out_shardings = {
        "wr": NamedSharding(mesh, P(None, None)),
        "w1": NamedSharding(mesh, P("ep", None, None)),
        "w2": NamedSharding(mesh, P("ep", None, None)),
    }
    return jax.jit(init, out_shardings=out_shardings)(jax.random.PRNGKey(seed))


def moe_layer_sharded(
    xs, wr, w1, w2, axis_name: str, capacity_factor: float = 2.0
):
    """The per-shard MoE program (call under shard_map: ``xs`` [n_loc, D]
    token-sharded over ``axis_name``, ``w1``/``w2`` [E_loc, ...]
    expert-sharded over it, ``wr`` replicated).

    Data path: route → dispatch einsum → all-to-all (tokens travel to
    their expert's chip) → expert FFN → all-to-all back → combine."""
    p = jax.lax.psum(1, axis_name)
    n_loc, d = xs.shape
    e_loc = w1.shape[0]
    e = e_loc * p
    c = _capacity(n_loc, e, capacity_factor)

    dispatch, combine, aux = route_top1(xs @ wr, c)          # [n, E, C]
    # per-shard routing stats → cluster means (replicated outputs)
    aux = {k: jax.lax.pmean(v, axis_name) for k, v in aux.items()}
    # per-shard expert buffers, then the first all-to-all: split the
    # global-expert axis p ways, tile my shard axis in — each chip ends
    # holding [p, E_loc, C, D]: every shard's tokens for MY experts
    buf = jnp.einsum("nec,nd->ecd", dispatch, xs)            # [E, C, D]
    buf = buf.reshape(p, e_loc, c, d)
    recv = jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                              tiled=False)                   # [p, E_loc, C, D]
    # expert FFN over this chip's expert group (tokens from all shards)
    h = jnp.maximum(jnp.einsum("secd,edh->sech", recv, w1), 0)
    out = jnp.einsum("sech,ehd->secd", h, w2)                # [p, E_loc, C, D]
    # second all-to-all: results travel home, combine un-permutes
    back = jax.lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                              tiled=False)                   # [p, E_loc, C, D]
    back = back.reshape(e, c, d)
    return jnp.einsum("nec,ecd->nd", combine, back), aux


def moe_layer(
    x: jax.Array, params: dict, mesh: Mesh, capacity_factor: float = 2.0
) -> tuple[jax.Array, dict]:
    """Token-sharded MoE layer over mesh axis "ep"; x [N, D] sharded
    P("ep", None)."""
    fn = functools.partial(
        moe_layer_sharded, axis_name="ep", capacity_factor=capacity_factor
    )
    shard = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P("ep", None), P(None, None),
                  P("ep", None, None), P("ep", None, None)),
        out_specs=(P("ep", None), P()),
    )
    out, aux = shard(x, params["wr"], params["w1"], params["w2"])
    return out, aux


def dense_reference(x, wr, w1, w2, n_shards: int, capacity_factor: float):
    """Single-device truth: every expert on every token, then per-token
    selection — with the SAME per-shard capacity accounting the
    distributed path applies (rank is computed within each shard's local
    token block)."""
    n, d = x.shape
    e = w1.shape[0]
    n_loc = n // n_shards
    c = _capacity(n_loc, e, capacity_factor)

    def per_shard(xs):
        dispatch, combine, _ = route_top1(xs @ wr, c)
        buf = jnp.einsum("nec,nd->ecd", dispatch, xs)
        h = jnp.maximum(jnp.einsum("ecd,edh->ech", buf, w1), 0)
        out = jnp.einsum("ech,ehd->ecd", h, w2)
        return jnp.einsum("nec,ecd->nd", combine, out)

    # vmap over the shard axis, NOT a Python loop: the distributed
    # validation calls this with n_shards = the global chip count, and an
    # unrolled loop would grow the traced program linearly with slice size
    return jax.vmap(per_shard)(x.reshape(n_shards, n_loc, d)).reshape(n, d)


def acceptance(
    tokens_per_shard: int = 64,
    d_model: int = 32,
    d_hidden: int = 64,
    experts_per_shard: int = 1,
    capacity_factor: float = 2.0,
    devices: Optional[list] = None,
    tol: float = 1e-4,
) -> dict:
    """Distributed MoE vs the single-device dense reference on identical
    inputs/params.  Returns the check-result dict (run_validation
    shape)."""
    devices = devices if devices is not None else jax.devices()
    p = len(devices)
    mesh = Mesh(np.array(devices), ("ep",))
    n = tokens_per_shard * p

    # arrays constructed BY jit with output shardings — correct in
    # multi-controller mode too (a host-side device_put of the full array
    # can only target addressable devices; this path also serves the
    # multi-host distributed validation program).  Tokens and ROUTER
    # weights are quantized to a coarse grid: router logits become exact
    # f32 sums of exact products (magnitudes far below 2^24), so the
    # distributed path and the reference compute bit-identical logits
    # despite differently-structured matmuls — an argmax near-tie can
    # never route a token differently in the two programs (which would
    # O(1)-differ the output and fail a healthy node)
    params = moe_params(mesh, d_model, d_hidden, experts_per_shard)
    # router quantized to the grid (replicated eager op — multi-controller
    # safe: every process computes its addressable shards identically)
    wr = jnp.round(params["wr"] * 128) / 128
    w1, w2 = params["w1"], params["w2"]

    def init(key):
        return jnp.round(jax.random.normal(key, (n, d_model), jnp.float32) * 8) / 8

    x = jax.jit(
        init, out_shardings=NamedSharding(mesh, P("ep", None))
    )(jax.random.PRNGKey(7))

    @jax.jit
    def program(x, wr, w1, w2):
        out, aux = moe_layer(x, {"wr": wr, "w1": w1, "w2": w2}, mesh,
                             capacity_factor)
        ref = dense_reference(x, wr, w1, w2, p, capacity_factor)
        err = jnp.max(jnp.abs(out - ref))
        return err, aux

    t0 = time.perf_counter()
    err, aux = program(x, wr, w1, w2)
    err = float(err)
    dt = time.perf_counter() - t0
    from tpu_operator.obs import flight

    flight.record("moe", "run", step_s=dt, tokens=n, max_error=err)
    return {
        "ok": bool(np.isfinite(err) and err < tol),
        "devices": p,
        "experts": p * experts_per_shard,
        "tokens": n,
        "capacity_factor": capacity_factor,
        "dropped_fraction": float(aux["dropped_fraction"]),
        "aux_loss": float(aux["aux_loss"]),
        "strategy": "ep-all-to-all-top1",
        "max_error": err,
        "time_s": dt,
        "backend": jax.default_backend(),
    }


def quick_check() -> dict:
    """The validator's probe: EP acceptance over every local chip — the
    all-pairs all-to-all is the point (full bisection coverage)."""
    if jax.default_backend() == "tpu":
        return acceptance(tokens_per_shard=1024, d_model=256, d_hidden=1024,
                          experts_per_shard=2)
    return acceptance()


def main() -> int:
    import json
    import sys

    from tpu_operator import workloads
    from tpu_operator.workloads import compile_cache

    workloads.honor_cpu_platform_request()
    compile_cache.enable()
    result = quick_check()
    from tpu_operator.obs import flight

    flight.record_result("moe", result)
    flight.close_active()
    print(json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
