"""Concurrent partition isolation acceptance — the MIG capability made real.

The reference ships MIG so tenants can share one device safely
(`assets/state-mig-manager/`); the TPU analogue partitions a host's chips
into disjoint ICI sub-slices (slices.py → slice manager →
deviceplugin/sliceconfig.py per-shape resources).  Partitioning EXACTLY is
proven elsewhere (test_slices.py); what this module proves is the point of
the exercise: two disjoint partitions of one host can run INDEPENDENT
workloads AT THE SAME TIME without perturbing each other.

``concurrent_acceptance`` spawns one REAL workload process per partition
unit — each with the masked device set the device plugin's Allocate would
inject (``TPU_VISIBLE_CHIPS`` + ``TPU_CHIPS_PER_HOST_BOUNDS``, the env
contract of plugin.py::Allocate) and its own burn-in seed — held at a
filesystem start barrier until every unit is present, so simultaneous
execution is a construction, not a race.  Each unit's loss trajectory is
then compared EXACTLY against that unit's solo reference run: a partition
whose numerics change when its neighbour is busy has a leaky isolation
boundary (shared scheduler state, cross-partition collective, wrong chip
masking).  With ``simulate_cpu`` (the default, and what this repo's
tests/dryrun exercise) each process models its unit as
``xla_force_host_platform_device_count=<unit size>`` virtual CPU devices;
``simulate_cpu=False`` exists for a real partitioned host, where the
masked env itself drives chip-level isolation through libtpu — untested
here (single-chip bench environment; see PARITY "Verification
environment limits").
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Optional


def unit_env(
    chip_indices: list[int],
    shape: str,
    *,
    seed: int,
    barrier_dir: Optional[str] = None,
    barrier_count: int = 0,
    simulate_cpu: bool = True,
) -> dict:
    """The env a workload process needs to run masked to one partition
    unit — mirrors the device plugin's Allocate response
    (plugin.py::Allocate: TPU_VISIBLE_CHIPS + TPU_CHIPS_PER_HOST_BOUNDS)
    plus the burn-in seed and optional start barrier.

    ``simulate_cpu`` (the default, and the only mode this environment can
    exercise) models the unit as ``len(chip_indices)`` virtual CPU
    devices; pass False on a real partitioned host to let the masked env
    itself drive chip-level isolation through libtpu."""
    from tpu_operator import workloads
    from tpu_operator.deviceplugin.plugin import shape_bounds

    env = {
        **os.environ,
        # unit processes re-import the package via -m; see subprocess_pythonpath
        "PYTHONPATH": workloads.subprocess_pythonpath(),
        "TPU_VISIBLE_CHIPS": ",".join(str(i) for i in sorted(chip_indices)),
        "TPU_CHIPS_PER_HOST_BOUNDS": shape_bounds(shape),
        "WORKLOAD_CHECKS": "burn-in",
        "BURN_IN_SEED": str(seed),
        "TPU_COMPILE_CACHE": "0",
        # the unit's true size — a leaked node-level EXPECTED_DEVICES (the
        # validator sets it for the WHOLE host) would fail the masked
        # subprocess's device-count gate before burn-in ever ran
        "EXPECTED_DEVICES": str(len(chip_indices)),
    }
    # likewise: a leaked RESULTS_SCOPE would redirect this unit's drop-box
    env.pop("RESULTS_SCOPE", None)
    if simulate_cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={len(chip_indices)}"
        )
    if barrier_dir:
        env["WORKLOAD_START_BARRIER"] = barrier_dir
        env["WORKLOAD_BARRIER_COUNT"] = str(barrier_count)
    return env


def _parse_burn_in(stdout: str) -> Optional[dict]:
    """The burn-in check record from a run_validation stdout stream — ONE
    parser for the solo and concurrent paths, so both runs always read
    the trajectory the same way."""
    burn = None
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("check") == "burn-in":
                burn = rec
    return burn


def _run_unit(env: dict, timeout: float) -> dict:
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_operator.workloads.run_validation"],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        return {
            "returncode": None,
            "timed_out": True,
            "burn_in": None,
            "stdout_tail": (e.stdout or b"").decode(errors="replace")[-1500:]
            if isinstance(e.stdout, bytes) else (e.stdout or "")[-1500:],
            "stderr_tail": (e.stderr or b"").decode(errors="replace")[-1500:]
            if isinstance(e.stderr, bytes) else (e.stderr or "")[-1500:],
        }
    return {
        "returncode": proc.returncode,
        "burn_in": _parse_burn_in(proc.stdout),
        "stdout_tail": proc.stdout[-1500:],
        "stderr_tail": proc.stderr[-1500:],
    }


def concurrent_acceptance(
    units: dict[str, list[int]],
    shape: str,
    steps: int = 3,
    timeout: float = 240,
    simulate_cpu: bool = True,
) -> dict:
    """Run every partition unit's burn-in SIMULTANEOUSLY (start-barrier
    synchronized) and compare each trajectory exactly against that unit's
    solo reference run.

    ``units``: unit name → local chip indices (disjoint — raises if not;
    sliceconfig.host_units output after path→index mapping, or a layout's
    partitions directly).  Returns ``ok`` plus per-unit evidence."""
    names = sorted(units)
    flat: list[int] = [c for name in names for c in units[name]]
    if len(set(flat)) != len(flat):
        raise ValueError(f"partition units overlap: {units}")

    # solo references first: each unit alone, nothing else running
    solo: dict[str, list[float]] = {}
    for i, name in enumerate(names):
        env = unit_env(units[name], shape, seed=i + 1, simulate_cpu=simulate_cpu)
        env["BURN_IN_STEPS"] = str(steps)
        r = _run_unit(env, timeout)
        if r["returncode"] != 0 or not (r["burn_in"] or {}).get("ok"):
            return {"ok": False, "stage": "solo", "unit": name, **r}
        solo[name] = r["burn_in"]["losses"]

    # the concurrent run: all units at once, held at the barrier until
    # every one is present
    with tempfile.TemporaryDirectory(prefix="tpu-partition-barrier-") as bd:
        procs = {}
        t0 = time.monotonic()
        for i, name in enumerate(names):
            env = unit_env(
                units[name], shape, seed=i + 1,
                barrier_dir=bd, barrier_count=len(names),
                simulate_cpu=simulate_cpu,
            )
            env["BURN_IN_STEPS"] = str(steps)
            procs[name] = subprocess.Popen(
                [sys.executable, "-m", "tpu_operator.workloads.run_validation"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        concurrent: dict[str, dict] = {}
        try:
            for name in names:
                try:
                    out, err = procs[name].communicate(
                        timeout=max(1.0, timeout - (time.monotonic() - t0))
                    )
                except subprocess.TimeoutExpired:
                    # a hung unit is evidence, not a traceback: kill it and
                    # record the shape like every other failure path
                    procs[name].kill()
                    out, err = procs[name].communicate()
                    concurrent[name] = {
                        "returncode": procs[name].returncode,
                        "timed_out": True,
                        "burn_in": None,
                        "stderr_tail": (err or "")[-1500:],
                    }
                    continue
                concurrent[name] = {
                    "returncode": procs[name].returncode,
                    "burn_in": _parse_burn_in(out),
                    "stderr_tail": (err or "")[-1500:],
                }
        finally:
            for proc in procs.values():
                if proc.poll() is None:
                    proc.kill()
                    proc.communicate()

    unit_results = {}
    ok = True
    for name in names:
        c = concurrent[name]
        burn = c["burn_in"] or {}
        losses = burn.get("losses")
        matches = losses == solo[name]
        unit_ok = c["returncode"] == 0 and bool(burn.get("ok")) and matches
        ok = ok and unit_ok
        unit_results[name] = {
            "ok": unit_ok,
            "chips": units[name],
            "losses": losses,
            "solo_losses": solo[name],
            "matches_solo": matches,
            "devices": burn.get("devices"),
        }
    # independence cross-check: disjoint partitions run DIFFERENT seeds, so
    # identical trajectories would mean one unit's computation leaked into
    # the other (or the masking collapsed both onto the same chips)
    trajectories = [tuple(u["losses"] or ()) for u in unit_results.values()]
    independent = len(set(trajectories)) == len(trajectories)
    return {
        "ok": ok and independent,
        "units": unit_results,
        "independent_trajectories": independent,
        "concurrent": True,
    }
