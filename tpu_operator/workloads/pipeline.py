"""Pipeline parallelism: GPipe-style microbatch streaming over a pp axis.

The last member of the parallelism census (SURVEY §2.6): dp (burn-in),
tp/sp (transformer step), ep (moe), cp/sp-attention (ring + ulysses),
and now pp.  Each chip owns ONE stage's weights; microbatches stream
through the pipe with one ``ppermute`` per tick carrying activations to
the next stage — M + p − 1 ticks fill and drain the pipe, and the bubble
fraction (p−1)/(M+p−1) shrinks as microbatches grow, the classic GPipe
trade.

SPMD formulation (no per-stage programs, XLA-friendly): every chip runs
the identical ``lax.scan``; stage identity comes from ``axis_index``.
Stage 0 feeds microbatch ``t`` at tick ``t``; interior stages consume
whatever the previous tick's ``ppermute`` delivered; the last stage
lands finished microbatches in its output buffer.  Control flow is all
static — ``jnp.where`` on the stage index, clamped ``dynamic_slice`` for
the feed — so the whole pipe is one compiled program, differentiable
end-to-end (the scan's AD replays ticks in reverse, ppermute transposes
to the inverted permutation: backprop streams the pipe backwards, which
is exactly pipeline-parallel training's backward pass).
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stage_fn(x, w1, w2):
    """One pipeline stage: residual relu MLP (bf16 matmuls, f32 carry)."""
    h = jnp.maximum(x.astype(jnp.bfloat16) @ w1, 0)
    return x + (h @ w2).astype(jnp.float32)


def pipeline_sharded(x, w1, w2, axis_name: str):
    """The per-shard pipe (call under shard_map: ``x`` [M, mb, D]
    replicated microbatches, ``w1``/``w2`` [1, ...] this stage's weights,
    stage = my index along ``axis_name``).

    Returns the pipe's output [M, mb, D] (replicated via a final psum —
    only the last stage's buffer is nonzero) — ticks M + p − 1 times."""
    from tpu_operator.workloads.collectives import _vary

    p = jax.lax.psum(1, axis_name)
    s = jax.lax.axis_index(axis_name)
    m, mb, d = x.shape
    w1, w2 = w1[0], w2[0]
    ticks = m + p - 1
    fwd = [(i, i + 1) for i in range(p - 1)]  # chain, not ring: no wraparound

    def feed(t):
        # stage 0's input at tick t: microbatch t (clamped — the pipe
        # drains on garbage that never reaches a valid output slot)
        mbi = jnp.clip(t, 0, m - 1)
        return jax.lax.dynamic_slice(x, (mbi, 0, 0), (1, mb, d))[0]

    x0 = jnp.where(s == 0, feed(jnp.int32(0)), jnp.zeros((mb, d), x.dtype))
    out0 = _vary(jnp.zeros_like(x), (axis_name,))

    def tick(carry, t):
        x_cur, out = carry
        y = stage_fn(x_cur, w1, w2)
        # the last stage lands microbatch j = t - (p-1) when it's real
        j = t - (p - 1)
        upd = jax.lax.dynamic_update_slice(out, y[None], (jnp.maximum(j, 0), 0, 0))
        out = jnp.where((s == p - 1) & (j >= 0), upd, out)
        # activations advance one stage; stage 0 pulls the next microbatch
        recv = jax.lax.ppermute(y, axis_name, fwd)
        x_next = jnp.where(s == 0, feed(t + 1), recv)
        return (x_next, out), None

    (_, out), _ = jax.lax.scan(tick, (x0, out0), jnp.arange(ticks, dtype=jnp.int32))
    # replicate the result: every stage but the last contributed zeros
    return jax.lax.psum(out, axis_name)


def pipeline_apply(x: jax.Array, w1: jax.Array, w2: jax.Array, mesh: Mesh) -> jax.Array:
    """Run x [M, mb, D] through the p-stage pipe; w1 [p, D, H] / w2
    [p, H, D] stage-sharded over mesh axis "pp"."""
    fn = functools.partial(pipeline_sharded, axis_name="pp")
    shard = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, None, None), P("pp", None, None), P("pp", None, None)),
        out_specs=P(None, None, None),
    )
    return shard(x, w1, w2)


def pipeline_params(mesh: Mesh, d_model: int = 64, d_hidden: int = 128, seed: int = 0):
    p = mesh.shape["pp"]
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    scale = 1.0 / np.sqrt(d_model)
    w1 = jax.device_put(
        jax.random.normal(k1, (p, d_model, d_hidden), jnp.bfloat16) * scale,
        NamedSharding(mesh, P("pp", None, None)),
    )
    w2 = jax.device_put(
        jax.random.normal(k2, (p, d_hidden, d_model), jnp.bfloat16) * scale,
        NamedSharding(mesh, P("pp", None, None)),
    )
    return w1, w2


def acceptance(
    microbatches: int = 8,
    microbatch: int = 4,
    d_model: int = 32,
    d_hidden: int = 64,
    devices: Optional[list] = None,
    tol: float = 1e-3,
) -> dict:
    """The pipe vs sequentially applying every stage on one device —
    identical weights, identical math, M + p − 1 ticks of streaming in
    between.  Returns the check-result dict (run_validation shape)."""
    devices = devices if devices is not None else jax.devices()
    p = len(devices)
    mesh = Mesh(np.array(devices), ("pp",))
    w1, w2 = pipeline_params(mesh, d_model, d_hidden)
    x = jax.random.normal(
        jax.random.PRNGKey(5), (microbatches, microbatch, d_model), jnp.float32
    )

    @jax.jit
    def program(x, w1, w2):
        out = pipeline_apply(x, w1, w2, mesh)

        def ref_stage(h, ws):
            return stage_fn(h, ws[0], ws[1]), None

        ref, _ = jax.lax.scan(ref_stage, x, (w1, w2))
        return jnp.max(jnp.abs(out - ref))

    t0 = time.perf_counter()
    err = float(program(x, w1, w2))
    dt = time.perf_counter() - t0
    from tpu_operator.obs import flight

    flight.record("pipeline", "run", step_s=dt, max_error=err)
    return {
        "ok": bool(np.isfinite(err) and err < tol),
        "devices": p,
        "stages": p,
        "microbatches": microbatches,
        "ticks": microbatches + p - 1,
        "bubble_fraction": round((p - 1) / (microbatches + p - 1), 4),
        "strategy": "pp-gpipe-microbatch",
        "max_error": err,
        "time_s": dt,
        "backend": jax.default_backend(),
    }


def quick_check() -> dict:
    """The validator's probe: the pipe exercises the neighbour-chain hops
    (the ring diagnostic's pattern) under streamed compute."""
    if jax.default_backend() == "tpu":
        return acceptance(microbatches=16, microbatch=64, d_model=512,
                          d_hidden=2048)
    return acceptance()


def main() -> int:
    import json
    import sys

    from tpu_operator import workloads
    from tpu_operator.workloads import compile_cache

    workloads.honor_cpu_platform_request()
    compile_cache.enable()
    result = quick_check()
    from tpu_operator.obs import flight

    flight.record_result("pipeline", result)
    flight.close_active()
    print(json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
