"""Ring attention: sequence-parallel exact attention over the chip ring.

The long-context acceptance workload (SURVEY §5.7: the reference has no
sequence-parallel concept; BASELINE's north star demands the TPU build
treat long-context as first-class).  The sequence axis is sharded over the
mesh ring: every chip holds one block of Q/K/V, computes attention of its
Q block against the K/V block it currently holds, then rotates K/V one hop
around the ring with ``lax.ppermute`` — after ``p`` hops every Q block has
attended to the full sequence while peak memory stayed at one block per
chip.  Numerics are exact (flash-style online softmax: running max +
denominator accumulated across hops), verified against single-device
attention on the gathered sequence; the interconnect pattern is the same
per-link ring the ``ring`` diagnostic measures (collectives.ring_benchmark).

Causal masking works from global positions: each shard knows its own
sequence offset and, at hop ``s``, the offset of the K/V block it holds
(source = (my_index - s) mod p) — no gather, no host control flow.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30  # large-negative instead of -inf: exp() of a fully-masked
# row must give 0/denom-guard, never nan from (-inf) - (-inf)


def reference_attention(q, k, v, causal: bool) -> jax.Array:
    """Single-device exact attention [B, T, H, D] — the truth the ring
    result must match."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    w = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v)


def _block_scores(q, k, scale):
    return jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale


def merge_heads(x):
    """[B, T, H, D] -> [B*H, T, D] — the pallas kernels' layout."""
    b, t, h, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, t, d)


def split_heads(x, b: int, h: int):
    """[B*H, T, D] -> [B, T, H, D] (merge_heads' inverse)."""
    _, t, d = x.shape
    return jnp.transpose(x.reshape(b, h, t, d), (0, 2, 1, 3))


def _hop_scores(q32, k, scale, causal, q_pos, src, block):
    """Scores of my Q block against the K block produced by shard ``src``,
    causal-masked from global positions — the one definition both the
    forward and the remat backward must agree on."""
    scores = _block_scores(q32, k.astype(jnp.float32), scale)  # [B,H,Tq,Tk]
    if causal:
        k_pos = src * block + jnp.arange(block)
        mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    return scores


# ---------------------------------------------------------------------------
# The hop's hot op as a fused pallas kernel: one K/V block folded into the
# online-softmax state entirely in VMEM — scores, mask, running max/denom
# correction and the PV matmul in a single Mosaic program (the unfused jnp
# path materializes the [B,H,Tq,Tk] score tensor in HBM twice per hop).
# The jnp math in ring_attention_sharded is the kernel's reference; the
# interpret-mode test pins them equal.


def online_softmax_block_update(causal, scale, q, k, v, m, l, acc,
                                q_base, k_base):
    """The per-block flash update BOTH pallas kernels run (the ring hop
    kernel below and longctx's full-attention kernel): fold one K/V
    block's scores into the (m, l, acc) online-softmax state.  Pure
    function of loaded VMEM values; numerically delicate — one home.

    Inputs stay in their storage dtype (bf16 from the training step):
    the MXU runs bf16 x bf16 -> f32 at full rate, while upcasting to
    f32 first would halve-or-worse the matmul throughput — this cost
    16% training MFU (0.56 -> 0.48) before the fix.  All softmax state
    math stays f32.  Shapes: q [Bq, D], k/v [Bk, D], m/l [Bq, 1],
    acc [Bq, D]."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale                                 # [Bq, Bk] on the MXU
    if causal:
        q_pos = q_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = k_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    blk_max = jnp.max(s, axis=-1, keepdims=True)  # [Bq, 1]
    m_new = jnp.maximum(m, blk_max)
    corr = jnp.exp(m - m_new)
    e = jnp.exp(s - m_new)
    e = jnp.where(s <= NEG_INF * 0.5, 0.0, e)  # fully-masked guard
    pv = jax.lax.dot_general(
        e.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l * corr + jnp.sum(e, axis=-1, keepdims=True), acc * corr + pv


def _flash_block_kernel(causal, scale, blk_q,
                        qoff_ref, koff_ref, q_ref, k_ref, v_ref,
                        m_in, l_in, o_in, m_out, l_out, o_out):
    # my q rows start at (shard offset) + (q-tile index) x blk_q
    q_base = qoff_ref[0] + pl.program_id(1) * blk_q
    m_new, l_new, o_new = online_softmax_block_update(
        causal, scale, q_ref[0], k_ref[0], v_ref[0],
        m_in[0], l_in[0], o_in[0], q_base, koff_ref[0],
    )
    m_out[0] = m_new
    l_out[0] = l_new
    o_out[0] = o_new


def _q_tile(tq: int, tk: int, budget_bytes: int = 4 << 20) -> int:
    """Largest divisor of ``tq`` (multiple of 8) whose [blk_q, Tk] f32
    score block fits the VMEM budget; ``tq`` itself when it already
    fits (small validation shapes keep the original single-tile grid)."""
    target = max(8, budget_bytes // (tk * 4))
    if tq <= target:
        return tq
    for blk in range(min(tq, target - target % 8), 7, -8):
        if tq % blk == 0:
            return blk
    return tq  # no aligned divisor — fall back to one tile


def _vary_all(vma, *arrays):
    """Mark every kernel operand varying over ``vma``'s axes: under a
    vma-checked shard_map the pallas machinery's internal index ops
    require matching varying-manual-axes across operands — a mp-varying
    scalar offset (axis_index) next to an unvarying array trips the
    checker (the alternative, check_vma=False on the whole step, is NOT
    an option: it changes collective transposes and inflated MLP grads
    by axis-size factors before this existed)."""
    if not vma:
        return arrays
    from tpu_operator.workloads.collectives import _vary

    out = []
    for a in arrays:
        have = getattr(jax.typeof(a), "vma", frozenset())
        need = tuple(ax for ax in vma if ax not in have)
        out.append(_vary(a, need) if need else a)
    return tuple(out)


def flash_block_update(q, k, v, q_off, k_off, m, l, o, causal: bool,
                       vma: Optional[frozenset] = None):
    """Fold one K/V block into (m, l, o) with the fused kernel.

    Shapes (per shard, already merged over batch×heads): q/k/v/o
    ``[BH, T, D]``, m/l ``[BH, T]``; ``q_off``/``k_off`` are the blocks'
    global sequence offsets (scalars, prefetched to SMEM for the causal
    iota).  Grid: (batch x head, q-tile) — Q (and its m/l/o state) is
    tiled so the [blk_q, Tk] score block stays inside VMEM at training
    shapes (a 2048x2048 f32 score block alone is 16 MB, the whole scoped
    budget); K/V are revisited whole per tile.  ``vma``: the mesh axes
    the outputs vary over when called under shard_map."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / np.sqrt(d)
    blk_q = _q_tile(tq, tk)
    # m/l travel as [BH, Tq, 1]: Mosaic requires the last two block dims
    # divisible by (8, 128) or equal to the array dims — a trailing unit
    # dim satisfies that where a flat [BH, Tq] block (1, Tq) cannot
    m3, l3 = m[..., None], l[..., None]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, tq // blk_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, blk_q, 1), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, blk_q, 1), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, blk_q, d), lambda i, j, *_: (i, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, 1), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, blk_q, 1), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, blk_q, d), lambda i, j, *_: (i, j, 0)),
        ],
    )
    m3, l3, o = pl.pallas_call(
        functools.partial(_flash_block_kernel, causal, scale, blk_q),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(m3.shape, jnp.float32, vma=vma),
            jax.ShapeDtypeStruct(l3.shape, jnp.float32, vma=vma),
            jax.ShapeDtypeStruct(o.shape, jnp.float32, vma=vma),
        ],
        input_output_aliases={5: 0, 6: 1, 7: 2},
        interpret=jax.default_backend() != "tpu",
    )(*_vary_all(
        vma,
        jnp.asarray([q_off], jnp.int32),
        jnp.asarray([k_off], jnp.int32),
        q, k, v, m3, l3, o,
    ))
    return m3[..., 0], l3[..., 0], o


def _jnp_ring_forward(q, k, v, axis_name: str, causal: bool, axes: tuple):
    """The jnp ring forward: returns (out, logsumexp) — the exact math the
    pallas kernel fuses and the residuals the remat backward needs."""
    p = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, block, h, d = q.shape
    scale = 1.0 / np.sqrt(d)
    q32 = q.astype(jnp.float32)

    from tpu_operator.workloads.collectives import _vary

    # running online-softmax state per query position (marked
    # device-varying: the loop carry must match the varying outputs)
    m = _vary(jnp.full((b, block, h), NEG_INF, jnp.float32), axes)
    l = _vary(jnp.zeros((b, block, h), jnp.float32), axes)
    o = _vary(jnp.zeros(q.shape, jnp.float32), axes)

    q_pos = idx * block + jnp.arange(block)  # global positions of MY queries

    def consume(s, m, l, o, k, v):
        """Fold the K/V block currently held (produced by shard
        (idx - s) mod p) into the online-softmax state."""
        src = jax.lax.rem(idx - s + p, p)
        scores = _hop_scores(q32, k, scale, causal, q_pos, src, block)
        blk_max = jnp.max(scores, axis=-1)  # [B,H,Tq]
        blk_max = jnp.moveaxis(blk_max, 1, -1)  # [B,Tq,H]
        m_new = jnp.maximum(m, blk_max)
        # fully-masked-so-far rows keep m at NEG_INF; the exp() below is 0
        corr = jnp.exp(m - m_new)
        e = jnp.exp(scores - jnp.moveaxis(m_new, -1, 1)[..., None])  # [B,H,Tq,Tk]
        # a fully-masked block keeps m_new at NEG_INF and exp(x - x) would
        # count every masked entry as 1 — mask them out explicitly.  (With
        # hop 0 being the diagonal block no query row starts fully masked,
        # but the guard keeps the math safe under any rotation order.)
        e = jnp.where(scores <= NEG_INF * 0.5, 0.0, e)
        l_new = l * corr + jnp.moveaxis(jnp.sum(e, -1), 1, -1)
        blk_o = jnp.einsum("bhqk,bkhd->bqhd", e, v.astype(jnp.float32))
        o_new = o * corr[:, :, :, None] + blk_o
        return m_new, l_new, o_new

    def hop(s, carry):
        m, l, o, k, v = carry
        m, l, o = consume(s, m, l, o, k, v)
        # rotate K/V one hop so the next iteration sees the next block
        perm = [(i, (i + 1) % p) for i in range(p)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return m, l, o, k, v

    # p-1 consume+rotate hops, then consume the final block WITHOUT the
    # rotation — the last ppermute's result would be discarded, a full
    # redundant block pair over every ICI link per call
    m, l, o, k, v = jax.lax.fori_loop(0, p - 1, hop, (m, l, o, k, v))
    m, l, o = consume(p - 1, m, l, o, k, v)
    # guard fully-masked rows (can only happen without causal=False edge
    # cases; kept for robustness): denom 0 → output 0
    denom = jnp.where(l > 0, l, 1.0)
    out = (o / denom[:, :, :, None]).astype(q.dtype)
    return out, _lse_of(m, l)


def ring_attention_sharded(
    q, k, v, axis_name: str, causal: bool, use_pallas: bool = False,
    vary_axes: Optional[tuple] = None,
) -> jax.Array:
    """The per-shard program (call under shard_map with the sequence axis
    sharded over ``axis_name``).  Shapes [B, T/p, H, D].

    ``use_pallas`` folds each block through the fused flash kernel
    (state in the merged [B×H, T, ...] layout); the jnp path
    (_jnp_ring_forward) is its bit-level reference.  ``vary_axes``: ALL
    manual axes the inputs vary over (defaults to just ``axis_name``) —
    under a multi-axis shard_map (e.g. the transformer step's (dp, mp)
    mesh, batch over dp) the loop state must carry every axis's variance
    or the fori_loop carry types mismatch."""
    axes = tuple(vary_axes) if vary_axes else (axis_name,)
    if not use_pallas:
        out, _ = _jnp_ring_forward(q, k, v, axis_name, causal, axes)
        return out
    out, _ = _pallas_ring_forward(q, k, v, axis_name, causal, axes)
    return out


def _pallas_ring_forward(q, k, v, axis_name: str, causal: bool, axes: tuple):
    """The fused-kernel ring forward: returns (out, logsumexp) in the same
    layouts as _jnp_ring_forward (out [B, T, H, D], lse [B, T, H]) — so
    the remat backward can consume either forward's residuals."""
    p = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, block, h, d = q.shape

    from tpu_operator.workloads.collectives import _vary

    merge = merge_heads
    m = _vary(jnp.full((b * h, block), NEG_INF, jnp.float32), axes)
    l = _vary(jnp.zeros((b * h, block), jnp.float32), axes)
    o = _vary(jnp.zeros((b * h, block, d), jnp.float32), axes)

    # merge ONCE and rotate in the kernel layout — ppermute is
    # layout-agnostic, and re-transposing K/V every hop would materialize
    # two full relayout copies per hop in HBM, undoing the traffic the
    # fused kernel saves
    qm, k, v = merge(q), merge(k), merge(v)

    def consume(s, m, l, o, k, v):
        src = jax.lax.rem(idx - s + p, p)
        return flash_block_update(
            qm, k, v,
            idx * block, src * block, m, l, o, causal,
            vma=frozenset(axes),
        )

    def hop(s, carry):
        m, l, o, k, v = carry
        m, l, o = consume(s, m, l, o, k, v)
        perm = [(i, (i + 1) % p) for i in range(p)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return m, l, o, k, v

    m, l, o, k, v = jax.lax.fori_loop(0, p - 1, hop, (m, l, o, k, v))
    m, l, o = consume(p - 1, m, l, o, k, v)
    denom = jnp.where(l > 0, l, 1.0)
    out = split_heads(o / denom[:, :, None], b, h)

    def split2(x):  # [B*H, T] -> [B, T, H] (jnp layout)
        return jnp.transpose(x.reshape(b, h, block), (0, 2, 1))

    return out.astype(q.dtype), _lse_of(split2(m), split2(l))


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
    causal: bool = True, use_pallas: bool = False,
) -> jax.Array:
    """Sequence-parallel attention over a 1-D mesh axis "x"; inputs/outputs
    sequence-sharded [B, T, H, D]."""
    fn = functools.partial(
        ring_attention_sharded, axis_name="x", causal=causal, use_pallas=use_pallas
    )
    shard = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "x"), P(None, "x"), P(None, "x")),
        out_specs=P(None, "x"),
        # the pallas path trips the vma checker's dynamic_slice rule (its
        # block machinery mixes varying operands with unvarying grid
        # indices); the jnp path keeps the strict checking
        check_vma=not use_pallas,
    )
    return shard(q, k, v)


def acceptance(
    batch: int = 1,
    seq_per_chip: int = 128,
    heads: int = 4,
    head_dim: int = 64,
    causal: bool = True,
    devices: Optional[list] = None,
    tol: float = 2e-2,
    use_pallas: bool = False,
) -> dict:
    """Run ring attention over every local chip and verify it matches the
    single-device reference bit-for-block (bf16 tolerance).  Returns the
    check-result dict (run_validation shape)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("x",))
    t = seq_per_chip * n
    sharding = NamedSharding(mesh, P(None, "x"))

    # arrays are constructed BY jit with output shardings — correct in
    # multi-controller mode too (a host-side device_put of the full array
    # can only target addressable devices; this path also serves the
    # multi-host distributed validation program)
    def init(key):
        kq, kk, kv = jax.random.split(key, 3)
        shape = (batch, t, heads, head_dim)
        return tuple(
            jax.random.normal(kk_, shape, jnp.bfloat16) for kk_ in (kq, kk, kv)
        )

    qs, ks, vs = jax.jit(init, out_shardings=(sharding,) * 3)(jax.random.PRNGKey(0))

    @jax.jit
    def program(qs, ks, vs):
        out = ring_attention(qs, ks, vs, mesh, causal=causal, use_pallas=use_pallas)
        ref = reference_attention(qs, ks, vs, causal)
        return jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32)))

    t0 = time.perf_counter()
    err = float(program(qs, ks, vs))
    dt = time.perf_counter() - t0
    from tpu_operator.obs import flight

    flight.record(
        "ring-attention", "run", step_s=dt, seq=t, max_error=err
    )
    return {
        "ok": bool(np.isfinite(err) and err < tol),
        "devices": n,
        "seq": t,
        "seq_per_chip": seq_per_chip,
        "heads": heads,
        "head_dim": head_dim,
        "causal": causal,
        "kernel": "pallas-flash" if use_pallas else "jnp",
        "max_error": err,
        "time_s": dt,
        "backend": jax.default_backend(),
    }


def quick_check() -> dict:
    """The validator's probe: real shapes + the fused pallas flash kernel
    on TPU; tiny jnp shapes elsewhere (the distributed CPU program must
    not crawl through the pallas interpreter)."""
    if jax.default_backend() == "tpu":
        return acceptance(seq_per_chip=512, head_dim=128, use_pallas=True)
    return acceptance(seq_per_chip=16, heads=2, head_dim=8)


def main() -> int:
    import json
    import sys

    from tpu_operator import workloads
    from tpu_operator.workloads import compile_cache

    workloads.honor_cpu_platform_request()
    compile_cache.enable()
    result = quick_check()
    from tpu_operator.obs import flight

    flight.record_result("ring-attention", result)
    flight.close_active()
    print(json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


# ---------------------------------------------------------------------------
# Memory-efficient training path (jax.custom_vjp).
#
# Plain AD through the forward's fori_loop saves every hop's residuals —
# O(p) block-pair intermediates per layer, which defeats ring attention's
# whole memory argument for long sequences.  The Ring Attention recipe
# (Liu et al.) instead RECOMPUTES each hop's scores in a second ring pass:
# the forward saves only (q, k, v, out, logsumexp), and the backward
# rotates K/V around the ring again with the FlashAttention-2 block
# backward at each hop.  dK/dV accumulators travel WITH their blocks —
# after the full revolution (p rotations this time; the accumulators must
# get home) every block's gradient lands on the shard that owns it.


def _lse_of(m, l):
    """logsumexp per query from the online-softmax state (jnp layout)."""
    return m + jnp.log(jnp.where(l > 0, l, 1.0))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_attention_remat(q, k, v, axis_name: str, causal: bool, axes: tuple,
                         use_pallas: bool = False):
    """ring_attention_sharded with an O(1)-residual backward; call under
    shard_map exactly like ring_attention_sharded.  ``use_pallas`` runs
    the FORWARD through the fused flash kernel (the jnp forward
    materializes the [B,H,Tq,Tk] score tensor in HBM twice per hop); the
    backward consumes only (q, k, v, out, lse) so either forward feeds
    the same second ring pass."""
    out, _ = (
        _pallas_ring_forward if use_pallas else _jnp_ring_forward
    )(q, k, v, axis_name, causal, axes)
    return out


def _remat_fwd(q, k, v, axis_name, causal, axes, use_pallas=False):
    out, lse = (
        _pallas_ring_forward if use_pallas else _jnp_ring_forward
    )(q, k, v, axis_name, causal, axes)
    return out, (q, k, v, out, lse)


def _remat_bwd(axis_name, causal, axes, use_pallas, res, dout):
    # residuals are layout-identical from either forward; use_pallas also
    # selects the fused FA2 block-backward kernel (defined below)
    if use_pallas:
        return _remat_bwd_pallas(axis_name, causal, axes, res, dout)
    from tpu_operator.workloads.collectives import _vary

    q, k, v, out, lse = res
    p = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, block, h, d = q.shape
    scale = 1.0 / np.sqrt(d)
    q32 = q.astype(jnp.float32)
    do32 = dout.astype(jnp.float32)
    # D_i = rowsum(dO * O): the softmax-jacobian correction term
    dsum = jnp.moveaxis(jnp.sum(do32 * out.astype(jnp.float32), -1), -1, 1)[..., None]
    lse_b = jnp.moveaxis(lse, -1, 1)[..., None]  # [B,H,Tq,1]
    q_pos = idx * block + jnp.arange(block)

    dq = _vary(jnp.zeros(q.shape, jnp.float32), axes)
    dk = _vary(jnp.zeros(k.shape, jnp.float32), axes)
    dv = _vary(jnp.zeros(v.shape, jnp.float32), axes)

    perm = [(i, (i + 1) % p) for i in range(p)]

    def consume(s, dq, dk, dv, k, v):
        src = jax.lax.rem(idx - s + p, p)
        k32, v32 = k.astype(jnp.float32), v.astype(jnp.float32)
        scores = _hop_scores(q32, k, scale, causal, q_pos, src, block)
        # exact probabilities from the SAVED logsumexp — no re-accumulation.
        # Masked entries: exp(NEG_INF - lse) = 0, EXCEPT a fully-masked row
        # whose lse collapsed to NEG_INF too — guard it like the forward
        prob = jnp.where(scores <= NEG_INF * 0.5, 0.0, jnp.exp(scores - lse_b))
        dv_new = dv + jnp.einsum("bhqk,bqhd->bkhd", prob, do32)
        dprob = jnp.einsum("bqhd,bkhd->bhqk", do32, v32)
        dscores = prob * (dprob - dsum)
        dq_new = dq + jnp.einsum("bhqk,bkhd->bqhd", dscores, k32) * scale
        dk_new = dk + jnp.einsum("bhqk,bqhd->bkhd", dscores, q32) * scale
        return dq_new, dk_new, dv_new

    def hop(s, carry):
        dq, dk, dv, k, v = carry
        dq, dk, dv = consume(s, dq, dk, dv, k, v)
        # dK/dV travel with their block: ALL p hops rotate, so after the
        # full revolution each accumulator is home on the shard that owns
        # its block's gradient
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        dk = jax.lax.ppermute(dk, axis_name, perm)
        dv = jax.lax.ppermute(dv, axis_name, perm)
        return dq, dk, dv, k, v

    # last hop peeled: the accumulators still need their homing rotation,
    # but rotating K/V once more would ship a redundant block pair over
    # every ICI link (same reasoning as the forward's peeled last hop)
    dq, dk, dv, k, v = jax.lax.fori_loop(0, p - 1, hop, (dq, dk, dv, k, v))
    dq, dk, dv = consume(p - 1, dq, dk, dv, k, v)
    dk = jax.lax.ppermute(dk, axis_name, perm)
    dv = jax.lax.ppermute(dv, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)




# ---------------------------------------------------------------------------
# The hop's backward as a fused kernel (FlashAttention-2 block backward):
# scores are recomputed from the saved logsumexp and dq/dk/dv
# contributions accumulate entirely in VMEM — the jnp backward
# materializes four [B,H,Tq,Tk] tensors (scores, prob, dprob, dscores)
# in HBM per hop, gigabytes each at training shapes.  Grid
# (batch x head, q-tile): dq tiles are visited once; the dk/dv blocks are
# revisited across a hop's q-tiles and accumulate in place on top of the
# travelling ring accumulators (aliased in/out).


def _flash_block_bwd_kernel(causal, scale, blk_q,
                            qoff_ref, koff_ref,
                            q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                            dq_in, dk_in, dv_in, dq_out, dk_out, dv_out):
    j = pl.program_id(1)
    q = q_ref[0]                                  # [blk_q, D] storage dtype
    k = k_ref[0]                                  # [Tk, D]
    v = v_ref[0]
    do = do_ref[0]                                # [blk_q, D]
    lse = lse_ref[0]                              # [blk_q, 1]
    dsum = dsum_ref[0]                            # [blk_q, 1]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale                                     # [blk_q, Tk]
    q_base = qoff_ref[0] + j * blk_q
    if causal:
        q_pos = q_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = koff_ref[0] + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    # exact probabilities from the SAVED logsumexp; fully-masked-row guard
    # mirrors the jnp backward (lse collapses to NEG_INF there too)
    prob = jnp.where(s <= NEG_INF * 0.5, 0.0, jnp.exp(s - lse))
    # bf16 operands into the MXU with f32 accumulation (the FA2 recipe;
    # the f32-input alternative halves matmul throughput — see the
    # forward kernel's note)
    pb = prob.astype(q.dtype)
    dv_c = jax.lax.dot_general(                   # P^T @ dO  [Tk, D]
        pb, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dprob = jax.lax.dot_general(                  # dO @ V^T  [blk_q, Tk]
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = (prob * (dprob - dsum)).astype(q.dtype)  # [blk_q, Tk]
    dq_c = jax.lax.dot_general(                   # dS @ K    [blk_q, D]
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    dk_c = jax.lax.dot_general(                   # dS^T @ Q  [Tk, D]
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    dq_out[0] = dq_in[0] + dq_c

    @pl.when(j == 0)
    def _first():
        # fold onto the travelling ring accumulators once per hop
        dk_out[0] = dk_in[0] + dk_c
        dv_out[0] = dv_in[0] + dv_c

    @pl.when(j != 0)
    def _rest():
        # revisited blocks: accumulate in place across the hop's q-tiles
        dk_out[0] = dk_out[0] + dk_c
        dv_out[0] = dv_out[0] + dv_c


def flash_block_backward(q, k, v, do, lse, dsum, dq, dk, dv,
                         q_off, k_off, causal: bool,
                         vma: Optional[frozenset] = None):
    """One hop's dq/dk/dv contributions via the fused backward kernel.

    Merged layout: q/do/dq ``[BH, Tq, D]``, k/v/dk/dv ``[BH, Tk, D]``,
    lse/dsum ``[BH, Tq]`` (the forward's saved residuals).  dq/dk/dv are
    accumulators: the returned arrays are input + this hop's
    contribution (aliased buffers, no extra HBM copies)."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / np.sqrt(d)
    # tighter budget than the forward: the backward keeps ~3 score-sized
    # f32 temporaries live at once (s, prob, dprob) plus their bf16
    # casts — a forward-sized q tile blew scoped VMEM by 50% at tk=2048
    blk_q = _q_tile(tq, tk, budget_bytes=1 << 20)
    lse3, dsum3 = lse[..., None], dsum[..., None]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, tq // blk_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, blk_q, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, blk_q, 1), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, blk_q, 1), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, blk_q, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j, *_: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j, *_: (i, 0, 0)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_flash_block_bwd_kernel, causal, scale, blk_q),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(dq.shape, jnp.float32, vma=vma),
            jax.ShapeDtypeStruct(dk.shape, jnp.float32, vma=vma),
            jax.ShapeDtypeStruct(dv.shape, jnp.float32, vma=vma),
        ],
        input_output_aliases={8: 0, 9: 1, 10: 2},
        interpret=jax.default_backend() != "tpu",
    )(*_vary_all(
        vma,
        jnp.asarray([q_off], jnp.int32),
        jnp.asarray([k_off], jnp.int32),
        q, k, v, do, lse3, dsum3, dq, dk, dv,
    ))


def _remat_bwd_pallas(axis_name, causal, axes, res, dout):
    """The remat backward with the fused FA2 block kernel per hop: merged
    layout throughout, dq/dk/dv accumulating in aliased HBM buffers, the
    same ring rotation/peeling as the jnp backward."""
    from tpu_operator.workloads.collectives import _vary

    q, k, v, out, lse = res
    p = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, block, h, d = q.shape

    def merge2(x):  # [B, T, H] -> [B*H, T]
        return jnp.transpose(x, (0, 2, 1)).reshape(b * h, block)

    qm, km, vm = merge_heads(q), merge_heads(k), merge_heads(v)
    dom = merge_heads(dout)
    # D_i = rowsum(dO * O): the softmax-jacobian correction term
    dsum = jnp.sum(dom.astype(jnp.float32) * merge_heads(out).astype(jnp.float32), -1)
    lsem = merge2(lse)

    vma = frozenset(axes)
    dq = _vary(jnp.zeros(qm.shape, jnp.float32), axes)
    dk = _vary(jnp.zeros(km.shape, jnp.float32), axes)
    dv = _vary(jnp.zeros(vm.shape, jnp.float32), axes)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def consume(s, dq, dk, dv, km, vm):
        src = jax.lax.rem(idx - s + p, p)
        return flash_block_backward(
            qm, km, vm, dom, lsem, dsum, dq, dk, dv,
            idx * block, src * block, causal, vma=vma,
        )

    def hop(s, carry):
        dq, dk, dv, km, vm = carry
        dq, dk, dv = consume(s, dq, dk, dv, km, vm)
        km = jax.lax.ppermute(km, axis_name, perm)
        vm = jax.lax.ppermute(vm, axis_name, perm)
        dk = jax.lax.ppermute(dk, axis_name, perm)
        dv = jax.lax.ppermute(dv, axis_name, perm)
        return dq, dk, dv, km, vm

    dq, dk, dv, km, vm = jax.lax.fori_loop(0, p - 1, hop, (dq, dk, dv, km, vm))
    dq, dk, dv = consume(p - 1, dq, dk, dv, km, vm)
    dk = jax.lax.ppermute(dk, axis_name, perm)
    dv = jax.lax.ppermute(dv, axis_name, perm)

    return (
        split_heads(dq, b, h).astype(q.dtype),
        split_heads(dk, b, h).astype(k.dtype),
        split_heads(dv, b, h).astype(v.dtype),
    )


ring_attention_remat.defvjp(_remat_fwd, _remat_bwd)


if __name__ == "__main__":
    import sys

    sys.exit(main())
